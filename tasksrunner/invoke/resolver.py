"""App-id name resolution for service invocation.

The reference's sidecar resolves ``InvokeMethodAsync(..., "tasksmanager-
backend-api", ...)`` to a peer sidecar by app-id (mDNS locally, the ACA
control plane in the cloud — docs/aca/03-aca-dapr-integration/index.md:
107-127). Here the registry is a JSON file shared by all local
sidecars: each sidecar registers itself on startup, peers re-read on
miss or mtime change. A static in-memory mode serves tests and
single-process setups.
"""

from __future__ import annotations

import json
import os
import pathlib
import tempfile
import time
from dataclasses import asdict, dataclass

from tasksrunner.errors import AppNotFound


@dataclass
class AppAddress:
    app_id: str
    host: str
    sidecar_port: int
    app_port: int | None = None
    pid: int | None = None
    registered_at: float = 0.0
    #: framed peer-transport port (invoke/mesh.py — the sidecar↔sidecar
    #: lane, ≙ Dapr's internal gRPC). None = peer only speaks HTTP.
    mesh_port: int | None = None

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.sidecar_port}"


def _pid_started_at(pid: int) -> float | None:
    """Wall-clock time the process holding ``pid`` was created, from
    /proc (Linux). None when undeterminable — non-Linux hosts, the
    process exiting mid-read, malformed stat — in which case callers
    must fall back to plain pid-exists liveness."""
    # Reached from async actor/runtime paths, but /proc is procfs —
    # RAM-backed, sub-microsecond, never touches disk. Dispatching two
    # reads to a worker thread would cost more than it saves on the
    # liveness hot path, so the transitive-blocking chain is allowlisted.
    try:
        stat = pathlib.Path(f"/proc/{pid}/stat").read_bytes()  # tasklint: disable=transitive-blocking
        # fields after the last ')' (comm may embed spaces and parens):
        # the first is field 3 (state); starttime is field 22, so
        # index 19 here — clock ticks since boot
        ticks = int(stat[stat.rindex(b")") + 2:].split()[19])
        for line in pathlib.Path("/proc/stat").read_text().splitlines():  # tasklint: disable=transitive-blocking
            if line.startswith("btime "):
                boot = int(line.split()[1])
                return boot + ticks / os.sysconf("SC_CLK_TCK")
        return None
    except (OSError, ValueError, IndexError):
        return None


def _same_replica(a: dict, b: dict) -> bool:
    """Entry identity for replace-on-reregister: one replica = one
    (pid, sidecar_port) pair. pid alone is not enough — several
    runtimes can share a process (tests, in-proc layouts)."""
    return a.get("pid") == b.get("pid") and \
        a.get("sidecar_port") == b.get("sidecar_port")


class NameResolver:
    """app-id → replicas of AppAddress, backed by a static table and/or
    a registry file.

    Multi-replica since round 4: every serving replica of an app
    registers its own address, and ``resolve`` hands them out
    round-robin — the local analog of ACA's HTTP ingress
    load-balancing across an app's replicas (the reference's scale
    rules add replicas precisely so traffic spreads over them,
    docs/aca/09-aca-autoscale-keda/index.md). A dead replica's entry
    fails its connect; the caller's retry re-resolves and the rotation
    serves the next replica, so one stale entry degrades a request to
    a retry, never to an outage.
    """

    def __init__(self, *, registry_file: str | pathlib.Path | None = None,
                 static: dict[str, AppAddress] | None = None):
        self.registry_file = pathlib.Path(registry_file) if registry_file else None
        self._static: dict[str, list[AppAddress]] = {
            app_id: [addr] for app_id, addr in (static or {}).items()}
        self._cache: dict[str, list[AppAddress]] = {}
        self._rr: dict[str, int] = {}
        self._mtime = 0.0

    # -- registration ----------------------------------------------------

    def register(self, addr: AppAddress) -> None:
        addr.registered_at = time.time()
        if addr.pid is None:
            addr.pid = os.getpid()
        if self.registry_file is None:
            replicas = self._static.setdefault(addr.app_id, [])
            doc = asdict(addr)
            replicas[:] = [a for a in replicas
                           if not _same_replica(asdict(a), doc)] + [addr]
            return

        def mutate(entries: dict) -> None:
            replicas = entries.get(addr.app_id) or []
            doc = asdict(addr)
            entries[addr.app_id] = [
                e for e in replicas if not _same_replica(e, doc)] + [doc]

        self._mutate(mutate)

    def unregister(self, app_id: str, *, pid: int | None = None,
                   sidecar_port: int | None = None) -> None:
        """Remove one replica's entry (by pid, optionally narrowed by
        sidecar_port), or every entry for the app when pid is None —
        a replica shutting down must not deregister its siblings."""
        def keep(e: dict) -> bool:
            if pid is None:
                return False
            if e.get("pid") != pid:
                return True
            return (sidecar_port is not None
                    and e.get("sidecar_port") != sidecar_port)

        if self.registry_file is None:
            replicas = [a for a in self._static.get(app_id, ())
                        if keep(asdict(a))]
            if replicas:
                self._static[app_id] = replicas
            else:
                self._static.pop(app_id, None)
            return

        def mutate(entries: dict) -> None:
            replicas = [e for e in (entries.get(app_id) or []) if keep(e)]
            if replicas:
                entries[app_id] = replicas
            else:
                entries.pop(app_id, None)

        self._mutate(mutate)

    @staticmethod
    def local_pid_dead(host: str | None, pid: int | None,
                       registered_at: float | None = None) -> bool:
        """True iff the entry was registered on THIS host (loopback)
        with a pid that no longer exists — the signature of SIGKILL
        debris. The ONE liveness predicate: `ps` and the prune sweep
        must never drift apart on what counts as stale. For a remote
        host a missing local pid proves nothing → False.

        ``registered_at`` closes the pid-recycling window: a pid that
        *exists* may belong to a NEW, unrelated process that inherited
        the dead replica's number (Linux wraps at pid_max). When the
        registration time is known and the current holder of the pid
        was born *after* it, the replica that registered is gone and
        the entry is debris — os.kill(pid, 0) succeeding proves only
        that the number is in use, not that it is still ours."""
        if host not in ("127.0.0.1", "localhost"):
            return False
        if not pid or pid == os.getpid():
            return False
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return True
        except PermissionError:  # exists, owned by someone else
            return False
        if registered_at:
            started = _pid_started_at(pid)
            # 2 s slack: /proc btime is whole seconds and the replica
            # sets registered_at after its process start — only a
            # clearly-later birth proves recycling; unknown (non-Linux,
            # proc race) falls back to today's pid-exists answer
            if started is not None and started > registered_at + 2.0:
                return True
        return False

    def prune_dead_local(self) -> list[tuple[str, int]]:
        """Remove replicas registered on THIS host whose pid no longer
        exists — the entries a SIGKILLed topology leaves behind
        (graceful shutdown unregisters; a kill -9 cannot). Stale
        entries only cost invokes a retry, but they poison `ps` (a new
        incarnation on the same ports answers the dead entry's health
        probe) and make every first invoke to the app gamble on the
        rotation. Returns the (app_id, pid) pairs pruned."""
        dead: list[tuple[str, int]] = []

        def is_dead(e: dict) -> bool:
            return self.local_pid_dead(e.get("host"), e.get("pid"),
                                       e.get("registered_at"))

        if self.registry_file is None:
            for app_id, replicas in list(self._static.items()):
                kept = []
                for a in replicas:
                    if is_dead(asdict(a)):
                        dead.append((app_id, a.pid))
                    else:
                        kept.append(a)
                if kept:
                    self._static[app_id] = kept
                else:
                    self._static.pop(app_id, None)
            return dead

        def mutate(entries: dict) -> None:
            for app_id, replicas in list(entries.items()):
                kept = []
                for e in replicas:
                    if is_dead(e):
                        dead.append((app_id, e.get("pid")))
                    else:
                        kept.append(e)
                if kept:
                    entries[app_id] = kept
                else:
                    entries.pop(app_id, None)

        self._mutate(mutate)
        self._mtime = 0.0  # force re-read on the next resolve
        return dead

    def _mutate(self, fn) -> None:  # tasklint: off-loop
        """Atomic read-modify-write with a lock file (cross-process).

        Busy-waits up to seconds on a contended/stale lock file, so
        async callers must dispatch via ``asyncio.to_thread`` — see
        hosting.AppHost.start/stop and orchestrator/run.py.
        """
        assert self.registry_file is not None
        self.registry_file.parent.mkdir(parents=True, exist_ok=True)
        lock = self.registry_file.with_suffix(".lock")
        deadline = time.time() + 5.0
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                break
            except FileExistsError:
                if time.time() > deadline:
                    # stale lock (holder crashed): steal it once, then
                    # give the normal acquisition window again so we
                    # don't unlink locks live processes just created
                    try:
                        lock.unlink()
                    except FileNotFoundError:
                        pass
                    deadline = time.time() + 5.0
                time.sleep(0.01)
        try:
            entries = self._read_file()
            fn(entries)
            tmp_fd, tmp_path = tempfile.mkstemp(dir=self.registry_file.parent)
            with os.fdopen(tmp_fd, "w") as f:
                json.dump(entries, f, indent=2)
            os.replace(tmp_path, self.registry_file)
        finally:
            os.close(fd)
            try:
                lock.unlink()
            except FileNotFoundError:
                pass

    def _read_file(self) -> dict[str, list[dict]]:
        if self.registry_file is None or not self.registry_file.is_file():
            return {}
        try:
            raw = json.loads(self.registry_file.read_text() or "{}")
        except ValueError:
            return {}
        # legacy single-entry format (pre multi-replica): one dict per
        # app_id — normalize so every consumer sees a replica list
        return {app_id: entry if isinstance(entry, list) else [entry]
                for app_id, entry in raw.items()}

    # -- resolution ------------------------------------------------------

    def _refresh(self) -> None:
        if self.registry_file is None:
            return
        try:
            mtime = self.registry_file.stat().st_mtime
        except OSError:
            return
        if mtime == self._mtime:
            return
        # the orchestrator's adoption/prune helpers run short-lived
        # resolvers inside to_thread; a racing refresh is idempotent
        # (worst case one redundant file re-read), so no lock
        self._mtime = mtime  # tasklint: disable=thread-shared-state
        self._cache = {
            app_id: [AppAddress(**e) for e in entries]
            for app_id, entries in self._read_file().items()
        }

    def resolve_all(self, app_id: str) -> list[AppAddress]:
        """Every registered replica of the app (empty ≠ error here —
        ``resolve`` owns the not-found contract)."""
        if app_id in self._static:
            return list(self._static[app_id])
        self._refresh()
        if app_id not in self._cache:
            # force one re-read in case the peer registered this instant
            self._mtime = 0.0
            self._refresh()
        return list(self._cache.get(app_id, ()))

    def resolve(self, app_id: str) -> AppAddress:
        replicas = self.resolve_all(app_id)
        if not replicas:
            known = sorted({*self._static, *self._cache})
            raise AppNotFound(
                f"no app registered with id {app_id!r} (known: {known})"
            ) from None
        # round-robin across replicas (≙ ACA ingress load balancing);
        # a failed attempt's re-resolve naturally rotates onward
        i = self._rr.get(app_id, 0)
        self._rr[app_id] = (i + 1) % (1 << 30)
        return replicas[i % len(replicas)]

    def known_apps(self) -> list[str]:
        self._refresh()
        return sorted({*self._static, *self._cache})
