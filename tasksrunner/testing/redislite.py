"""RedisLite: an in-process asyncio RESP2 server (test double).

Implements exactly the command subset the framework's redis drivers
use — strings (SET/GET/DEL/MGET/SCAN), optimistic transactions
(WATCH/MULTI/EXEC/DISCARD), and streams with consumer groups
(XADD/XGROUP/XREADGROUP/XACK/XPENDING/XCLAIM/XRANGE/XLEN) — with
real Redis semantics for the parts the drivers' correctness depends
on: WATCH aborting EXEC after a concurrent write, '>' delivery
advancing the group cursor, per-entry pending lists with delivery
counts, claim-on-idle redelivery.

This is a TEST DOUBLE, not a database: single-process, in-memory,
no persistence, no AUTH/SELECT/cluster. It exists so the redis
state/pubsub drivers are exercised over a real TCP socket in this
image (no redis-server installed); see tasksrunner/testing/__init__.py
for the parity rationale.
"""

from __future__ import annotations

import asyncio
import itertools
import re
import time
from dataclasses import dataclass, field
from typing import Any

CRLF = b"\r\n"


# ---------------------------------------------------------------- replies

def _simple(s: str) -> bytes:
    return b"+" + s.encode() + CRLF


def _error(s: str) -> bytes:
    return b"-" + s.encode() + CRLF


def _int(n: int) -> bytes:
    return b":%d" % n + CRLF


def _bulk(v: bytes | str | None) -> bytes:
    if v is None:
        return b"$-1" + CRLF
    if isinstance(v, str):
        v = v.encode()
    return b"$%d" % len(v) + CRLF + v + CRLF


def _array(items: list | None) -> bytes:
    if items is None:
        return b"*-1" + CRLF
    out = [b"*%d" % len(items) + CRLF]
    for item in items:
        if isinstance(item, (bytes, str)):
            out.append(_bulk(item))
        elif isinstance(item, int):
            out.append(_int(item))
        elif isinstance(item, list):
            out.append(_array(item))
        elif item is None:
            out.append(_bulk(None))
        else:
            raise TypeError(f"cannot encode {item!r}")
    return b"".join(out)


def _glob_match(pattern: str, value: str) -> bool:
    """Redis MATCH globbing: ``*``, ``?``, ``[...]``, and ``\\x``
    escaping a metacharacter to a literal (fnmatch has no escapes, so
    drivers that escape prefixes would diverge from a live server)."""
    out, i = [], 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "*":
            out.append(".*")
        elif ch == "?":
            out.append(".")
        elif ch == "[":
            j = pattern.find("]", i + 1)
            if j == -1:
                out.append(re.escape(ch))
            else:
                out.append(pattern[i:j + 1])
                i = j
        else:
            out.append(re.escape(ch))
        i += 1
    return re.fullmatch("".join(out), value) is not None


# ---------------------------------------------------------------- streams

def _parse_id(raw: bytes, *, default_seq: int = 0) -> tuple[int, int]:
    s = raw.decode()
    if "-" in s:
        ms, seq = s.split("-", 1)
        return int(ms), int(seq)
    return int(s), default_seq


def _fmt_id(ms: int, seq: int) -> bytes:
    return b"%d-%d" % (ms, seq)


@dataclass
class PendingEntry:
    consumer: bytes
    delivered_at: float
    delivery_count: int = 1


@dataclass
class Group:
    #: id of the last entry handed out via '>' reads
    last_delivered: tuple[int, int] = (0, 0)
    #: entry-id → pending bookkeeping (the PEL)
    pending: dict[bytes, PendingEntry] = field(default_factory=dict)


@dataclass
class Stream:
    entries: list[tuple[bytes, list[bytes]]] = field(default_factory=list)
    last_id: tuple[int, int] = (0, 0)
    groups: dict[bytes, Group] = field(default_factory=dict)
    #: wakes blocked XREADGROUP waiters on append
    appended: asyncio.Event = field(default_factory=asyncio.Event)

    def entry(self, entry_id: bytes) -> list[bytes] | None:
        for eid, fields in self.entries:
            if eid == entry_id:
                return fields
        return None


# ---------------------------------------------------------------- server

class _ConnState:
    def __init__(self) -> None:
        self.watched: dict[bytes, int] = {}
        self.multi: list[list[bytes]] | None = None


class RedisLiteServer:
    """``async with RedisLiteServer() as srv: ... srv.port``"""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        self.strings: dict[bytes, bytes] = {}
        self.streams: dict[bytes, Stream] = {}
        #: key → version counter, drives WATCH invalidation
        self._versions: dict[bytes, int] = {}
        self._version_ctr = itertools.count(1)
        self._id_clock = 0

    # -- lifecycle

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_connection, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def aclose(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def __aenter__(self) -> "RedisLiteServer":
        await self.start()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.aclose()

    # -- wire handling

    async def _serve_connection(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        state = _ConnState()
        try:
            while True:
                try:
                    parts = await self._read_command(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if parts is None:
                    break
                reply = await self._dispatch(parts, state)
                if reply is _CLOSE:
                    writer.write(_simple("OK"))
                    break
                writer.write(reply)
                await writer.drain()
        finally:
            writer.close()

    async def _read_command(self, reader: asyncio.StreamReader) -> list[bytes] | None:
        line = await reader.readline()
        if not line:
            return None
        if not line.startswith(b"*"):
            # inline commands (redis-cli convenience) — not needed
            return [line.strip()]
        count = int(line[1:].strip())
        parts: list[bytes] = []
        for _ in range(count):
            header = await reader.readline()
            length = int(header[1:].strip())
            body = await reader.readexactly(length + 2)
            parts.append(body[:-2])
        return parts

    # -- bookkeeping

    def _touch(self, key: bytes) -> None:
        self._versions[key] = next(self._version_ctr)

    def _version(self, key: bytes) -> int:
        return self._versions.get(key, 0)

    def _next_stream_id(self, stream: Stream) -> tuple[int, int]:
        now_ms = int(time.time() * 1000)
        self._id_clock = max(self._id_clock, now_ms)
        ms, seq = stream.last_id
        if self._id_clock > ms:
            return (self._id_clock, 0)
        return (ms, seq + 1)

    # -- dispatch

    async def _dispatch(self, parts: list[bytes], state: _ConnState) -> bytes:
        cmd = parts[0].upper().decode()
        args = parts[1:]

        if cmd == "QUIT":
            return _CLOSE
        if cmd == "MULTI":
            if state.multi is not None:
                return _error("ERR MULTI calls can not be nested")
            state.multi = []
            return _simple("OK")
        if cmd == "DISCARD":
            state.multi = None
            state.watched.clear()
            return _simple("OK")
        if cmd == "EXEC":
            if state.multi is None:
                return _error("ERR EXEC without MULTI")
            queued, state.multi = state.multi, None
            aborted = any(
                self._version(k) != v for k, v in state.watched.items())
            state.watched.clear()
            if aborted:
                return _array(None)
            replies = []
            for q in queued:
                replies.append(await self._run(q[0].upper().decode(), q[1:], state))
            return b"*%d" % len(replies) + CRLF + b"".join(replies)
        if state.multi is not None:
            # blocking commands may not be queued in this double
            if cmd in ("XREADGROUP",):
                return _error("ERR XREADGROUP inside MULTI is not supported")
            state.multi.append(parts)
            return _simple("QUEUED")
        if cmd == "WATCH":
            for key in args:
                state.watched[key] = self._version(key)
            return _simple("OK")
        if cmd == "UNWATCH":
            state.watched.clear()
            return _simple("OK")
        return await self._run(cmd, args, state)

    async def _run(self, cmd: str, args: list[bytes], state: _ConnState) -> bytes:
        handler = getattr(self, "_cmd_" + cmd.lower(), None)
        if handler is None:
            return _error(f"ERR unknown command '{cmd}'")
        try:
            return await handler(args)
        except RedisLiteBadArgs as exc:
            return _error(f"ERR {exc}")

    # -- string commands

    async def _cmd_ping(self, args: list[bytes]) -> bytes:
        return _simple(args[0].decode()) if args else _simple("PONG")

    async def _cmd_flushall(self, args: list[bytes]) -> bytes:
        self.strings.clear()
        self.streams.clear()
        for key in list(self._versions):
            self._touch(key)
        return _simple("OK")

    async def _cmd_set(self, args: list[bytes]) -> bytes:
        if len(args) < 2:
            raise RedisLiteBadArgs("wrong number of arguments for 'set'")
        self.strings[args[0]] = args[1]
        self._touch(args[0])
        return _simple("OK")

    async def _cmd_get(self, args: list[bytes]) -> bytes:
        return _bulk(self.strings.get(args[0]))

    async def _cmd_del(self, args: list[bytes]) -> bytes:
        n = 0
        for key in args:
            if key in self.strings:
                del self.strings[key]
                n += 1
            elif key in self.streams:
                del self.streams[key]
                n += 1
            self._touch(key)
        return _int(n)

    async def _cmd_exists(self, args: list[bytes]) -> bytes:
        return _int(sum(1 for k in args if k in self.strings or k in self.streams))

    async def _cmd_mget(self, args: list[bytes]) -> bytes:
        return _array([self.strings.get(k) for k in args])

    async def _cmd_keys(self, args: list[bytes]) -> bytes:
        pat = args[0].decode() if args else "*"
        keys = sorted(k for k in (set(self.strings) | set(self.streams))
                      if _glob_match(pat, k.decode()))
        return _array(list(keys))

    async def _cmd_scan(self, args: list[bytes]) -> bytes:
        # single-shot scan: always returns cursor 0 with the full match set
        pat = "*"
        for i in range(1, len(args) - 1):
            if args[i].upper() == b"MATCH":
                pat = args[i + 1].decode()
        keys = sorted(k for k in (set(self.strings) | set(self.streams))
                      if _glob_match(pat, k.decode()))
        return b"*2" + CRLF + _bulk(b"0") + _array(list(keys))

    async def _cmd_type(self, args: list[bytes]) -> bytes:
        key = args[0]
        if key in self.strings:
            return _simple("string")
        if key in self.streams:
            return _simple("stream")
        return _simple("none")

    # -- stream commands

    async def _cmd_xadd(self, args: list[bytes]) -> bytes:
        key, rest = args[0], args[1:]
        maxlen = None
        if rest and rest[0].upper() == b"MAXLEN":
            rest = rest[1:]
            if rest and rest[0] in (b"~", b"="):
                rest = rest[1:]
            if not rest:
                raise RedisLiteBadArgs("MAXLEN needs a count")
            maxlen = int(rest[0])
            rest = rest[1:]
        if len(rest) < 3 or len(rest) % 2 != 1:
            raise RedisLiteBadArgs("wrong number of arguments for 'xadd'")
        raw_id, fields = rest[0], rest[1:]
        stream = self.streams.setdefault(key, Stream())
        if raw_id == b"*":
            entry_id = self._next_stream_id(stream)
        else:
            entry_id = _parse_id(raw_id)
            if entry_id <= stream.last_id:
                return _error(
                    "ERR The ID specified in XADD is equal or smaller than "
                    "the target stream top item")
        stream.last_id = entry_id
        eid = _fmt_id(*entry_id)
        stream.entries.append((eid, list(fields)))
        if maxlen is not None and len(stream.entries) > maxlen:
            stream.entries = stream.entries[-maxlen:]
        self._touch(key)
        stream.appended.set()
        stream.appended = asyncio.Event()  # fresh event for next waiters
        return _bulk(eid)

    async def _cmd_xlen(self, args: list[bytes]) -> bytes:
        stream = self.streams.get(args[0])
        return _int(len(stream.entries) if stream else 0)

    async def _cmd_xrange(self, args: list[bytes]) -> bytes:
        stream = self.streams.get(args[0])
        if stream is None:
            return _array([])
        lo = (0, 0) if args[1] == b"-" else _parse_id(args[1])
        hi = (2**62, 2**62) if args[2] == b"+" else _parse_id(args[2], default_seq=2**62)
        count = None
        if len(args) >= 5 and args[3].upper() == b"COUNT":
            count = int(args[4])
        out = []
        for eid, fields in stream.entries:
            if lo <= _parse_id(eid) <= hi:
                out.append(b"*2" + CRLF + _bulk(eid) + _array(list(fields)))
                if count is not None and len(out) >= count:
                    break
        return b"*%d" % len(out) + CRLF + b"".join(out)

    async def _cmd_xgroup(self, args: list[bytes]) -> bytes:
        sub = args[0].upper()
        if sub != b"CREATE":
            raise RedisLiteBadArgs(f"unsupported XGROUP subcommand {sub!r}")
        key, group, start = args[1], args[2], args[3]
        mkstream = any(a.upper() == b"MKSTREAM" for a in args[4:])
        stream = self.streams.get(key)
        if stream is None:
            if not mkstream:
                return _error(
                    "ERR The XGROUP subcommand requires the key to exist. "
                    "Note that for CREATE you may want to use the MKSTREAM "
                    "option to create an empty stream automatically.")
            stream = self.streams.setdefault(key, Stream())
        if group in stream.groups:
            return _error("BUSYGROUP Consumer Group name already exists")
        if start == b"$":
            last = stream.last_id
        elif start == b"0":
            last = (0, 0)
        else:
            last = _parse_id(start)
        stream.groups[group] = Group(last_delivered=last)
        return _simple("OK")

    async def _cmd_xreadgroup(self, args: list[bytes]) -> bytes:
        # XREADGROUP GROUP g consumer [COUNT n] [BLOCK ms] STREAMS key id
        if args[0].upper() != b"GROUP":
            raise RedisLiteBadArgs("expected GROUP")
        group_name, consumer = args[1], args[2]
        count, block_ms = 16, None
        i = 3
        while i < len(args) and args[i].upper() != b"STREAMS":
            opt = args[i].upper()
            if opt == b"COUNT":
                count = int(args[i + 1]); i += 2
            elif opt == b"BLOCK":
                block_ms = int(args[i + 1]); i += 2
            elif opt == b"NOACK":
                i += 1
            else:
                raise RedisLiteBadArgs(f"unknown XREADGROUP option {opt!r}")
        key, read_id = args[i + 1], args[i + 2]
        if read_id != b">":
            raise RedisLiteBadArgs("this double only supports the '>' id")
        deadline = None if block_ms is None else (
            asyncio.get_running_loop().time() + block_ms / 1000.0)
        while True:
            stream = self.streams.get(key)
            group = stream.groups.get(group_name) if stream else None
            if group is None:
                return _error(
                    f"NOGROUP No such consumer group '{group_name.decode()}' "
                    f"for key name '{key.decode()}'")
            fresh = [(eid, fields) for eid, fields in stream.entries
                     if _parse_id(eid) > group.last_delivered][:count]
            if fresh:
                now = time.monotonic()
                for eid, _ in fresh:
                    group.last_delivered = max(
                        group.last_delivered, _parse_id(eid))
                    group.pending[eid] = PendingEntry(consumer, now)
                entries = b"".join(
                    b"*2" + CRLF + _bulk(eid) + _array(list(fields))
                    for eid, fields in fresh)
                inner = b"*2" + CRLF + _bulk(key) + \
                    b"*%d" % len(fresh) + CRLF + entries
                return b"*1" + CRLF + inner
            if deadline is None:
                return _array(None)
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                return _array(None)
            event = stream.appended
            try:
                await asyncio.wait_for(event.wait(), timeout=remaining)
            except asyncio.TimeoutError:
                return _array(None)

    async def _cmd_xack(self, args: list[bytes]) -> bytes:
        stream = self.streams.get(args[0])
        if stream is None:
            return _int(0)
        group = stream.groups.get(args[1])
        if group is None:
            return _int(0)
        n = 0
        for eid in args[2:]:
            if eid in group.pending:
                del group.pending[eid]
                n += 1
        return _int(n)

    async def _cmd_xpending(self, args: list[bytes]) -> bytes:
        # extended form: XPENDING key group [IDLE ms] start end count [consumer]
        stream = self.streams.get(args[0])
        group = stream.groups.get(args[1]) if stream else None
        if group is None:
            return _array([])
        rest = args[2:]
        min_idle_ms = 0
        if rest and rest[0].upper() == b"IDLE":
            min_idle_ms = int(rest[1])
            rest = rest[2:]
        if len(rest) < 3:
            raise RedisLiteBadArgs("this double only supports extended XPENDING")
        lo = (0, 0) if rest[0] == b"-" else _parse_id(rest[0])
        hi = (2**62, 2**62) if rest[1] == b"+" else _parse_id(rest[1], default_seq=2**62)
        count = int(rest[2])
        now = time.monotonic()
        rows = []
        for eid in sorted(group.pending, key=_parse_id):
            if not (lo <= _parse_id(eid) <= hi):
                continue
            pe = group.pending[eid]
            idle_ms = int((now - pe.delivered_at) * 1000)
            if idle_ms < min_idle_ms:
                continue
            rows.append(
                b"*4" + CRLF + _bulk(eid) + _bulk(pe.consumer)
                + _int(idle_ms) + _int(pe.delivery_count))
            if len(rows) >= count:
                break
        return b"*%d" % len(rows) + CRLF + b"".join(rows)

    async def _cmd_xclaim(self, args: list[bytes]) -> bytes:
        key, group_name, consumer, min_idle = args[0], args[1], args[2], int(args[3])
        stream = self.streams.get(key)
        group = stream.groups.get(group_name) if stream else None
        if group is None:
            return _error(
                f"NOGROUP No such consumer group '{group_name.decode()}' "
                f"for key name '{key.decode()}'")
        now = time.monotonic()
        out = []
        for eid in args[4:]:
            pe = group.pending.get(eid)
            if pe is None:
                continue
            if (now - pe.delivered_at) * 1000 < min_idle:
                continue
            fields = stream.entry(eid)
            if fields is None:
                del group.pending[eid]  # entry trimmed: drop from PEL
                continue
            pe.consumer = consumer
            pe.delivered_at = now
            pe.delivery_count += 1
            out.append(b"*2" + CRLF + _bulk(eid) + _array(list(fields)))
        return b"*%d" % len(out) + CRLF + b"".join(out)


class RedisLiteBadArgs(Exception):
    pass


#: sentinel: close the connection after replying OK
_CLOSE = object()
