"""The chaos-seeded overload drill: the closed loop, end to end.

This module is the shared harness behind ``tests/test_overload_drill.py``
(which asserts the trajectory) and ``bench.py --overload-bench`` /
``make bench-overload`` (which prints it): drive a real orchestrator —
subprocess replicas, sqlite state store slowed by a deterministic
``kind: Chaos`` latency fault — through sustained overload and record
what the control loop does about it.

The trajectory the loop must produce:

1. **shed** — admission control (``TASKSRUNNER_ADMISSION=1``, tight
   in-flight line) answers the flood's excess with 429 + Retry-After
   instead of queueing into collapse;
2. **scale out** — the ``target-p99`` rule reads the replicas' merged
   latency histograms through sidecar ``/v1.0/metadata`` (exempt from
   shedding) and adds replicas;
3. **recover** — the flood stops, the windowed p99 clears, and after
   the cooldown the fleet returns to ``min_replicas``;
4. **no lost acks** — every key a client got a 2xx for is durably in
   the store afterwards; shed requests failed loudly, acked requests
   never silently vanished.

``make_app`` is the replica entrypoint
(``tasksrunner.testing.overload:make_app``): one POST route that
writes a state key per request, so overload pressure lands on the
chaos-slowed store and the drill's loss check is exact.
"""

from __future__ import annotations

import asyncio
import json
import pathlib
import sqlite3

from tasksrunner.app import App

#: module path the orchestrator spawns replicas from
APP_MODULE = "tasksrunner.testing.overload:make_app"
APP_ID = "overload-target"
STORE = "statestore"


def make_app() -> App:
    app = App(APP_ID)

    @app.post("/api/work")
    async def work(req):
        body = req.json() or {}
        key = str(body.get("key", "k"))
        await app.client.save_state(STORE, key, {"n": body.get("n", 0)})
        return {"stored": key}

    return app


def _write_resources(resources: pathlib.Path, db_path: pathlib.Path,
                     latency_ms: int) -> None:
    resources.mkdir(parents=True, exist_ok=True)
    (resources / f"{STORE}.yaml").write_text(json.dumps({
        "componentType": "state.sqlite",
        "metadata": [{"name": "databasePath", "value": str(db_path)}],
    }))
    # deterministic fault: every store call gets latency_ms extra —
    # the overload that makes a modest flood saturate one replica
    (resources / "chaos.yaml").write_text(f"""\
apiVersion: tasksrunner/v1alpha1
kind: Chaos
metadata:
  name: overload-drill
spec:
  seed: 7
  faults:
    slowStore:
      latency:
        duration: {latency_ms}ms
        jitter: {latency_ms // 2}ms
  targets:
    components:
      {STORE}:
        outbound: [slowStore]
""")


def stored_keys(db_path: pathlib.Path) -> set[str]:
    """User-visible keys durably in the store file (prefix stripped)."""
    from tasksrunner.state.keyprefix import SEPARATOR

    if not db_path.exists():
        return set()
    conn = sqlite3.connect(db_path)
    try:
        rows = conn.execute("SELECT key FROM state").fetchall()
    finally:
        conn.close()
    return {row[0].split(SEPARATOR, 1)[-1] for row in rows}


def _parse_prometheus(text: str, name: str) -> float:
    """Sum of every sample of ``name`` in a text exposition."""
    total = 0.0
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        if line.startswith(name) and line[len(name):len(name) + 1] in ("{", " "):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except (IndexError, ValueError):
                continue
    return total


async def run_overload_drill(
    base_dir: pathlib.Path,
    *,
    flood_seconds: float = 3.5,
    concurrency: int = 16,
    max_replicas: int = 2,
    max_inflight: int = 4,
    latency_ms: int = 120,
    cooldown_seconds: float = 1.0,
    settle_timeout: float = 30.0,
) -> dict:
    """Run the drill; return the measured trajectory (no assertions —
    callers decide what passing means)."""
    import aiohttp

    from tasksrunner.observability.metrics import metrics
    from tasksrunner.orchestrator.config import (
        AppSpec,
        RunConfig,
        ScaleRule,
        ScaleSpec,
    )
    from tasksrunner.orchestrator.run import Orchestrator

    base_dir = pathlib.Path(base_dir)
    db_path = base_dir / "overload-state.db"
    resources = base_dir / "resources"
    await asyncio.to_thread(_write_resources, resources, db_path, latency_ms)

    config = RunConfig(
        apps=[AppSpec(
            app_id=APP_ID, module=APP_MODULE,
            env={
                "TASKSRUNNER_CHAOS": "1",
                "TASKSRUNNER_ADMISSION": "1",
                "TASKSRUNNER_ADMISSION_MAX_INFLIGHT": str(max_inflight),
                "TASKSRUNNER_ACCESS_LOG": "0",
            },
            scale=ScaleSpec(
                min_replicas=1, max_replicas=max_replicas,
                cooldown_seconds=cooldown_seconds,
                rules=[
                    ScaleRule(type="target-p99", metadata={
                        "metric": "state_op_latency_seconds",
                        # far below the injected latency: sustained
                        # traffic through the slowed store must argue
                        # for the whole allowed fleet
                        "targetSeconds": str(latency_ms / 1000.0 / 4),
                        "minSamples": "8",
                    }),
                    ScaleRule(type="loop-lag",
                              metadata={"maxLagSeconds": "0.5"}),
                ],
            ),
        )],
        resources_path=str(resources),
        registry_file=str(base_dir / "apps.json"),
        base_dir=base_dir,
    )

    loop = asyncio.get_running_loop()
    orch = Orchestrator(config)
    acked: set[str] = set()
    result = {
        "acked": 0, "shed": 0, "shed_without_retry_after": 0,
        "unexpected_statuses": {}, "connection_errors": 0,
        "retry_after_min": None, "retry_after_max": None,
        "max_replicas_seen": 1, "desired_gauge_peak": 0.0,
        "final_replicas": None, "recovered_to_min": False,
        "shed_metric_total": 0.0, "admission_state_after": None,
        "lost_acked_keys": [],
    }
    try:
        await orch.start()
        replica = orch.replicas[APP_ID][0]
        await asyncio.wait_for(replica.ready.wait(), timeout=30)
        app_port, sidecar_port = replica.ports

        stop_flood = asyncio.Event()

        async def flood_worker(session: "aiohttp.ClientSession", w: int):
            i = 0
            while not stop_flood.is_set():
                key = f"w{w}-{i}"
                i += 1
                try:
                    async with session.post(
                            f"http://127.0.0.1:{app_port}/api/work",
                            json={"key": key, "n": i}) as resp:
                        await resp.read()
                        if 200 <= resp.status < 300:
                            acked.add(key)
                        elif resp.status == 429:
                            result["shed"] += 1
                            ra = resp.headers.get("Retry-After")
                            if ra is None:
                                result["shed_without_retry_after"] += 1
                            else:
                                v = float(ra)
                                for bound, fn in (("retry_after_min", min),
                                                  ("retry_after_max", max)):
                                    cur = result[bound]
                                    result[bound] = v if cur is None else fn(cur, v)
                                # honor the hint, capped so the drill
                                # keeps producing pressure
                                await asyncio.sleep(min(v, 0.2))
                        else:
                            k = str(resp.status)
                            result["unexpected_statuses"][k] = (
                                result["unexpected_statuses"].get(k, 0) + 1)
                except (OSError, aiohttp.ClientError):
                    # connection collapse — exactly what shedding exists
                    # to prevent; callers assert this stays 0
                    result["connection_errors"] += 1
                    await asyncio.sleep(0.05)

        async def watch_fleet():
            while not stop_flood.is_set():
                result["max_replicas_seen"] = max(
                    result["max_replicas_seen"], orch.replica_count(APP_ID))
                result["desired_gauge_peak"] = max(
                    result["desired_gauge_peak"],
                    metrics.get("autoscale_desired_replicas", app=APP_ID))
                await asyncio.sleep(0.1)

        async with aiohttp.ClientSession() as session:
            tasks = [asyncio.create_task(flood_worker(session, w))
                     for w in range(concurrency)]
            tasks.append(asyncio.create_task(watch_fleet()))
            await asyncio.sleep(flood_seconds)
            stop_flood.set()
            await asyncio.gather(*tasks, return_exceptions=True)

            # recovery: windowed p99 clears, cooldown elapses, the
            # fleet returns to min
            deadline = loop.time() + settle_timeout
            while loop.time() < deadline:
                count = orch.replica_count(APP_ID)
                result["max_replicas_seen"] = max(
                    result["max_replicas_seen"], count)
                result["desired_gauge_peak"] = max(
                    result["desired_gauge_peak"],
                    metrics.get("autoscale_desired_replicas", app=APP_ID))
                if (count <= config.apps[0].scale.min_replicas
                        and result["max_replicas_seen"] > 1):
                    result["recovered_to_min"] = True
                    break
                await asyncio.sleep(0.2)
            result["final_replicas"] = orch.replica_count(APP_ID)

            # the trajectory must be visible from the outside: scrape
            # replica 0's /metrics exposition
            deadline = loop.time() + 10
            while loop.time() < deadline:
                try:
                    async with session.get(
                            f"http://127.0.0.1:{sidecar_port}/metrics") as resp:
                        text = await resp.text()
                except (OSError, aiohttp.ClientError):
                    await asyncio.sleep(0.2)
                    continue
                result["shed_metric_total"] = _parse_prometheus(
                    text, "admission_shed_total")
                result["admission_state_after"] = _parse_prometheus(
                    text, "admission_state")
                if result["admission_state_after"] == 0.0:
                    break  # hysteresis exited; trajectory complete
                await asyncio.sleep(0.2)
    finally:
        await asyncio.shield(orch.stop())

    result["acked"] = len(acked)
    durable = await asyncio.to_thread(stored_keys, db_path)
    result["lost_acked_keys"] = sorted(acked - durable)
    return result
