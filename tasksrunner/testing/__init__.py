"""Test doubles shipped with the framework.

The reference's QA story leans on *local-parity backends instead of
mocks* — `dapr init` drops a real Redis container next to the apps
(docs/aca/04-aca-dapr-stateapi/index.md:29-33) so the same component
YAML runs against a live wire protocol in dev. This image has no Redis
server, so the framework ships ``redislite``: a hermetic in-process
RESP2 server implementing the command subset the redis drivers speak.
Tests (and users without a Redis) get real-socket coverage of the
redis backends; against a genuine Redis the same drivers run unchanged.
"""

from tasksrunner.testing.redislite import RedisLiteServer  # noqa: F401
