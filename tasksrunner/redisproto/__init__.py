"""Minimal asyncio RESP2 (Redis serialization protocol) client.

Why this exists: the reference's local-dev story is "Redis stands in
for the cloud backends" (`dapr init` starts a Redis container;
components/dapr-pubsub-redis.yaml:1-12 points the pub/sub block at it,
docs/aca/04-aca-dapr-stateapi/index.md:29-33). To honor that parity
slot with a *real wire protocol* — not just a type alias onto the
sqlite engines — the framework speaks RESP itself. No third-party
redis package is required (none is installed in this image); the
protocol is simple enough that a ~200-line client is the honest
dependency-free implementation.

Used by: tasksrunner/state/redis.py (state.redis driver),
tasksrunner/pubsub/redis.py (pubsub.redis streams broker), and the
hermetic test server tasksrunner/testing/redislite.py.
"""

from tasksrunner.redisproto.client import (  # noqa: F401
    CleanExit,
    RedisClient,
    RedisConnection,
    RedisProtocolError,
    RedisReplyError,
    as_str,
)
