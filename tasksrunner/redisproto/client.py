"""RESP2 wire client: encode command arrays, parse reply trees.

Reply mapping: simple string → ``str``; error → raised
``RedisReplyError``; integer → ``int``; bulk string → ``bytes`` (or
``None`` for the null bulk); array → ``list`` (or ``None`` for the
null array, e.g. a WATCH-aborted ``EXEC``).

Concurrency model: a ``RedisConnection`` is a single socket and must
not be shared by interleaving tasks. ``RedisClient`` pools
connections — ``execute()`` grabs a free one per command;
``acquire()`` checks one out for multi-command sequences that need
connection affinity (WATCH/MULTI/EXEC transactions, blocking
XREADGROUP consumer loops).
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import Any

from tasksrunner.errors import TasksRunnerError


class RedisProtocolError(TasksRunnerError):
    """Malformed RESP frame or connection failure."""


class RedisReplyError(TasksRunnerError):
    """The server answered with a ``-ERR``-class reply."""

    def __init__(self, message: str):
        super().__init__(message)
        self.code = message.split(" ", 1)[0] if message else ""


class CleanExit(Exception):
    """Raise inside ``RedisClient.acquire`` to leave the block with an
    application-level error while certifying the connection is in a
    clean, pool-safe state (no armed WATCH, no open MULTI, no unread
    reply). ``acquire`` re-raises the wrapped ``error``."""

    def __init__(self, error: BaseException):
        super().__init__(str(error))
        self.error = error


def as_str(value: Any) -> str:
    """Bulk strings arrive as bytes; normalize for comparisons."""
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    return str(value)


def encode_command(*parts: Any) -> bytes:
    """RESP array of bulk strings: ``*N\\r\\n$len\\r\\n<part>\\r\\n...``"""
    out = [b"*%d\r\n" % len(parts)]
    for part in parts:
        if isinstance(part, bytes):
            raw = part
        elif isinstance(part, str):
            raw = part.encode()
        elif isinstance(part, bool):  # before int: bool is an int subtype
            raw = b"1" if part else b"0"
        elif isinstance(part, (int, float)):
            raw = repr(part).encode()
        else:
            raise TypeError(f"cannot send {type(part).__name__} as a command part")
        out.append(b"$%d\r\n%s\r\n" % (len(raw), raw))
    return b"".join(out)


async def read_reply(reader: asyncio.StreamReader) -> Any:
    line = await reader.readline()
    if not line:
        raise RedisProtocolError("connection closed mid-reply")
    if not line.endswith(b"\r\n"):
        raise RedisProtocolError(f"unterminated reply line: {line!r}")
    kind, payload = line[:1], line[1:-2]
    if kind == b"+":
        return payload.decode()
    if kind == b"-":
        raise RedisReplyError(payload.decode())
    if kind == b":":
        return int(payload)
    if kind == b"$":
        length = int(payload)
        if length == -1:
            return None
        body = await reader.readexactly(length + 2)
        return body[:-2]
    if kind == b"*":
        count = int(payload)
        if count == -1:
            return None
        return [await read_reply(reader) for _ in range(count)]
    raise RedisProtocolError(f"unknown reply type {kind!r}")


class RedisConnection:
    """One socket. Owns request/reply framing, nothing else."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None

    @property
    def connected(self) -> bool:
        return self._writer is not None and not self._writer.is_closing()

    async def connect(self) -> None:
        try:
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port)
        except OSError as exc:
            raise RedisProtocolError(
                f"cannot reach redis at {self.host}:{self.port}: {exc}") from exc

    async def execute(self, *parts: Any) -> Any:
        if not self.connected:
            await self.connect()
        assert self._reader is not None and self._writer is not None
        self._writer.write(encode_command(*parts))
        await self._writer.drain()
        return await read_reply(self._reader)

    def close_now(self) -> None:
        """Synchronous close: schedules the transport teardown without
        awaiting it (safe from non-async cleanup paths)."""
        if self._writer is not None:
            with contextlib.suppress(Exception):
                self._writer.close()
            self._writer = None
            self._reader = None

    async def aclose(self) -> None:
        if self._writer is not None:
            writer = self._writer
            self.close_now()
            with contextlib.suppress(Exception):
                await writer.wait_closed()


class RedisClient:
    """Connection pool over ``RedisConnection``.

    ``host`` accepts the reference's component-metadata shape
    ``"localhost:6379"`` (components/dapr-pubsub-redis.yaml `redisHost`)
    or a bare hostname plus an explicit ``port``.
    """

    def __init__(self, host: str = "localhost", port: int = 6379, *,
                 max_connections: int = 16):
        if ":" in host:
            host, _, port_s = host.rpartition(":")
            port = int(port_s)
        self.host = host
        self.port = port
        self._free: list[RedisConnection] = []
        self._sem = asyncio.Semaphore(max_connections)
        self._all: list[RedisConnection] = []
        self._closed = False

    async def _checkout(self) -> RedisConnection:
        if self._closed:
            raise RedisProtocolError("client closed")
        await self._sem.acquire()
        while self._free:
            conn = self._free.pop()
            if conn.connected:
                return conn
            conn.close_now()
            if conn in self._all:
                self._all.remove(conn)
        conn = RedisConnection(self.host, self.port)
        try:
            await conn.connect()
        except BaseException:
            # BaseException: a cancellation here must not leak the
            # permit or the half-open socket either
            conn.close_now()
            self._sem.release()
            raise
        self._all.append(conn)
        return conn

    def _checkin(self, conn: RedisConnection, *, broken: bool = False) -> None:
        if broken or self._closed or not conn.connected:
            conn.close_now()
            if conn in self._all:
                self._all.remove(conn)
        else:
            self._free.append(conn)
        self._sem.release()

    async def execute(self, *parts: Any) -> Any:
        conn = await self._checkout()
        # Any non-protocol failure — including cancellation while a
        # reply is in flight (BLOCK'd XREADGROUP being torn down) —
        # must retire the socket: an unread reply would desync RESP
        # framing for the next borrower.
        broken = True
        try:
            reply = await conn.execute(*parts)
            broken = False
            return reply
        except RedisReplyError:
            broken = False  # server replied; the stream is in sync
            raise
        finally:
            self._checkin(conn, broken=broken)

    @contextlib.asynccontextmanager
    async def acquire(self):
        """Dedicated connection for WATCH/MULTI/EXEC or blocking reads.

        Exit classification: a clean exit or a ``CleanExit``-wrapped
        error returns the connection to the pool as-is; a server reply
        error sanitizes possible WATCH/MULTI leftovers first (an armed
        WATCH on a pooled connection would spuriously abort the next
        borrower's EXEC); anything else — including cancellation mid-
        reply — retires the socket."""
        conn = await self._checkout()
        broken = True
        try:
            yield conn
            broken = False
        except CleanExit as exc:
            broken = False
            raise exc.error from None
        except RedisReplyError:
            broken = not await self._sanitize(conn)
            raise
        finally:
            self._checkin(conn, broken=broken)

    @staticmethod
    async def _sanitize(conn: RedisConnection) -> bool:
        """Best-effort DISCARD + UNWATCH; False if the socket is gone."""
        for cmd in ("DISCARD", "UNWATCH"):
            try:
                await conn.execute(cmd)
            except RedisReplyError:
                pass  # "DISCARD without MULTI" — nothing was open
            except Exception:
                return False
        return True

    async def ping(self) -> bool:
        return await self.execute("PING") == "PONG"

    async def aclose(self) -> None:
        self._closed = True
        for conn in list(self._all):
            await conn.aclose()
        self._all.clear()
        self._free.clear()
