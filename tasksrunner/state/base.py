"""State-store building block interface.

API shape mirrors the client surface the reference's services program
against (DaprClient in TasksTracker.TasksManager.Backend.Api/Services/
TasksStoreManager.cs: SaveStateAsync :35, GetStateAsync :73,
DeleteStateAsync :49, QueryStateAsync :56-61) and the sidecar routes
``POST/GET/DELETE /v1.0/state/{store}`` plus
``POST /v1.0/state/{store}/query``.

Values are JSON documents (anything ``json.dumps`` accepts). Every
write produces a fresh opaque etag; writes may assert an expected etag
for optimistic concurrency (first-write-wins) — the reference's
read-modify-write race noted in SURVEY.md §5.2 is thereby fixable in
this framework, while plain last-write-wins stays the default.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Literal

from tasksrunner.errors import EtagMismatch  # noqa: F401  (re-export for drivers)


@dataclass
class StateItem:
    """One key's stored document + concurrency token."""

    key: str
    value: Any
    etag: str


@dataclass
class TransactionOp:
    """One operation inside a state transaction (upsert or delete)."""

    operation: Literal["upsert", "delete"]
    key: str
    value: Any = None
    etag: str | None = None


@dataclass
class QueryResponse:
    items: list[StateItem] = field(default_factory=list)
    #: Continuation token (index-based) when paging; None = exhausted.
    token: str | None = None


class StateStore(abc.ABC):
    """Pluggable state backend. All methods are coroutine functions so
    network-backed drivers can await; local drivers just return."""

    #: Whether the backend supports the filter-query dialect. Plain
    #: key-value backends (reference: Redis without RediSearch,
    #: docs/aca/04-aca-dapr-stateapi/index.md:166-168) set this False
    #: and `query` raises QueryError.
    supports_query = True

    def __init__(self, name: str):
        self.name = name

    @abc.abstractmethod
    async def get(self, key: str) -> StateItem | None: ...

    @abc.abstractmethod
    async def set(self, key: str, value: Any, *, etag: str | None = None) -> str:
        """Upsert; returns the new etag. Raises EtagMismatch if ``etag``
        is given and doesn't match the stored one.

        Drivers MAY coalesce concurrent writes into one backend
        transaction (the sqlite engine's group-commit queue does), but
        per-call semantics must be preserved exactly: each caller gets
        its own etag or EtagMismatch, a call resolves only after its
        write is durable, and writes apply in submission order."""

    @abc.abstractmethod
    async def delete(self, key: str, *, etag: str | None = None) -> bool:
        """Delete; returns False if the key didn't exist."""

    @abc.abstractmethod
    async def query(self, query: dict, *, key_prefix: str = "") -> QueryResponse:
        """Evaluate the filter-query dialect (see state/query.py) over
        keys starting with ``key_prefix``."""

    async def bulk_get(self, keys: list[str]) -> list[StateItem | None]:
        return [await self.get(k) for k in keys]

    async def transact(self, ops: list[TransactionOp]) -> None:
        """Apply ops atomically (best-effort for drivers without real
        transactions; sqlite driver overrides with a DB transaction)."""
        for op in ops:
            if op.operation == "upsert":
                await self.set(op.key, op.value, etag=op.etag)
            else:
                await self.delete(op.key, etag=op.etag)

    async def keys(self, *, prefix: str = "") -> list[str]:
        """List keys (diagnostics; not part of the reference surface)."""
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - default no-op
        """Release backend resources. Must be callable without a
        running event loop (CLI probes close stores synchronously)."""
        pass

    async def aclose(self) -> None:
        """Async close; the component registry prefers this when
        present. Default delegates to the sync ``close()`` — drivers
        with real async teardown (network stores) override it."""
        self.close()
