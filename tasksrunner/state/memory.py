"""In-memory state store — the framework's first-class test double.

Plays the role FakeTasksManager's List<TaskModel> plays in the
reference (Services/FakeTasksManager.cs:5-113): full contract, zero
dependencies — but lives at the building-block layer so *every* app
gets it by swapping one component file, and it is lock-guarded (the
reference's fake is unsynchronized, SURVEY.md §5.2).
"""

from __future__ import annotations

import asyncio
import copy
import itertools
from typing import Any

from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import EtagMismatch
from tasksrunner.state.base import QueryResponse, StateItem, StateStore, TransactionOp
from tasksrunner.state.query import run_query


class InMemoryStateStore(StateStore):
    def __init__(self, name: str = "memory"):
        super().__init__(name)
        self._data: dict[str, StateItem] = {}
        self._etag_counter = itertools.count(1)
        self._lock = asyncio.Lock()

    def _next_etag(self) -> str:
        return str(next(self._etag_counter))

    async def get(self, key: str) -> StateItem | None:
        item = self._data.get(key)
        if item is None:
            return None
        return StateItem(key=item.key, value=copy.deepcopy(item.value), etag=item.etag)

    async def set(self, key: str, value: Any, *, etag: str | None = None) -> str:
        async with self._lock:
            return self._set_locked(key, value, etag)

    def _set_locked(self, key: str, value: Any, etag: str | None) -> str:
        current = self._data.get(key)
        if etag is not None and (current is None or current.etag != etag):
            raise EtagMismatch(f"etag mismatch for key {key!r}")
        new_etag = self._next_etag()
        self._data[key] = StateItem(key=key, value=copy.deepcopy(value), etag=new_etag)
        return new_etag

    async def delete(self, key: str, *, etag: str | None = None) -> bool:
        async with self._lock:
            current = self._data.get(key)
            if current is None:
                if etag is not None:
                    raise EtagMismatch(f"etag mismatch for key {key!r}")
                return False
            if etag is not None and current.etag != etag:
                raise EtagMismatch(f"etag mismatch for key {key!r}")
            del self._data[key]
            return True

    async def transact(self, ops: list[TransactionOp]) -> None:
        """Atomic: validate all etags under the lock, then apply."""
        async with self._lock:
            for op in ops:
                current = self._data.get(op.key)
                if op.etag is not None and (current is None or current.etag != op.etag):
                    raise EtagMismatch(f"etag mismatch for key {op.key!r}")
            for op in ops:
                if op.operation == "upsert":
                    self._set_locked(op.key, op.value, None)
                else:
                    self._data.pop(op.key, None)

    async def query(self, query: dict, *, key_prefix: str = "") -> QueryResponse:
        candidates = [
            it for key, it in sorted(self._data.items())
            if key.startswith(key_prefix)
        ]
        # filter/sort/page on the live items (read-only), deep-copy only
        # the page actually returned
        items, token = run_query(candidates, query)
        items = [
            StateItem(key=it.key, value=copy.deepcopy(it.value), etag=it.etag)
            for it in items
        ]
        return QueryResponse(items=items, token=token)

    async def keys(self, *, prefix: str = "") -> list[str]:
        return sorted(k for k in self._data if k.startswith(prefix))


@driver("state.in-memory", "state.memory")
def _memory_state(spec: ComponentSpec, metadata: dict[str, str]) -> InMemoryStateStore:
    return InMemoryStateStore(spec.name)
