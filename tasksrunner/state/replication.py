"""Replicated state plane: per-shard replica sets with leased
leadership, epoch fencing, and ack-after-replication.

Each shard of a state component becomes a replica set of N members.
Exactly one member at a time holds the shard's **lease** (an
etag-guarded record in a shared meta store — the PR 7 actor-placement
fencing model applied one layer down) and is the shard's leader: its
group-commit flusher appends every batch to a logical write-ahead
record stream (``repl_log``, monotonic per-shard sequence numbers,
state/sqlite.py) and ships the stream to the followers, which apply
records in order and ack their high-water mark.

The durability contract is **ack-after-replication**: with
``ackQuorum`` > 1, a caller's write future resolves only once the
record is durable on that many members (leader included). A leader
that loses its lease is **fenced** — a follower's promotion bumps the
epoch, every member refuses lower-epoch records, and the zombie's late
commits fail :class:`~tasksrunner.errors.ReplicaFencedError` without
ever having been acked. Zero lost acked writes is therefore structural,
not probabilistic; the chaos drill in tests/test_replication.py proves
it under ``kill -9`` and blackhole.

Roles are dynamic: every member runs a small role loop (renew the
lease when leader; watch for expiry and promote when follower). A
promoted follower first appends a **leadership barrier** — an empty
record at its new epoch, Raft's no-op commit — then resyncs peers from
its log (or a full snapshot when a peer's log diverged or the bounded
log was pruned past the gap).

Follower reads are the optional stale-tolerant path: with
``followerReads: true`` the facade serves reads from a follower whose
lag (leader hwm − follower hwm) is within ``maxLagRecords``,
redirecting to the leader beyond the bound; a *direct* follower read
past the bound raises :class:`~tasksrunner.errors.StaleReadError`.

The in-process member/link classes here are the unit the mesh-framed
transport (state/replmesh.py) wraps for cross-process replica sets;
the protocol — ``append`` / ``install`` / ``position`` plus the gap
and fencing errors — is identical on both.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import pathlib
import time
from typing import Any, Callable

from tasksrunner.errors import (
    ComponentError, EtagMismatch, NotLeaderError, ReplicaFencedError,
    ReplicationError, ReplicationGapError, ReplicationQuorumError,
    StaleReadError, StateError,
)
from tasksrunner.ids import hex8
from tasksrunner.observability.metrics import metrics
from tasksrunner.observability.spans import active as spans_active, record_span
from tasksrunner.observability.tracing import TraceContext
from tasksrunner.state.base import (
    QueryResponse, StateItem, StateStore, TransactionOp,
)
from tasksrunner.state.sqlite import SqliteStateStore, _shard_path

logger = logging.getLogger(__name__)


def _batch_tp(records: list[dict]) -> str | None:
    """The traceparent keying a shipped batch: the first record that
    carries one (records without a captured context stay quiet)."""
    for rec in records:
        tp = rec.get("tp")
        if tp:
            return tp
    return None


def _tp_span(tp: str | None, *, name: str, kind: str, status: int,
             start: float, duration: float, attrs: dict) -> None:
    """Ship/apply/ack spans join the committing write's trace via the
    traceparent the record carries — the replication loops run nowhere
    near the write's ambient context."""
    if tp is None or not spans_active():
        return
    ctx = TraceContext.parse(tp)
    if ctx is None:
        return
    record_span(name=name, kind=kind, status=status, start=start,
                duration=duration, attrs=attrs, trace_id=ctx.trace_id,
                span_id=hex8(), parent_id=ctx.span_id)

#: hard ceiling on replication factor — each member is a full engine
#: (file + threads + connections); past RF 5 the write amplification
#: costs more availability than it buys
MAX_REPLICAS = 5

DEFAULT_LEASE_SECONDS = 5.0
DEFAULT_ACK_TIMEOUT_SECONDS = 10.0
DEFAULT_MAX_LAG_RECORDS = 256
DEFAULT_LOG_RETAIN = 4096


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def lease_seconds_default() -> float:
    return _env_float("TASKSRUNNER_REPL_LEASE_SECONDS", DEFAULT_LEASE_SECONDS)


def ack_timeout_default() -> float:
    return _env_float("TASKSRUNNER_REPL_ACK_TIMEOUT_SECONDS",
                      DEFAULT_ACK_TIMEOUT_SECONDS)


def max_lag_default() -> int:
    return _env_int("TASKSRUNNER_REPL_MAX_LAG_RECORDS",
                    DEFAULT_MAX_LAG_RECORDS)


def log_retain_default() -> int:
    return _env_int("TASKSRUNNER_REPL_LOG_RETAIN", DEFAULT_LOG_RETAIN)


class Lease:
    """An epoch-fenced lease over ONE record in a state store.

    The record — ``{owner, epoch, expires, host, pid, registered_at}``
    — is only ever replaced with an etag-guarded write, so two
    contenders can never both win a takeover: the loser's write fails
    :class:`EtagMismatch`. Every change of ownership bumps ``epoch``;
    holders embed their epoch in everything they emit, and consumers
    refuse lower epochs — the fencing contract shared with the actor
    placement table (PR 7) and now the shard record stream.

    Liveness is lease expiry OR a dead local pid: the record carries
    the holder's host/pid/registration time, and
    ``NameResolver.local_pid_dead`` (the ONE liveness predicate in this
    codebase) detects SIGKILL debris without waiting out the lease.
    """

    def __init__(self, store: StateStore, key: str, *,
                 lease_seconds: float | None = None):
        self._store = store
        self.key = key
        self.lease_seconds = (float(lease_seconds) if lease_seconds
                              else lease_seconds_default())

    def _record(self, owner: str, epoch: int) -> dict:
        return {
            "owner": owner,
            "epoch": int(epoch),
            "expires": time.time() + self.lease_seconds,
            "host": "127.0.0.1",
            "pid": os.getpid(),
            "registered_at": time.time(),
        }

    @staticmethod
    def holder_gone(rec: dict) -> bool:
        """Expired, or registered by a local pid that no longer exists."""
        if float(rec.get("expires", 0.0)) <= time.time():
            return True
        from tasksrunner.invoke.resolver import NameResolver
        return NameResolver.local_pid_dead(
            rec.get("host"), rec.get("pid"), rec.get("registered_at"))

    async def peek(self) -> dict | None:
        item = await self._store.get(self.key)
        return None if item is None else item.value

    async def acquire(self, owner: str) -> int | None:
        """Take or renew the lease. Returns the (possibly bumped) epoch
        on success, None if another live holder has it or we lost the
        takeover race."""
        item = await self._store.get(self.key)
        if item is None:
            # creation race: write, then verify we are the one who won
            # (last write wins the upsert; exactly one owner survives)
            await self._store.set(self.key, self._record(owner, 1))
            check = await self._store.get(self.key)
            if check is not None and check.value.get("owner") == owner:
                return 1
            return None
        rec = item.value
        epoch = int(rec.get("epoch", 0))
        if rec.get("owner") == owner:
            try:
                await self._store.set(self.key, self._record(owner, epoch),
                                      etag=item.etag)
                return epoch
            except EtagMismatch:
                return None
        if not self.holder_gone(rec):
            return None
        try:
            await self._store.set(self.key, self._record(owner, epoch + 1),
                                  etag=item.etag)
            return epoch + 1
        except EtagMismatch:
            return None

    async def renew(self, owner: str) -> bool:
        item = await self._store.get(self.key)
        if item is None or item.value.get("owner") != owner:
            return False
        try:
            await self._store.set(
                self.key,
                self._record(owner, int(item.value.get("epoch", 0))),
                etag=item.etag)
            return True
        except EtagMismatch:
            return False

    async def release(self, owner: str) -> None:
        """Expire our own lease in place (epoch preserved, so the next
        acquisition still bumps it); a no-op if we don't hold it."""
        item = await self._store.get(self.key)
        if item is None or item.value.get("owner") != owner:
            return
        rec = dict(item.value)
        rec["expires"] = 0.0
        try:
            await self._store.set(self.key, rec, etag=item.etag)
        except EtagMismatch:
            pass


class LocalLink:
    """Leader's handle on one in-process follower member.

    The protocol surface — ``append(records) -> hwm``,
    ``install(snapshot)``, ``position() -> (hwm, epoch)`` — is exactly
    what the mesh link (state/replmesh.py) implements over TCP, so the
    replicator is transport-agnostic. A chaos policy attached to the
    lane (``kind:Chaos`` ``targets.replication``) injects before every
    shipment, which is how blackhole/latency failover drills sever one
    specific leader→follower stream."""

    def __init__(self, node: "ReplicationNode"):
        self._node = node
        self.member = node.node_id
        self.chaos = None  # ChaosPolicy | None, set via attach_chaos

    async def _chaos_gate(self) -> None:
        if self.chaos is not None:
            status = await self.chaos.before_call()
            if status is not None:
                self.chaos.raise_for_status(status)

    async def append(self, records: list[dict]) -> int:
        await self._chaos_gate()
        return await self._node.apply_records(records)

    async def install(self, snapshot: dict) -> None:
        await self._chaos_gate()
        await self._node.install_snapshot(snapshot)

    async def position(self) -> tuple[int, int]:
        return self._node.position()


class _Pending:
    """One committed-on-leader record awaiting its ack quorum."""

    __slots__ = ("record", "resolve", "fail", "acks", "deadline", "admitted")

    def __init__(self, record: dict, resolve: Callable[[], None],
                 fail: Callable[[BaseException], None], first_ack: str,
                 deadline: float):
        self.record = record
        self.resolve = resolve
        self.fail = fail
        self.acks = {first_ack}
        self.deadline = deadline
        # wall-clock admit time: the repl-ack span measures commit →
        # quorum, the durability tail the caller actually waited out
        self.admitted = time.time()


class ShardReplicator:
    """The leader-side replication session for one shard.

    Attached to the leader's :class:`SqliteStateStore` as ``_repl``:
    the flusher calls :meth:`on_commit` (writer thread) after every
    replicated batch, and the callers' futures resolve only when the
    record reaches ``ack_quorum`` members — or fail with
    :class:`ReplicationQuorumError` at the ack timeout, or
    :class:`ReplicaFencedError` if leadership was lost meanwhile.

    One shipper task per follower streams the log from that member's
    acked position; a follower that answers with a gap gets a log
    catch-up, a diverged or pruned-past follower gets a full snapshot.
    A fencing signal from any follower (it saw a higher epoch) fences
    this whole session: all pending and future writes fail closed.
    """

    def __init__(self, node: "ReplicationNode", *, epoch: int,
                 ack_quorum: int, ack_timeout: float):
        self._node = node
        self._store = node.store
        self._loop = asyncio.get_running_loop()
        self.epoch = int(epoch)
        self.ack_quorum = max(1, int(ack_quorum))
        self.ack_timeout = float(ack_timeout)
        self.fenced = False
        self._closed = False
        self._pending: "collections.OrderedDict[int, _Pending]" = \
            collections.OrderedDict()
        self._member_hwm: dict[str, int] = {}
        self._wake: dict[str, asyncio.Event] = {}
        self._tasks: list[asyncio.Task] = []

    def start(self) -> None:
        for member, link in self._node.links.items():
            self._member_hwm[member] = 0
            self._wake[member] = asyncio.Event()
            self._wake[member].set()  # immediate catch-up pass
            self._tasks.append(
                asyncio.ensure_future(self._ship_loop(member, link)))
        self._tasks.append(asyncio.ensure_future(self._timeout_loop()))

    # -- flusher side (writer thread) -------------------------------------

    def on_commit(self, record: dict, resolve: Callable[[], None],
                  fail: Callable[[BaseException], None]) -> None:
        """Called by the store after a replicated batch COMMITs locally.
        ``resolve``/``fail`` complete the batch's caller futures (both
        are thread-safe)."""
        if self.fenced:
            fail(ReplicaFencedError(
                f"state store {self._store.name!r}: leadership lost "
                "(epoch fenced); the write was not acked"))
            return
        if self.ack_quorum <= 1:
            # leader-only durability: ack now, ship in the background
            resolve()
            resolve = None  # type: ignore[assignment]
        try:
            self._loop.call_soon_threadsafe(self._admit, record, resolve, fail)
        except RuntimeError:  # loop closed (shutdown race)
            if resolve is not None:
                fail(StateError(
                    f"state store {self._store.name!r}: replication "
                    "session closed before the write could be acked"))

    # -- loop side ---------------------------------------------------------

    def _admit(self, record: dict, resolve: Callable[[], None] | None,
               fail: Callable[[BaseException], None]) -> None:
        if resolve is not None:
            if self._closed or self.fenced:
                fail(ReplicaFencedError(
                    f"state store {self._store.name!r}: leadership lost "
                    "(epoch fenced); the write was not acked")
                    if self.fenced else
                    StateError(f"state store {self._store.name!r}: "
                               "replication session closed"))
                return
            self._pending[record["seq"]] = _Pending(
                record, resolve, fail, self._node.node_id,
                time.monotonic() + self.ack_timeout)
        for evt in self._wake.values():
            evt.set()

    def _on_ack(self, member: str, hwm: int) -> None:
        done: list[int] = []
        for seq, p in self._pending.items():
            if seq > hwm:
                break
            p.acks.add(member)
            if len(p.acks) >= self.ack_quorum:
                done.append(seq)
        now = time.time()
        for seq in done:
            p = self._pending.pop(seq)
            p.resolve()
            _tp_span(p.record.get("tp"), name="repl-ack", kind="internal",
                     status=200, start=p.admitted,
                     duration=now - p.admitted,
                     attrs={"seq": seq, "acks": len(p.acks),
                            "quorum": self.ack_quorum,
                            "store": self._store.name})

    async def _ship_loop(self, member: str, link) -> None:
        labels = self._node.metric_labels
        backoff = 0.05
        primed = False
        force_snapshot = False
        while not self._closed and not self.fenced:
            evt = self._wake[member]
            evt.clear()
            try:
                if not primed:
                    hwm, f_epoch = await link.position()
                    self._member_hwm[member] = hwm
                    # log-matching check (Raft §5.3): the follower's
                    # log is a prefix of ours only if OUR entry at ITS
                    # hwm carries the same epoch. A zombie ex-leader
                    # that committed past our barrier fails this and
                    # gets a snapshot, dropping its divergent suffix.
                    if hwm > 0:
                        ours = await self._run_store(
                            self._store.read_repl_epoch_at, hwm)
                        if ours != f_epoch:
                            force_snapshot = True
                    primed = True
                leader_hwm, _ = self._store.repl_position()
                sent = self._member_hwm[member]
                metrics.set_gauge("repl_follower_lag_records",
                                  max(0, leader_hwm - sent),
                                  member=member, **labels)
                if not force_snapshot and sent >= leader_hwm:
                    await evt.wait()
                    continue
                records = (None if force_snapshot
                           else await self._read_log(sent))
                if records is None:
                    # pruned past the gap, or the follower diverged:
                    # reinstall from a full snapshot
                    snap = await self._run_store(
                        self._store.read_repl_snapshot)
                    await link.install(snap)
                    acked = int(snap["hwm"])
                    force_snapshot = False
                else:
                    ship_wall = time.time()
                    ship_t0 = time.monotonic()
                    acked = await link.append(records)
                    metrics.inc("repl_records_total", len(records),
                                member=member, **labels)
                    _tp_span(_batch_tp(records), name="repl-ship",
                             kind="producer", status=200, start=ship_wall,
                             duration=time.monotonic() - ship_t0,
                             attrs={"member": member,
                                    "records": len(records), **labels})
                self._member_hwm[member] = acked
                self._on_ack(member, acked)
                backoff = 0.05
            except ReplicationGapError as exc:
                if exc.diverged:
                    force_snapshot = True
                else:
                    self._member_hwm[member] = exc.hwm
            except ReplicaFencedError:
                self._fence()
                return
            except asyncio.CancelledError:
                raise
            except Exception:
                # transport failure, chaos injection, follower down:
                # back off and retry — the ack-timeout loop owns
                # failing the pending writes if this never recovers
                primed = False
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 0.5)

    async def _timeout_loop(self) -> None:
        interval = max(0.02, min(self.ack_timeout / 4, 1.0))
        while not self._closed and not self.fenced:
            await asyncio.sleep(interval)
            now = time.monotonic()
            expired = [seq for seq, p in self._pending.items()
                       if p.deadline <= now]
            for seq in expired:
                p = self._pending.pop(seq)
                p.fail(ReplicationQuorumError(
                    f"state store {self._store.name!r}: record seq {seq} "
                    f"did not reach ack quorum {self.ack_quorum} within "
                    f"{self.ack_timeout}s — the replica set is degraded"))

    async def _read_log(self, after_seq: int) -> list[dict] | None:
        return await self._run_store(self._store.read_repl_log, after_seq)

    async def _run_store(self, fn, *args):
        return await self._loop.run_in_executor(
            self._store._write_exec, fn, *args)

    def _fence(self) -> None:
        """Leadership is gone: fail everything pending, refuse
        everything future. The store keeps this fenced session attached
        so late flushes fail fast until a new leader resyncs us."""
        if self.fenced:
            return
        self.fenced = True
        metrics.inc("repl_fenced_total", **self._node.metric_labels)
        pending, self._pending = self._pending, collections.OrderedDict()
        err = ReplicaFencedError(
            f"state store {self._store.name!r}: leadership lost "
            "(epoch fenced); the write was not acked")
        for p in pending.values():
            p.fail(err)
        self._node._on_fenced()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for t in self._tasks:
            t.cancel()
        for evt in self._wake.values():
            evt.set()
        pending, self._pending = self._pending, collections.OrderedDict()
        err = StateError(
            f"state store {self._store.name!r}: replication session closed")
        for p in pending.values():
            p.fail(err)


class ReplicationNode:
    """One member of a shard's replica set: a full SQLite engine plus
    a role loop that renews the shard lease while leader and contends
    for it while follower."""

    def __init__(self, name: str, path: str | pathlib.Path, *,
                 member: int, shard: int, meta_store: StateStore,
                 lease_seconds: float | None = None,
                 ack_quorum: int = 2, ack_timeout: float | None = None,
                 log_retain: int | None = None,
                 group_commit: bool = True, cache_size: int = 0,
                 shard_label: int | None = None):
        self.name = name
        self.member = int(member)
        self.node_id = f"r{member}"
        self.shard = int(shard)
        self.ack_quorum = int(ack_quorum)
        self.ack_timeout = (float(ack_timeout) if ack_timeout
                            else ack_timeout_default())
        self.store = SqliteStateStore(
            name, path, replication=True,
            repl_log_retain=log_retain or log_retain_default(),
            group_commit=group_commit, cache_size=cache_size,
            shard=shard_label)
        self.lease = Lease(meta_store, f"repl-lease||{name}||{shard}",
                           lease_seconds=lease_seconds)
        #: links to the OTHER members, wired by the builder
        self.links: dict[str, LocalLink] = {}
        self.replicator: ShardReplicator | None = None
        #: simulated host death (tests/chaos): every inbound protocol
        #: call raises OSError, the role loop goes inert
        self.crashed = False
        #: zombie drill switch: a "paused" leader stops renewing (as a
        #: GC-stalled or partitioned process would) but keeps accepting
        #: writes until fenced
        self.renewal_paused = False
        #: set when this member lost leadership with a possibly
        #: divergent log suffix; it must NOT re-promote until the new
        #: leader resynced it (snapshot or higher-epoch records), or
        #: its unacked suffix could overwrite quorum-acked writes
        self._needs_resync = False
        self._running = False
        self._task: asyncio.Task | None = None

    @property
    def metric_labels(self) -> dict:
        return {"store": self.name, "shard": self.shard}

    @property
    def is_leader(self) -> bool:
        return self.replicator is not None and not self.replicator.fenced

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._task is None:
            self._running = True
            self._task = asyncio.ensure_future(self._role_loop())

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None
        if self.is_leader and not self.crashed:
            try:
                await self.lease.release(self.node_id)
            except Exception:  # meta store may already be gone
                logger.debug("lease release failed for %s/%s %s",
                             self.name, self.shard, self.node_id,
                             exc_info=True)
        if self.replicator is not None:
            self.replicator.close()
            self.replicator = None
            self.store._repl = None

    def crash(self) -> None:
        """Simulate host loss: protocol calls fail, the role loop goes
        inert, and the lease is left to expire — exactly what a real
        ``kill -9`` leaves behind."""
        self.crashed = True
        if self.replicator is not None:
            self.replicator.close()
            self.replicator = None
            self.store._repl = None
            self._needs_resync = True

    def revive(self) -> None:
        self.crashed = False

    # -- role loop ---------------------------------------------------------

    async def _role_loop(self) -> None:
        interval = self.lease.lease_seconds / 3.0
        if self.member:
            # cold-boot bias: member 0 contends first so the initial
            # election is deterministic; irrelevant after any failover
            await asyncio.sleep(min(interval, 0.03 * self.member))
        while self._running:
            try:
                if not self.crashed:
                    await self._tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.debug("replication %s/%s %s: role tick failed",
                             self.name, self.shard, self.node_id,
                             exc_info=True)
            await asyncio.sleep(interval)

    async def _tick(self) -> None:
        if self.is_leader:
            if self.renewal_paused:
                return  # zombie drill: let the lease run out
            if not await self.lease.renew(self.node_id):
                # someone took the lease from us — fence locally NOW
                # rather than waiting for a follower to refuse a record
                if self.replicator is not None:
                    self.replicator._fence()
        else:
            await self._maybe_promote()

    async def _maybe_promote(self) -> None:
        rec = await self.lease.peek()
        if rec is not None and not Lease.holder_gone(rec):
            return
        if self._needs_resync:
            return
        # don't take leadership while a reachable peer is ahead of us:
        # our stream would truncate its acked suffix. An unreachable
        # peer can't object — it will be resynced when it returns.
        my_hwm, _ = self.store.repl_position()
        for link in self.links.values():
            try:
                hwm, _ = await link.position()
            except Exception:
                continue
            if hwm > my_hwm:
                return
        epoch = await self.lease.acquire(self.node_id)
        if epoch is None:
            return
        promoted = False
        try:
            if not self._needs_resync:
                await self._become_leader(epoch)
                promoted = True
        finally:
            if not promoted:
                # fenced while the position probes / acquire were in
                # flight (_on_fenced): our log may now be behind — hand
                # the lease back instead of leading with a stale
                # stream (shielded so a cancellation mid-promotion
                # still surrenders instead of squatting on the lease)
                await asyncio.shield(self.lease.release(self.node_id))

    async def _become_leader(self, epoch: int) -> None:
        if self.replicator is not None:
            self.replicator.close()
        loop = asyncio.get_running_loop()
        # the leadership barrier: an empty record at the new epoch,
        # durable before any data is accepted at this epoch
        await loop.run_in_executor(
            self.store._write_exec, self.store.append_repl_barrier, epoch)
        self.replicator = ShardReplicator(
            self, epoch=epoch, ack_quorum=self.ack_quorum,
            ack_timeout=self.ack_timeout)
        self.store._repl = self.replicator
        self.replicator.start()
        self._needs_resync = False
        metrics.set_gauge("repl_epoch", epoch, **self.metric_labels)
        if epoch > 1:
            metrics.inc("repl_failover_total", **self.metric_labels)
        logger.info("replication: %s shard %d: %s is leader (epoch %d)",
                    self.name, self.shard, self.node_id, epoch)

    def _on_fenced(self) -> None:
        self._needs_resync = True

    # -- follower protocol (called via links / mesh server) ----------------

    async def apply_records(self, records: list[dict]) -> int:  # tasklint: fenced-lane
        if self.crashed:
            raise OSError(f"replica member {self.node_id} is down")
        loop = asyncio.get_running_loop()
        _, prev_epoch = self.store.repl_position()
        apply_wall = time.time()
        apply_t0 = time.monotonic()
        hwm = await loop.run_in_executor(
            self.store._write_exec, self.store.apply_repl_records, records)
        _tp_span(_batch_tp(records), name="repl-apply", kind="consumer",
                 status=200, start=apply_wall,
                 duration=time.monotonic() - apply_t0,
                 attrs={"member": self.node_id, "records": len(records),
                        **self.metric_labels})
        _, epoch = self.store.repl_position()
        if epoch > prev_epoch:
            # a new leader's records applied cleanly: our log is a
            # prefix of its log — safe to contend for leadership again
            self._accept_new_leader()
        return hwm

    async def install_snapshot(self, snapshot: dict) -> None:
        if self.crashed:
            raise OSError(f"replica member {self.node_id} is down")
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            self.store._write_exec, self.store.install_repl_snapshot,
            snapshot)
        self._accept_new_leader()

    def position(self) -> tuple[int, int]:
        if self.crashed:
            raise OSError(f"replica member {self.node_id} is down")
        return self.store.repl_position()

    def _accept_new_leader(self) -> None:
        if self.replicator is not None:
            self.replicator.close()
            self.replicator = None
            self.store._repl = None
        self._needs_resync = False


class ReplicaSetStore(StateStore):
    """One shard's replica set behind the plain ``StateStore`` API.

    Writes route to whichever member currently holds the lease, with
    one transparent retry after a fencing failure (the write was
    provably not applied). Reads go to the leader, or — with
    ``followerReads`` — to a follower whose lag is within the bound.
    Members start lazily on first use because drivers construct
    components without a running event loop."""

    supports_query = True

    def __init__(self, name: str, nodes: list[ReplicationNode], *,
                 shard: int = 0, follower_reads: bool = False,
                 max_lag: int | None = None,
                 meta_store: StateStore | None = None,
                 owns_meta: bool = False):
        super().__init__(name)
        self.nodes = nodes
        self.shard = int(shard)
        self.follower_reads = bool(follower_reads)
        self.max_lag = int(max_lag) if max_lag else max_lag_default()
        self._meta = meta_store
        self._owns_meta = bool(owns_meta)
        self._started = False
        self._rr = 0

    # -- membership --------------------------------------------------------

    async def _ensure_started(self) -> None:
        if not self._started:
            self._started = True
            for node in self.nodes:
                await node.start()

    async def _leader_node(self) -> ReplicationNode:
        await self._ensure_started()
        lease_s = self.nodes[0].lease.lease_seconds
        deadline = time.monotonic() + 3.0 * lease_s + 1.0
        while True:
            for node in self.nodes:
                if node.is_leader and not node.crashed:
                    return node
            if time.monotonic() > deadline:
                raise NotLeaderError(
                    f"state store {self.name!r} shard {self.shard}: no "
                    "member holds the shard lease")
            await asyncio.sleep(min(0.02, lease_s / 10.0))

    def leader_member(self) -> str | None:
        for node in self.nodes:
            if node.is_leader:
                return node.node_id
        return None

    def attach_chaos(self, policies) -> None:
        """Bind ``kind:Chaos`` replication-lane faults to the member
        links (called by chaos/wrappers.py at component build)."""
        for node in self.nodes:
            for member_id, link in node.links.items():
                link.chaos = policies.for_replication(
                    self.name, self.shard, member_id)

    def member_lag(self, member: str) -> int | None:
        """Records ``member`` trails the current leader by, or None
        when no live leader session exists to measure against. The
        elastic-placement catch-up loop polls this before attempting a
        handoff — shipping is continuous, so the lag converges to 0 on
        its own once the writer quiesces."""
        leader = next(
            (n for n in self.nodes if n.is_leader and not n.crashed), None)
        if leader is None or leader.replicator is None:
            return None
        if leader.node_id == member:
            # the member won a takeover mid-catch-up (leader crash
            # degraded to ordinary failover): it holds the quorum hwm,
            # so it trails nobody — measuring it against its own
            # follower table would read 'infinitely behind' forever
            return 0
        hwm, _ = leader.store.repl_position()
        return max(0, hwm - leader.replicator._member_hwm.get(member, 0))

    async def transfer_leadership(self, member: str, *,
                                  timeout: float | None = None) -> int:
        """Fenced leadership handoff to ``member`` — the live-migration
        transport primitive (PR 20). The caller (the sharded facade's
        fenced flip) has already quiesced writes, so the leader's hwm
        is static; this method (1) waits for the target's log to reach
        it — the ordinary snapshot+log resync ladder does the moving —
        (2) retires the old leader's session cleanly (nothing pending:
        the writer is quiesced and drained), (3) hands the lease over,
        which bumps the epoch exactly like a takeover, and (4) promotes
        the target through the normal barrier path. A leader crash in
        the middle degrades to the ordinary failover machinery: the
        lease expires, a caught-up member promotes, and every acked
        write survives because it reached the quorum.
        """
        await self._ensure_started()
        target = next(
            (n for n in self.nodes if n.node_id == member), None)
        if target is None:
            raise ReplicationError(
                f"state store {self.name!r} shard {self.shard}: no "
                f"member {member!r}")
        if target.crashed:
            raise ReplicationError(
                f"state store {self.name!r} shard {self.shard}: member "
                f"{member!r} is down")
        leader = await self._leader_node()
        if leader is target:
            _, epoch = leader.store.repl_position()
            return epoch
        deadline = time.monotonic() + (
            float(timeout) if timeout else 2.0 * leader.ack_timeout)
        while True:
            if leader.crashed or not leader.is_leader:
                raise NotLeaderError(
                    f"state store {self.name!r} shard {self.shard}: "
                    f"leadership moved mid-transfer — retry against the "
                    f"new leader")
            leader_hwm, _ = leader.store.repl_position()
            try:
                hwm, _ = target.position()
            except OSError as exc:
                raise ReplicationError(
                    f"state store {self.name!r} shard {self.shard}: "
                    f"transfer target {member!r} went down") from exc
            if hwm >= leader_hwm:
                break
            if time.monotonic() > deadline:
                raise ReplicationError(
                    f"state store {self.name!r} shard {self.shard}: "
                    f"{member!r} still trails by "
                    f"{leader_hwm - hwm} records at the transfer deadline")
            await asyncio.sleep(0.01)
        # retire the old session before surrendering the lease: the
        # writer is quiesced, so nothing is pending to fail — this is
        # the graceful sibling of the crash path's _fence()
        if leader.replicator is not None:
            leader.replicator.close()
        leader.replicator = None
        leader.store._repl = None
        await leader.lease.release(leader.node_id)
        epoch = None
        for _ in range(3):
            epoch = await target.lease.acquire(target.node_id)
            if epoch is not None:
                break
            rec = await target.lease.peek()
            if (rec is not None and rec.get("owner") != target.node_id
                    and not Lease.holder_gone(rec)):
                raise NotLeaderError(
                    f"state store {self.name!r} shard {self.shard}: "
                    f"{rec.get('owner')!r} won the takeover race during "
                    f"the transfer to {member!r}")
        if epoch is None:
            raise NotLeaderError(
                f"state store {self.name!r} shard {self.shard}: could "
                f"not acquire the shard lease for {member!r}")
        await target._become_leader(epoch)
        return epoch

    # -- writes ------------------------------------------------------------

    async def _write(self, fn) -> Any:
        await self._ensure_started()
        last: BaseException | None = None
        for attempt in (0, 1):
            node = await self._leader_node()
            try:
                return await fn(node)
            except (NotLeaderError, ReplicaFencedError) as exc:
                # fenced means NOT applied and NOT acked: one
                # re-resolve + retry against the new leader is safe
                last = exc
        raise last  # type: ignore[misc]

    async def set(self, key: str, value: Any, *,
                  etag: str | None = None) -> str:
        return await self._write(lambda n: n.store.set(key, value, etag=etag))

    async def delete(self, key: str, *, etag: str | None = None) -> bool:
        return await self._write(lambda n: n.store.delete(key, etag=etag))

    async def transact(self, ops: list[TransactionOp]) -> None:
        return await self._write(lambda n: n.store.transact(ops))

    async def stage_transact(self, ops: list[TransactionOp]):
        """Two-phase hook for the sharded facade: stage on the current
        leader (no retry — a staged transaction holds the commit slot)."""
        node = await self._leader_node()
        return await node.store.stage_transact(ops)

    # -- reads -------------------------------------------------------------

    async def _read_node(self) -> ReplicationNode:
        await self._ensure_started()
        leader = await self._leader_node()
        if not self.follower_reads:
            return leader
        leader_hwm, _ = leader.store.repl_position()
        n = len(self.nodes)
        for i in range(n):
            node = self.nodes[(self._rr + i) % n]
            if node is leader or node.crashed:
                continue
            hwm, _ = node.store.repl_position()
            if leader_hwm - hwm <= self.max_lag:
                self._rr = (self._rr + i + 1) % n
                return node
        return leader  # every follower beyond the bound → redirect

    async def get(self, key: str) -> StateItem | None:
        node = await self._read_node()
        return await node.store.get(key)

    async def bulk_get(self, keys: list[str]) -> list[StateItem | None]:
        node = await self._read_node()
        return await node.store.bulk_get(keys)

    async def query(self, query: dict, *, key_prefix: str = "") -> QueryResponse:
        node = await self._read_node()
        return await node.store.query(query, key_prefix=key_prefix)

    async def keys(self, *, prefix: str = "") -> list[str]:
        node = await self._read_node()
        return await node.store.keys(prefix=prefix)

    async def read_follower(self, key: str, *,
                            member: str | None = None) -> StateItem | None:
        """Read from a specific follower, enforcing the lag bound the
        hard way: beyond ``maxLagRecords`` this raises
        :class:`StaleReadError` instead of redirecting — the contract
        for callers that addressed the member deliberately."""
        await self._ensure_started()
        leader = await self._leader_node()
        leader_hwm, _ = leader.store.repl_position()
        for node in self.nodes:
            if node is leader:
                continue
            if member is not None and node.node_id != member:
                continue
            hwm, _ = node.store.repl_position()
            if leader_hwm - hwm > self.max_lag:
                raise StaleReadError(
                    f"state store {self.name!r}: follower {node.node_id} "
                    f"lags {leader_hwm - hwm} records "
                    f"(> maxLagRecords {self.max_lag})")
            return await node.store.get(key)
        raise StaleReadError(
            f"state store {self.name!r}: no follower matches {member!r}")

    # -- lifecycle ---------------------------------------------------------

    async def aclose(self) -> None:
        for node in self.nodes:
            await node.stop()
        for node in self.nodes:
            node.store.close()
        if self._owns_meta and self._meta is not None:
            self._meta.close()

    def close(self) -> None:
        """Sync teardown is the crash-equivalent path: no lease
        release (it expires on its own), just stop the machinery."""
        for node in self.nodes:
            node._running = False
            if node._task is not None:
                node._task.cancel()
                node._task = None
            if node.replicator is not None:
                node.replicator.close()
                node.replicator = None
                node.store._repl = None
        for node in self.nodes:
            node.store.close()
        if self._owns_meta and self._meta is not None:
            self._meta.close()


def _member_path(path: str, shard: int, member: int, shards: int) -> str:
    """Member ``m`` of shard ``s``: member 0 keeps the unreplicated
    layout's exact file (``tasks.db`` / ``tasks-shardN.db``) so
    enabling replication on existing data promotes the existing file
    to the seed copy; followers add an ``-rM`` suffix. ``":memory:"``
    passes through — each member's connection gets a private database,
    which is exactly one private replica."""
    if path == ":memory:":
        return path
    base = path if shards == 1 else _shard_path(path, shard)
    if member == 0:
        return base
    p = pathlib.Path(base)
    return str(p.with_name(f"{p.stem}-r{member}{p.suffix}"))


def _meta_path(path: str) -> str:
    if path == ":memory:":
        return ":memory:"
    p = pathlib.Path(path)
    return str(p.with_name(f"{p.stem}-repl-meta{p.suffix}"))


def build_replicated_store(
        name: str, path: str | pathlib.Path = ":memory:", *,
        shards: int = 1, replicas: int, ack_quorum: int | None = None,
        hash_seed: str = "", group_commit: bool = True, cache_size: int = 0,
        follower_reads: bool = False, max_lag: int | None = None,
        lease_seconds: float | None = None, ack_timeout: float | None = None,
        log_retain: int | None = None) -> StateStore:
    """Assemble the replicated state plane for one component: per
    shard, a replica set of ``replicas`` members sharing one meta store
    (the lease table); across shards, the PR 5 rendezvous facade over
    the per-shard replica sets. ``ack_quorum`` defaults to a majority
    (RF 2 → 2, RF 3 → 2): zero lost acked writes as long as any
    majority survives."""
    from tasksrunner.state.sharding import MAX_SHARDS, ShardedStateStore
    if replicas < 1 or replicas > MAX_REPLICAS:
        raise ComponentError(
            f"state store {name!r}: replicas must be in 1..{MAX_REPLICAS}, "
            f"not {replicas}")
    if shards < 1 or shards > MAX_SHARDS:
        raise ComponentError(
            f"state store {name!r}: shards must be in 1..{MAX_SHARDS}, "
            f"not {shards}")
    if replicas == 1:
        # RF 1 is exactly the unreplicated engine — the bench baseline
        from tasksrunner.state.sqlite import build_sharded_store
        if shards == 1:
            return SqliteStateStore(name, path, group_commit=group_commit,
                                    cache_size=cache_size)
        return build_sharded_store(name, path, shards=shards,
                                   hash_seed=hash_seed,
                                   group_commit=group_commit,
                                   cache_size=cache_size)
    quorum = int(ack_quorum) if ack_quorum else replicas // 2 + 1
    quorum = max(1, min(quorum, replicas))
    per_cache = (max(1, cache_size // shards)
                 if cache_size and shards > 1 else cache_size)
    meta = SqliteStateStore(f"{name}.repl-meta", _meta_path(str(path)))

    def _make_set(s: int, *, owns_meta: bool) -> ReplicaSetStore:
        nodes = [
            ReplicationNode(
                name, _member_path(str(path), s, m, shards),
                member=m, shard=s, meta_store=meta,
                lease_seconds=lease_seconds, ack_quorum=quorum,
                ack_timeout=ack_timeout, log_retain=log_retain,
                group_commit=group_commit, cache_size=per_cache,
                shard_label=s if shards > 1 else None)
            for m in range(replicas)
        ]
        for node in nodes:
            node.links = {
                other.node_id: LocalLink(other)
                for other in nodes if other is not node
            }
        return ReplicaSetStore(
            name, nodes, shard=s, follower_reads=follower_reads,
            max_lag=max_lag, meta_store=meta, owns_meta=owns_meta)

    sets = [_make_set(s, owns_meta=(s == shards - 1))
            for s in range(shards)]
    if shards == 1:
        return sets[0]
    facade = ShardedStateStore(name, sets, hash_seed=hash_seed)
    # online split (PR 20) mints replica set N+1 through the same
    # assembly; meta ownership stays with the original last set, which
    # a split never retires
    facade._child_factory = lambda s: _make_set(s, owns_meta=False)
    return facade
