"""Sharded state plane: hash-partitioned multi-writer stores.

PR 1's group-commit made one SQLite file fast, but a single write
queue + flusher is still the throughput ceiling: every write in the
component serializes through one writer thread and one WAL. This
module partitions a state component across N independent child stores
— N write queues, N writer threads, N WALs — behind one ``StateStore``
facade, the same shape as SNIPPETS.md's ``shard_map`` exemplars
(shard by the leading dim, mesh of independent executors).

Routing — rendezvous (highest-random-weight) hashing
----------------------------------------------------

Each shard ``i`` gets a salt derived from ``(hashSeed, i)``; a key
lands on the shard whose ``mix(key_hash ^ salt_i)`` is largest.
Compared to ``hash(key) % N`` this buys the reshard property for free:
growing ``N → N+1`` leaves salts ``0..N-1`` unchanged, so a key moves
only if the *new* shard wins its rendezvous — an expected ``1/(N+1)``
of the key space, the provable minimum for a balanced reshard (modulo
hashing, by contrast, moves ``1 - 1/lcm(N, N+1)`` ≈ all of it).
Assignment depends only on ``(key, hashSeed, shards)`` — no state, no
ring file — so every replica and every restart routes identically.

Cross-shard transactions — ordered two-phase commit
---------------------------------------------------

``transact`` over keys that all land on one shard stays exactly PR 1's
single ``BEGIN IMMEDIATE … COMMIT``. Ops spanning shards run two-phase:

1. **Stage** on every touched shard in ascending shard-index order:
   each shard's writer thread opens its transaction, validates etags,
   applies the ops, and parks holding the commit slot. Ordered
   acquisition makes concurrent cross-shard transactions deadlock-free
   (any holder of shard ``i``'s slot already holds all its lower
   shards, so the wait graph cannot cycle). A stage failure
   (``EtagMismatch``, lock deadline) rolls back every staged shard and
   re-raises — nothing committed, the all-or-nothing contract intact.
2. **Commit** in the same ascending order. If the *first* commit
   fails, the rest roll back — still atomic. If a commit fails after
   one or more shards already committed, atomicity is gone and the
   facade raises :class:`~tasksrunner.errors.CrossShardAtomicityError`
   naming the committed/uncommitted split — the documented ambiguity
   window of two-phase commit without a coordinator log. Callers that
   cannot tolerate it should keep transaction keys on one shard (same
   rendezvous input, e.g. a shared key prefix routed via a designated
   key) or treat the error as "repair by re-read".

While a shard's transaction is staged its writer thread is parked, so
queued group-commit flushes on that shard wait behind the decision —
the commit slot IS the writer thread, no second lock to leak.
"""

from __future__ import annotations

import asyncio
import hashlib
import heapq
from typing import Any, Sequence

from tasksrunner.errors import (
    ComponentError, CrossShardAtomicityError, QueryError, StateError,
)
from tasksrunner.state.base import (
    QueryResponse, StateItem, StateStore, TransactionOp,
)
from tasksrunner.state.query import paginate, sort_items, validate_filter

_MASK64 = (1 << 64) - 1

#: hard ceiling on shard count — each shard is a file + 2-3 threads +
#: 2 sqlite connections; past this the fan-out costs more than it buys
MAX_SHARDS = 64


def _blake64(data: bytes) -> int:
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big")


def _mix64(x: int) -> int:
    """splitmix64 finalizer: full-avalanche mix of a 64-bit value, so
    one flipped bit of ``key_hash ^ salt`` reshuffles the whole
    rendezvous weight (bare xor would correlate weights across shards
    and skew the balance)."""
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & _MASK64
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & _MASK64
    return (x ^ (x >> 31)) & _MASK64


class ShardRouter:
    """Pure key → shard-index routing via rendezvous hashing.

    Stateless and deterministic in ``(shards, seed)``; reusable by any
    sharded component (the broker's partitioned topics are next).
    """

    __slots__ = ("shards", "seed", "_salts", "_cache")

    #: bounded key→shard memo: real key spaces revisit keys constantly
    #: and the rendezvous argmax is pure-Python work per lookup; the
    #: memo turns the hot-key path into one dict hit. Assignment is a
    #: pure function of (key, seed, shards), so cached entries can
    #: never go stale within a router instance.
    _CACHE_MAX = 65536

    def __init__(self, shards: int, seed: str = ""):
        if not isinstance(shards, int) or shards < 1:
            raise ComponentError(
                f"shards must be a positive integer, not {shards!r}")
        if shards > MAX_SHARDS:
            raise ComponentError(
                f"shards must be <= {MAX_SHARDS}, not {shards}")
        self.shards = shards
        self.seed = seed
        # salt i depends only on (seed, i): growing the shard count
        # appends salts without touching existing ones — the minimal-
        # movement property rests exactly here
        self._salts = tuple(
            _blake64(f"{seed}|{i}".encode("utf-8")) for i in range(shards))
        self._cache: dict[str, int] = {}

    def shard_of(self, key: str) -> int:
        if self.shards == 1:
            return 0
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        h = _blake64(key.encode("utf-8"))
        best_i = 0
        best_w = -1
        for i, salt in enumerate(self._salts):
            w = _mix64(h ^ salt)
            if w > best_w:
                best_w = w
                best_i = i
        if len(self._cache) >= self._CACHE_MAX:
            # rare full reset beats per-hit LRU bookkeeping: the memo
            # refills from the live key distribution in one pass
            self._cache.clear()
        self._cache[key] = best_i
        return best_i

    def spread(self, keys: Sequence[str]) -> list[int]:
        """Shard index per key; diagnostics and tests."""
        return [self.shard_of(k) for k in keys]


class ShardedStateStore(StateStore):
    """One ``StateStore`` facade over N child stores + a router.

    Children are full independent engines (own writer/flusher threads,
    WAL, checkpointer when SQLite-backed); the facade only routes,
    fans out, and merges. Cross-shard ``transact`` requires children
    implementing the ``stage_transact`` two-phase protocol (the sqlite
    engine does); single-shard transactions work on any child.
    """

    supports_query = True

    def __init__(self, name: str, shards: Sequence[StateStore], *,
                 hash_seed: str = ""):
        super().__init__(name)
        if not shards:
            raise ComponentError(f"sharded store {name!r} needs >= 1 shard")
        self._shards = list(shards)
        self.router = ShardRouter(len(self._shards), hash_seed)

    # -- routing -----------------------------------------------------------

    def shard_for(self, key: str) -> StateStore:
        return self._shards[self.router.shard_of(key)]

    @property
    def shard_count(self) -> int:
        return len(self._shards)

    # -- single-key ops: pure routing -------------------------------------

    async def get(self, key: str) -> StateItem | None:
        return await self.shard_for(key).get(key)

    async def set(self, key: str, value: Any, *, etag: str | None = None) -> str:
        return await self.shard_for(key).set(key, value, etag=etag)

    async def delete(self, key: str, *, etag: str | None = None) -> bool:
        return await self.shard_for(key).delete(key, etag=etag)

    # -- fan-out reads -----------------------------------------------------

    async def bulk_get(self, keys: list[str]) -> list[StateItem | None]:
        out: list[StateItem | None] = [None] * len(keys)
        by_shard: dict[int, list[int]] = {}
        for i, key in enumerate(keys):
            by_shard.setdefault(self.router.shard_of(key), []).append(i)
        async def _one(shard_idx: int, idxs: list[int]) -> None:
            items = await self._shards[shard_idx].bulk_get(
                [keys[i] for i in idxs])
            for i, item in zip(idxs, items):
                out[i] = item
        await asyncio.gather(
            *(_one(s, idxs) for s, idxs in by_shard.items()))
        return out

    async def keys(self, *, prefix: str = "") -> list[str]:
        per_shard = await asyncio.gather(
            *(s.keys(prefix=prefix) for s in self._shards))
        # children return sorted lists; k-way merge keeps the facade's
        # answer identical to the single-file engine's ORDER BY key
        return list(heapq.merge(*per_shard))

    async def query(self, query: dict, *, key_prefix: str = "") -> QueryResponse:
        """Scatter the filter, gather + merge, then sort/page at the
        facade. Children get the filter only — sort and page must see
        the *global* result set, so they run here on the merged items
        via the same ``state/query.py`` pipeline the memory engine
        uses; semantics stay contract-suite identical to one shard."""
        if not isinstance(query, dict):
            raise QueryError("query must be a JSON object")
        filt = query.get("filter")
        validate_filter(filt)
        per_shard = await asyncio.gather(
            *(s.query({"filter": filt}, key_prefix=key_prefix)
              for s in self._shards))
        items = list(heapq.merge(
            *(r.items for r in per_shard), key=lambda it: it.key))
        items = sort_items(items, query.get("sort"))
        items, token = paginate(items, query.get("page"))
        return QueryResponse(items=items, token=token)

    # -- transactions ------------------------------------------------------

    async def transact(self, ops: list[TransactionOp]) -> None:
        by_shard: dict[int, list[TransactionOp]] = {}
        for op in ops:
            by_shard.setdefault(self.router.shard_of(op.key), []).append(op)
        if len(by_shard) <= 1:
            # the hot path: all keys rendezvous to one shard — exactly
            # PR 1's single BEGIN IMMEDIATE..COMMIT, no staging at all
            for shard_idx, shard_ops in by_shard.items():
                await self._shards[shard_idx].transact(shard_ops)
            return
        await self._transact_cross_shard(by_shard)

    async def _transact_cross_shard(
            self, by_shard: dict[int, list[TransactionOp]]) -> None:
        order = sorted(by_shard)
        staged = []
        try:
            for shard_idx in order:
                child = self._shards[shard_idx]
                stage = getattr(child, "stage_transact", None)
                if stage is None:
                    raise StateError(
                        f"store {self.name!r}: cross-shard transactions "
                        f"need shards that support staged commits; shard "
                        f"{shard_idx} ({type(child).__name__}) does not")
                staged.append((shard_idx, await stage(by_shard[shard_idx])))
        except BaseException:
            # stage phase failed: nothing committed anywhere; unwind
            # every already-staged shard and surface the original error
            await self._rollback_staged(staged)
            raise
        committed: list[int] = []
        for pos, (shard_idx, txn) in enumerate(staged):
            try:
                await txn.commit()
            except BaseException as exc:
                await self._rollback_staged(staged[pos + 1:])
                if committed:
                    raise CrossShardAtomicityError(
                        f"store {self.name!r}: cross-shard transaction "
                        f"committed on shard(s) {committed} but failed on "
                        f"shard {shard_idx}; remaining shards rolled back "
                        f"— repair by re-reading the affected keys"
                    ) from exc
                raise
            committed.append(shard_idx)

    async def _rollback_staged(self, staged: list) -> None:
        for _shard_idx, txn in staged:
            await txn.rollback()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        first: BaseException | None = None
        for child in self._shards:
            try:
                child.close()
            except Exception as exc:
                if first is None:
                    first = exc
        if first is not None:
            raise first

    async def aclose(self) -> None:
        """Prefer the children's async teardown: replicated children
        (state/replication.py) release shard leases gracefully only on
        the async path — sync ``close()`` is the crash-equivalent."""
        first: BaseException | None = None
        for child in self._shards:
            try:
                await child.aclose()
            except Exception as exc:
                if first is None:
                    first = exc
        if first is not None:
            raise first
