"""Sharded state plane: hash-partitioned multi-writer stores.

PR 1's group-commit made one SQLite file fast, but a single write
queue + flusher is still the throughput ceiling: every write in the
component serializes through one writer thread and one WAL. This
module partitions a state component across N independent child stores
— N write queues, N writer threads, N WALs — behind one ``StateStore``
facade, the same shape as SNIPPETS.md's ``shard_map`` exemplars
(shard by the leading dim, mesh of independent executors).

Routing — rendezvous (highest-random-weight) hashing
----------------------------------------------------

Each shard ``i`` gets a salt derived from ``(hashSeed, i)``; a key
lands on the shard whose ``mix(key_hash ^ salt_i)`` is largest.
Compared to ``hash(key) % N`` this buys the reshard property for free:
growing ``N → N+1`` leaves salts ``0..N-1`` unchanged, so a key moves
only if the *new* shard wins its rendezvous — an expected ``1/(N+1)``
of the key space, the provable minimum for a balanced reshard (modulo
hashing, by contrast, moves ``1 - 1/lcm(N, N+1)`` ≈ all of it).
Assignment depends only on ``(key, hashSeed, shards)`` — no state, no
ring file — so every replica and every restart routes identically.

Cross-shard transactions — ordered two-phase commit
---------------------------------------------------

``transact`` over keys that all land on one shard stays exactly PR 1's
single ``BEGIN IMMEDIATE … COMMIT``. Ops spanning shards run two-phase:

1. **Stage** on every touched shard in ascending shard-index order:
   each shard's writer thread opens its transaction, validates etags,
   applies the ops, and parks holding the commit slot. Ordered
   acquisition makes concurrent cross-shard transactions deadlock-free
   (any holder of shard ``i``'s slot already holds all its lower
   shards, so the wait graph cannot cycle). A stage failure
   (``EtagMismatch``, lock deadline) rolls back every staged shard and
   re-raises — nothing committed, the all-or-nothing contract intact.
2. **Commit** in the same ascending order. If the *first* commit
   fails, the rest roll back — still atomic. If a commit fails after
   one or more shards already committed, atomicity is gone and the
   facade raises :class:`~tasksrunner.errors.CrossShardAtomicityError`
   naming the committed/uncommitted split — the documented ambiguity
   window of two-phase commit without a coordinator log. Callers that
   cannot tolerate it should keep transaction keys on one shard (same
   rendezvous input, e.g. a shared key prefix routed via a designated
   key) or treat the error as "repair by re-read".

While a shard's transaction is staged its writer thread is parked, so
queued group-commit flushes on that shard wait behind the decision —
the commit slot IS the writer thread, no second lock to leak.

Elastic placement — epoched routing over the frozen hash
--------------------------------------------------------

PR 20 layers a :class:`~tasksrunner.state.placement.PlacementMap` over
the router: a version (epoch) plus per-shard host assignment, flipped
atomically by the fenced handoff at the end of a live migration or an
online shard split. Every facade operation passes a short barrier
(``_op_gate``) that is open in steady state and closes only for the
final drain of a flip; callers that present a routing epoch
(``check_epoch``) get a 409-with-new-epoch redirect when stale. The
migration data path reuses whatever the children provide: replica-set
children hand leadership over the PR 9 record stream
(``transfer_leadership``), plain children stream keys through a
facade-level dirty-key tap. Growing the ring appends one HRW salt, so
a split moves an expected ``1/(N+1)`` of the key space — all of it TO
the new shard, never between survivors.
"""

from __future__ import annotations

import asyncio
import hashlib
import heapq
import logging
import time
from typing import Any, Callable, Sequence

from tasksrunner.errors import (
    ComponentError, CrossShardAtomicityError, PlacementEpochError,
    QueryError, StateError,
)
from tasksrunner.observability.metrics import metrics
from tasksrunner.state.base import (
    QueryResponse, StateItem, StateStore, TransactionOp,
)
from tasksrunner.state.placement import (
    PlacementMap, ShardHeatTracker, pause_budget_default,
)
from tasksrunner.state.query import paginate, sort_items, validate_filter

logger = logging.getLogger(__name__)

_MASK64 = (1 << 64) - 1

#: hard ceiling on shard count — each shard is a file + 2-3 threads +
#: 2 sqlite connections; past this the fan-out costs more than it buys
MAX_SHARDS = 64


def _blake64(data: bytes) -> int:
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big")


def _mix64(x: int) -> int:
    """splitmix64 finalizer: full-avalanche mix of a 64-bit value, so
    one flipped bit of ``key_hash ^ salt`` reshuffles the whole
    rendezvous weight (bare xor would correlate weights across shards
    and skew the balance)."""
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & _MASK64
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & _MASK64
    return (x ^ (x >> 31)) & _MASK64


class ShardRouter:
    """Pure key → shard-index routing via rendezvous hashing.

    Stateless and deterministic in ``(shards, seed)``; reusable by any
    sharded component (the broker's partitioned topics are next).
    """

    __slots__ = ("shards", "seed", "_salts", "_cache")

    #: bounded key→shard memo: real key spaces revisit keys constantly
    #: and the rendezvous argmax is pure-Python work per lookup; the
    #: memo turns the hot-key path into one dict hit. Assignment is a
    #: pure function of (key, seed, shards), so cached entries can
    #: never go stale within a router instance.
    _CACHE_MAX = 65536

    def __init__(self, shards: int, seed: str = ""):
        if not isinstance(shards, int) or shards < 1:
            raise ComponentError(
                f"shards must be a positive integer, not {shards!r}")
        if shards > MAX_SHARDS:
            raise ComponentError(
                f"shards must be <= {MAX_SHARDS}, not {shards}")
        self.shards = shards
        self.seed = seed
        # salt i depends only on (seed, i): growing the shard count
        # appends salts without touching existing ones — the minimal-
        # movement property rests exactly here
        self._salts = tuple(
            _blake64(f"{seed}|{i}".encode("utf-8")) for i in range(shards))
        self._cache: dict[str, int] = {}

    def shard_of(self, key: str) -> int:
        if self.shards == 1:
            return 0
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        h = _blake64(key.encode("utf-8"))
        best_i = 0
        best_w = -1
        for i, salt in enumerate(self._salts):
            w = _mix64(h ^ salt)
            if w > best_w:
                best_w = w
                best_i = i
        if len(self._cache) >= self._CACHE_MAX:
            # rare full reset beats per-hit LRU bookkeeping: the memo
            # refills from the live key distribution in one pass
            self._cache.clear()
        self._cache[key] = best_i
        return best_i

    def spread(self, keys: Sequence[str]) -> list[int]:
        """Shard index per key; diagnostics and tests."""
        return [self.shard_of(k) for k in keys]


class ShardedStateStore(StateStore):
    """One ``StateStore`` facade over N child stores + a router.

    Children are full independent engines (own writer/flusher threads,
    WAL, checkpointer when SQLite-backed); the facade only routes,
    fans out, and merges. Cross-shard ``transact`` requires children
    implementing the ``stage_transact`` two-phase protocol (the sqlite
    engine does); single-shard transactions work on any child.
    """

    supports_query = True

    def __init__(self, name: str, shards: Sequence[StateStore], *,
                 hash_seed: str = ""):
        super().__init__(name)
        if not shards:
            raise ComponentError(f"sharded store {name!r} needs >= 1 shard")
        self._shards = list(shards)
        self.hash_seed = hash_seed
        self.router = ShardRouter(len(self._shards), hash_seed)
        #: the epoched routing table (PR 20); replaced atomically by
        #: the fenced flip, validated by check_epoch on every request
        self.placement = PlacementMap(shards=len(self._shards))
        self.heat = ShardHeatTracker(len(self._shards))
        #: this process's member/host label for locality ranking; None
        #: (the default) means "no locality information" → rank 1.0
        self.local_member: str | None = None
        #: mints child engine N for an online split (wired by the
        #: builders that know how; None = splits need an explicit target)
        self._child_factory: Callable[[int], StateStore] | None = None
        # the flip barrier: open in steady state, closed only for the
        # final drain of a fenced handoff. Ops count themselves in and
        # out so the flip can wait for true quiescence, not just an
        # empty gate.
        self._op_gate = asyncio.Event()
        self._op_gate.set()
        self._inflight = 0
        self._drained = asyncio.Event()
        self._drained.set()
        #: keys written while a migration session copies (None = no
        #: session); drained round-by-round, finally under the pause
        self._dirty: set[str] | None = None
        self._reshard_lock = asyncio.Lock()
        self._chaos = None  # ChaosPolicies | None

    # -- routing -----------------------------------------------------------

    def shard_for(self, key: str) -> StateStore:
        return self._shards[self.router.shard_of(key)]

    @property
    def shard_count(self) -> int:
        return len(self._shards)

    # -- op barrier / telemetry taps ---------------------------------------

    async def _enter(self) -> None:
        """Cross the flip barrier and count in. Steady state is one
        already-set Event check — no suspension, no allocation."""
        await self._op_gate.wait()
        self._inflight += 1
        self._drained.clear()

    def _exit(self) -> None:
        self._inflight -= 1
        if self._inflight <= 0:
            self._drained.set()

    def _note_write(self, key: str) -> None:
        self.heat.note_write(self.router.shard_of(key), key)
        if self._dirty is not None:
            self._dirty.add(key)

    # -- single-key ops: pure routing -------------------------------------

    async def get(self, key: str) -> StateItem | None:
        await self._enter()
        try:
            return await self.shard_for(key).get(key)
        finally:
            self._exit()

    async def set(self, key: str, value: Any, *, etag: str | None = None) -> str:
        await self._enter()
        try:
            self._note_write(key)
            return await self.shard_for(key).set(key, value, etag=etag)
        finally:
            self._exit()

    async def delete(self, key: str, *, etag: str | None = None) -> bool:
        await self._enter()
        try:
            self._note_write(key)
            return await self.shard_for(key).delete(key, etag=etag)
        finally:
            self._exit()

    # -- fan-out reads -----------------------------------------------------

    async def bulk_get(self, keys: list[str]) -> list[StateItem | None]:
        await self._enter()
        try:
            out: list[StateItem | None] = [None] * len(keys)
            by_shard: dict[int, list[int]] = {}
            for i, key in enumerate(keys):
                by_shard.setdefault(self.router.shard_of(key), []).append(i)
            async def _one(shard_idx: int, idxs: list[int]) -> None:
                items = await self._shards[shard_idx].bulk_get(
                    [keys[i] for i in idxs])
                for i, item in zip(idxs, items):
                    out[i] = item
            await asyncio.gather(
                *(_one(s, idxs) for s, idxs in by_shard.items()))
            return out
        finally:
            self._exit()

    async def keys(self, *, prefix: str = "") -> list[str]:
        await self._enter()
        try:
            per_shard = await asyncio.gather(
                *(s.keys(prefix=prefix) for s in self._shards))
            # children return sorted lists; k-way merge keeps the facade's
            # answer identical to the single-file engine's ORDER BY key
            return list(heapq.merge(*per_shard))
        finally:
            self._exit()

    async def query(self, query: dict, *, key_prefix: str = "") -> QueryResponse:
        """Scatter the filter, gather + merge, then sort/page at the
        facade. Children get the filter only — sort and page must see
        the *global* result set, so they run here on the merged items
        via the same ``state/query.py`` pipeline the memory engine
        uses; semantics stay contract-suite identical to one shard."""
        if not isinstance(query, dict):
            raise QueryError("query must be a JSON object")
        filt = query.get("filter")
        validate_filter(filt)
        await self._enter()
        try:
            per_shard = await asyncio.gather(
                *(s.query({"filter": filt}, key_prefix=key_prefix)
                  for s in self._shards))
        finally:
            self._exit()
        items = list(heapq.merge(
            *(r.items for r in per_shard), key=lambda it: it.key))
        items = sort_items(items, query.get("sort"))
        items, token = paginate(items, query.get("page"))
        return QueryResponse(items=items, token=token)

    # -- transactions ------------------------------------------------------

    async def transact(self, ops: list[TransactionOp]) -> None:
        await self._enter()
        try:
            by_shard: dict[int, list[TransactionOp]] = {}
            for op in ops:
                self._note_write(op.key)
                by_shard.setdefault(self.router.shard_of(op.key), []).append(op)
            if len(by_shard) <= 1:
                # the hot path: all keys rendezvous to one shard — exactly
                # PR 1's single BEGIN IMMEDIATE..COMMIT, no staging at all
                for shard_idx, shard_ops in by_shard.items():
                    await self._shards[shard_idx].transact(shard_ops)
                return
            await self._transact_cross_shard(by_shard)
        finally:
            self._exit()

    async def _transact_cross_shard(
            self, by_shard: dict[int, list[TransactionOp]]) -> None:
        order = sorted(by_shard)
        staged = []
        try:
            for shard_idx in order:
                child = self._shards[shard_idx]
                stage = getattr(child, "stage_transact", None)
                if stage is None:
                    raise StateError(
                        f"store {self.name!r}: cross-shard transactions "
                        f"need shards that support staged commits; shard "
                        f"{shard_idx} ({type(child).__name__}) does not")
                staged.append((shard_idx, await stage(by_shard[shard_idx])))
        except BaseException:
            # stage phase failed: nothing committed anywhere; unwind
            # every already-staged shard and surface the original error
            await self._rollback_staged(staged)
            raise
        committed: list[int] = []
        for pos, (shard_idx, txn) in enumerate(staged):
            try:
                await txn.commit()
            except BaseException as exc:
                await self._rollback_staged(staged[pos + 1:])
                if committed:
                    raise CrossShardAtomicityError(
                        f"store {self.name!r}: cross-shard transaction "
                        f"committed on shard(s) {committed} but failed on "
                        f"shard {shard_idx}; remaining shards rolled back "
                        f"— repair by re-reading the affected keys"
                    ) from exc
                raise
            committed.append(shard_idx)

    async def _rollback_staged(self, staged: list) -> None:
        for _shard_idx, txn in staged:
            await txn.rollback()

    # -- elastic placement: epoch validation + telemetry -------------------

    def check_epoch(self, epoch: int | None) -> None:
        """Validate a caller's routing epoch against the live map.

        Any mismatch is a redirect: lower means the caller routed with
        a stale table; higher means OURS is stale (the caller saw a
        flip this replica hasn't). Either way nothing was attempted —
        the 409 carries the epoch we do hold and the client refreshes.
        ``None`` (no header) skips validation for pre-elastic callers.
        """
        if epoch is None:
            return
        current_epoch = self.placement.epoch
        if epoch < current_epoch or current_epoch < epoch:
            metrics.inc("placement_stale_routes_total", store=self.name)
            raise PlacementEpochError(
                f"state store {self.name!r}: routing epoch {epoch} does "
                f"not match placement epoch {current_epoch} — refresh "
                f"the placement map and retry", current_epoch=current_epoch)

    def placement_doc(self) -> dict:
        """The telemetry document the sidecar metadata exports and the
        orchestrator's control loop merges: epoch, assignment,
        migration status, per-shard heat, and (for replicated
        children) the current shard leaders."""
        self.heat.sample()
        doc = self.placement.to_doc()
        doc["store"] = self.name
        doc["heat"] = self.heat.snapshot()
        doc["local_member"] = self.local_member
        leaders: dict[str, str | None] = {}
        for i, child in enumerate(self._shards):
            leader_of = getattr(child, "leader_member", None)
            if leader_of is not None:
                leaders[str(i)] = leader_of()
        if leaders:
            doc["leaders"] = leaders
        metrics.set_gauge("placement_epoch", float(self.placement.epoch),
                          store=self.name)
        for i, rate in enumerate(self.heat.rates()):
            metrics.set_gauge("shard_heat", rate, store=self.name, shard=i)
        return doc

    def locality_rank(self, key: str) -> float:
        """1.0 when this process hosts the shard backing ``key`` (or
        nothing is known), 0.0 when another member owns it — the hint
        actor placement (PR 7) uses to keep an actor's turns on the
        host that already holds its records."""
        if self.local_member is None:
            return 1.0
        idx = self.router.shard_of(key)
        child = self._shards[idx]
        leader_of = getattr(child, "leader_member", None)
        owner = (leader_of() if leader_of is not None
                 else self.placement.assignment.get(idx))
        if owner is None:
            return 1.0
        return 1.0 if owner == self.local_member else 0.0

    def attach_chaos(self, policies) -> None:
        """Bind ``kind:Chaos`` faults: ``targets.placement`` rules gate
        the migration/catch-up lane at the facade, and children that
        carry their own lanes (replication streams) get the policies
        forwarded (called by chaos/wrappers.py at component build)."""
        self._chaos = policies
        for child in self._shards:
            child_attach = getattr(child, "attach_chaos", None)
            if child_attach is not None:
                child_attach(policies)

    async def _placement_gate(self, shard: int) -> None:
        """Chaos seam on the catch-up stream: consulted before every
        pre-flip copy batch and catch-up poll — never inside the
        paused flip, so an injected fault aborts a migration cleanly
        instead of wedging the barrier."""
        if self._chaos is None:
            return
        resolver = getattr(self._chaos, "for_placement", None)
        policy = resolver(self.name, shard) if resolver is not None else None
        if policy is not None:
            status = await policy.before_call()
            if status is not None:
                policy.raise_for_status(status)

    # -- elastic placement: live migration / split -------------------------

    def _publish_migration(self, status: dict | None) -> None:
        self.placement = self.placement.with_migration(status)

    def _take_dirty(self, pred) -> list[str]:
        """Swap out the dirty tap and keep the keys the session cares
        about (sorted for deterministic copy order)."""
        if not self._dirty:
            return []
        dirty, self._dirty = self._dirty, set()
        return sorted(k for k in dirty if pred(k))

    async def _stream_keys(self, keys: list[str], target: StateStore, *,
                           chaos_shard: int | None = None) -> int:
        """Copy ``keys`` onto ``target``, reading straight from the
        owning children (works under the flip pause, when the facade
        ops are gated). A key that vanished mid-copy becomes a delete
        on the target — deletes are writes too. Returns keys copied."""
        moved = 0
        for start in range(0, len(keys), 256):
            chunk = keys[start:start + 256]
            if chaos_shard is not None:
                await self._placement_gate(chaos_shard)
            by_shard: dict[int, list[str]] = {}
            for k in chunk:
                by_shard.setdefault(self.router.shard_of(k), []).append(k)
            for src, ks in by_shard.items():
                items = await self._shards[src].bulk_get(ks)
                for k, item in zip(ks, items):
                    if item is None:
                        await target.delete(k)
                    else:
                        await target.set(k, item.value)
                        moved += 1
        if moved:
            metrics.inc("placement_keys_moved_total", moved, store=self.name)
        return moved

    async def _delete_moved(self, keys: list[str]) -> None:
        """Drop moved keys from their source shards (grouped, batched,
        concurrent within a batch so the group-commit engines coalesce
        the deletes into a handful of fsyncs)."""
        by_shard: dict[int, list[str]] = {}
        for k in keys:
            by_shard.setdefault(self.router.shard_of(k), []).append(k)
        for src, ks in by_shard.items():
            child = self._shards[src]
            for start in range(0, len(ks), 512):
                await asyncio.gather(
                    *(child.delete(k) for k in ks[start:start + 512]))

    async def _fenced_flip(self, mutate, *, shards: int | None = None,  # tasklint: fenced-lane
                           assignment: dict[int, str] | None = None) -> float:
        """The zero-downtime handoff: close the op barrier, wait for
        true quiescence (every in-flight op counted out), run the
        final drain + structural swap, publish the successor placement
        map at a strictly higher epoch, reopen. The barrier is closed
        for exactly the final-drain window — the pre-copy and catch-up
        rounds all ran with writes flowing — and the epoch advance is
        monotone by construction, so a router that saw the old map
        fails ``check_epoch`` the instant the new map is live.
        """
        budget = pause_budget_default()
        pause_t0 = time.monotonic()
        self._op_gate.clear()
        try:
            await self._drained.wait()
            await mutate()
            successor = self.placement.advanced(
                shards=shards, assignment=assignment, migration=None)
            if successor.epoch <= self.placement.epoch:
                raise StateError(
                    f"state store {self.name!r}: refusing a non-monotone "
                    f"placement epoch flip")
            self.placement = successor
        finally:
            self._op_gate.set()
        pause = time.monotonic() - pause_t0
        metrics.inc("placement_flips_total", store=self.name)
        metrics.set_gauge("placement_pause_seconds", pause, store=self.name)
        metrics.set_gauge("placement_epoch", float(self.placement.epoch),
                          store=self.name)
        if pause > budget:
            logger.warning(
                "placement: %s flip paused writes %.3fs (budget %.3fs)",
                self.name, pause, budget)
        return pause

    async def migrate_shard(self, shard: int, *,
                            member: str | None = None,
                            target: StateStore | None = None,
                            retire_source: bool = True,
                            max_rounds: int = 8) -> dict:
        """Move shard ``shard`` live, then flip the routing epoch.

        Two transports, one contract (zero lost acked writes):

        * ``member=...`` — replicated children: the PR 9 record stream
          IS the copy. Wait for the target member to catch up (chaos
          gate on the lane), then hand leadership over inside the
          fenced flip (``transfer_leadership`` quiesces, fences the
          old leader's session, and promotes at a bumped lease epoch).
        * ``target=...`` — plain children: stream the shard's keys to
          the target engine with a facade-level dirty-key tap, converge
          the tap round-by-round, drain the residue under the pause,
          and swap the child.
        """
        if shard < 0 or shard >= len(self._shards):
            raise StateError(
                f"state store {self.name!r} has no shard {shard}")
        if (member is None) == (target is None):
            raise StateError(
                f"state store {self.name!r}: migrate_shard needs exactly "
                f"one of member= (replicated handoff) or target= (key "
                f"streaming)")
        async with self._reshard_lock:
            if member is not None:
                return await self._migrate_leadership(shard, member)
            return await self._migrate_copy(
                shard, target, max_rounds=max_rounds,
                retire_source=retire_source)

    async def _migrate_leadership(self, shard: int, member: str) -> dict:
        child = self._shards[shard]
        transfer = getattr(child, "transfer_leadership", None)
        if transfer is None:
            raise StateError(
                f"state store {self.name!r}: shard {shard} is not "
                f"replicated — migrate with an explicit target= store")
        lag_of = getattr(child, "member_lag", None)
        try:
            self._publish_migration({
                "kind": "move", "shard": shard, "target": member,
                "phase": "catchup"})
            deadline = time.monotonic() + 30.0
            while True:
                await self._placement_gate(shard)
                lag = lag_of(member) if lag_of is not None else 0
                if lag is not None and lag <= 0:
                    break
                if time.monotonic() > deadline:
                    raise StateError(
                        f"state store {self.name!r}: shard {shard} "
                        f"catch-up toward {member} did not converge")
                await asyncio.sleep(0.02)
            self._publish_migration({
                "kind": "move", "shard": shard, "target": member,
                "phase": "flip"})
            pause = await self._fenced_flip(
                lambda: transfer(member), assignment={shard: member})
        finally:
            self._publish_migration(None)
        return {"action": "move", "shard": shard, "target": member,
                "epoch": self.placement.epoch, "pause_seconds": pause}

    async def _migrate_copy(self, shard: int, target: StateStore, *,
                            max_rounds: int, retire_source: bool) -> dict:
        old = self._shards[shard]
        if self._chaos is not None:
            target_attach = getattr(target, "attach_chaos", None)
            if target_attach is not None:
                target_attach(self._chaos)
        of_shard = lambda k: self.router.shard_of(k) == shard
        self._dirty = set()
        moved = 0
        try:
            self._publish_migration({
                "kind": "move", "shard": shard, "target": target.name,
                "phase": "copy"})
            snapshot_keys = await old.keys()
            moved += await self._stream_keys(
                snapshot_keys, target, chaos_shard=shard)
            residue: list[str] = []
            for _ in range(max_rounds):
                residue = self._take_dirty(of_shard)
                if len(residue) <= 64:
                    break
                self._publish_migration({
                    "kind": "move", "shard": shard, "target": target.name,
                    "phase": "catchup", "pending": len(residue)})
                moved += await self._stream_keys(
                    residue, target, chaos_shard=shard)
                residue = []
            else:
                raise StateError(
                    f"state store {self.name!r}: shard {shard} migration "
                    f"dirty set did not converge in {max_rounds} rounds — "
                    f"the writer outruns the copy; raise the pause budget "
                    f"or throttle the writer")
            self._publish_migration({
                "kind": "move", "shard": shard, "target": target.name,
                "phase": "flip"})

            async def _mutate() -> None:
                final = sorted(set(residue) | set(self._take_dirty(of_shard)))
                await self._stream_keys(final, target)
                self._shards[shard] = target

            pause = await self._fenced_flip(
                _mutate, assignment={shard: target.name})
        finally:
            self._dirty = None
            self._publish_migration(None)
        if retire_source:
            try:
                await old.aclose()
            except Exception:
                logger.debug("placement: %s: retired source shard close "
                             "failed", self.name, exc_info=True)
        return {"action": "move", "shard": shard, "target": target.name,
                "epoch": self.placement.epoch, "keys_moved": moved,
                "pause_seconds": pause}

    async def split_shard(self, *, target: StateStore | None = None,
                          max_rounds: int = 8) -> dict:
        """Grow the ring ``N → N+1`` live: stream every key the grown
        router sends to the new shard (an expected ``1/(N+1)`` of the
        space, drawn from ALL shards — the HRW salt design never moves
        a key between survivors), converge the dirty tap, then flip
        router + placement epoch inside the fenced barrier. Source
        copies of moved keys are deleted under the same pause so the
        fan-out reads (``keys``/``query``) never see duplicates."""
        async with self._reshard_lock:
            n = len(self._shards)
            if n + 1 > MAX_SHARDS:
                raise ComponentError(
                    f"state store {self.name!r}: cannot split past "
                    f"{MAX_SHARDS} shards")
            if target is None:
                if self._child_factory is None:
                    raise StateError(
                        f"state store {self.name!r}: online split needs a "
                        f"child factory (sqlite-backed stores wire one) or "
                        f"an explicit target= store")
                target = self._child_factory(n)
            if self._chaos is not None:
                target_attach = getattr(target, "attach_chaos", None)
                if target_attach is not None:
                    target_attach(self._chaos)
            grown = ShardRouter(n + 1, self.hash_seed)
            moving = lambda k: grown.shard_of(k) == n
            self._dirty = set()
            moved_keys: set[str] = set()
            moved = 0
            try:
                self._publish_migration({
                    "kind": "split", "shard": n, "phase": "copy"})
                initial = [k for k in await self.keys() if moving(k)]
                moved_keys.update(initial)
                moved += await self._stream_keys(
                    initial, target, chaos_shard=n)
                residue: list[str] = []
                for _ in range(max_rounds):
                    residue = self._take_dirty(moving)
                    if len(residue) <= 64:
                        break
                    moved_keys.update(residue)
                    self._publish_migration({
                        "kind": "split", "shard": n, "phase": "catchup",
                        "pending": len(residue)})
                    moved += await self._stream_keys(
                        residue, target, chaos_shard=n)
                    residue = []
                else:
                    raise StateError(
                        f"state store {self.name!r}: split dirty set did "
                        f"not converge in {max_rounds} rounds")
                self._publish_migration({
                    "kind": "split", "shard": n, "phase": "flip"})

                async def _mutate() -> None:
                    final = sorted(set(residue) | set(self._take_dirty(moving)))
                    moved_keys.update(final)
                    await self._stream_keys(final, target)
                    # sources shed their moved copies while quiesced:
                    # after the flip, keys()/query() fan out over the
                    # grown ring and must not double-count
                    await self._delete_moved(sorted(moved_keys))
                    self._shards.append(target)
                    self.router = grown
                    self.heat.grow(1)

                pause = await self._fenced_flip(
                    _mutate, shards=n + 1,
                    assignment={n: target.name})
            finally:
                self._dirty = None
                self._publish_migration(None)
            return {"action": "split", "new_shard": n, "shards": n + 1,
                    "epoch": self.placement.epoch, "keys_moved": moved,
                    "pause_seconds": pause}

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        first: BaseException | None = None
        for child in self._shards:
            try:
                child.close()
            except Exception as exc:
                if first is None:
                    first = exc
        if first is not None:
            raise first

    async def aclose(self) -> None:
        """Prefer the children's async teardown: replicated children
        (state/replication.py) release shard leases gracefully only on
        the async path — sync ``close()`` is the crash-equivalent."""
        first: BaseException | None = None
        for child in self._shards:
            try:
                await child.aclose()
            except Exception as exc:
                if first is None:
                    first = exc
        if first is not None:
            raise first
