"""SQLite-backed state store — the durable local engine.

Fills the slot Cosmos DB fills in the reference (component
``state.azure.cosmosdb``, components/dapr-statestore-cosmos.yaml):
durable, queryable document state. The type alias means the reference's
cloud component file runs unchanged against this engine locally.

The filter/sort dialect (state/query.py) is compiled to SQL over
``json_extract`` so filtering happens in the engine, not in Python —
the framework-level analog of Cosmos executing the JSON query
server-side rather than the sidecar scanning keys.

Write architecture (mirrors pubsub/sqlite.py, which solved the same
problem for the broker one round earlier):

* **Group-commit write queue** — concurrent ``set``/``delete``/
  ``transact`` calls enqueue and a single flusher on the writer thread
  drains whatever accumulated into ONE ``BEGIN IMMEDIATE … COMMIT``.
  Commits amortise across the burst; each caller's future still
  resolves with its own etag or ``EtagMismatch``, so per-key etag
  semantics are identical to one-transaction-per-call.
* **Off-loop execution** — all SQL (reads included) runs on dedicated
  reader/writer threads, so a checkpoint, fsync, or cross-process lock
  wait never stalls unrelated coroutines on the event loop.
* **Decoupled checkpointing** — ``wal_autocheckpoint=0`` plus a
  background PASSIVE checkpoint thread: no commit ever pays the
  WAL→db page-copy inline.
* **Sub-ms busy backoff** — the write transaction acquires the
  cross-process write lock with a 0.2→2 ms retry loop instead of
  sqlite's built-in 1→100 ms busy handler.
* **Optional read cache** — a bounded write-through LRU of
  (key → serialized doc, etag), off by default (``readCacheSize``
  metadata). Safe only while this process is the sole writer to the
  file; every write/delete/transact updates or invalidates it.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import json
import pathlib
import sqlite3
import threading
import time
from typing import Any

from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec, metadata_bool, metadata_int
from tasksrunner.errors import (
    ComponentError, EtagMismatch, QueryError, ReplicaFencedError,
    ReplicationGapError, StateError,
)
from tasksrunner.ids import hex8
from tasksrunner.observability.metrics import metrics
from tasksrunner.observability.spans import active as spans_active, record_span
from tasksrunner.observability.tracing import current_trace
from tasksrunner.state.base import QueryResponse, StateItem, StateStore, TransactionOp
from tasksrunner.state.query import validate_filter

_SCHEMA = """
CREATE TABLE IF NOT EXISTS state (
    key   TEXT PRIMARY KEY,
    value TEXT NOT NULL,
    etag  TEXT NOT NULL
) WITHOUT ROWID;
CREATE TABLE IF NOT EXISTS etag_seq (
    id  INTEGER PRIMARY KEY CHECK (id = 1),
    n   INTEGER NOT NULL
);
INSERT OR IGNORE INTO etag_seq(id, n) VALUES (1, 0);
"""

#: created only on replicated members (``replication=True``) so a
#: plain store's file layout stays bit-for-bit what it was: the
#: logical write-ahead record stream (state/replication.py) plus the
#: member's durable position (high-water mark + fencing epoch).
_REPL_SCHEMA = """
CREATE TABLE IF NOT EXISTS repl_log (
    seq    INTEGER PRIMARY KEY,
    epoch  INTEGER NOT NULL,
    record TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS repl_meta (
    id    INTEGER PRIMARY KEY CHECK (id = 1),
    hwm   INTEGER NOT NULL,
    epoch INTEGER NOT NULL
);
INSERT OR IGNORE INTO repl_meta(id, hwm, epoch) VALUES (1, 0, 0);
"""


def _like_escape(prefix: str) -> str:
    return prefix.replace("\\", "\\\\").replace("%", "\\%").replace("_", "\\_")


def _param(value: Any) -> Any:
    """Bind a JSON scalar the way json_extract represents it."""
    if isinstance(value, bool):
        return int(value)
    return value


def compile_filter(filt: Any) -> tuple[str, list[Any]]:
    """Compile a validated filter to a WHERE fragment + params.

    Semantics must match state.query.matches exactly; the contract
    suite in tests/test_state.py runs both engines on the same cases.
    """
    if filt in (None, {}):
        return "1", []
    op, operand = next(iter(filt.items()))
    if op in ("AND", "OR"):
        parts, params = [], []
        for sub in operand:
            frag, p = compile_filter(sub)
            parts.append(f"({frag})")
            params.extend(p)
        return f" {op} ".join(parts), params
    path, expected = next(iter(operand.items()))
    col = "json_extract(value, ?)"
    jpath = "$." + path
    if op == "EQ":
        return f"{col} IS ?", [jpath, _param(expected)]
    if op == "NEQ":
        return f"{col} IS NOT ?", [jpath, _param(expected)]
    if op == "IN":
        if not expected:
            return "0", []
        placeholders = ", ".join("?" for _ in expected)
        frag = f"{col} IN ({placeholders})"
        params: list[Any] = [jpath, *(_param(v) for v in expected)]
        if any(v is None for v in expected):
            frag = f"({frag} OR json_extract(value, ?) IS NULL)"
            params.append(jpath)
        return frag, params
    raise QueryError(f"unknown filter operator {op!r}")


def compile_sort(sort_spec: list[dict] | None) -> tuple[str, list[Any]]:
    if not sort_spec:
        return "ORDER BY key", []
    clauses, params = [], []
    for clause in sort_spec:
        if not isinstance(clause, dict) or "key" not in clause:
            raise QueryError("each sort clause needs a key")
        order = str(clause.get("order", "ASC")).upper()
        if order not in ("ASC", "DESC"):
            raise QueryError(f"sort order must be ASC or DESC, not {clause.get('order')!r}")
        clauses.append(f"json_extract(value, ?) {order}")
        params.append("$." + clause["key"])
    return "ORDER BY " + ", ".join(clauses), params


def _encode(key: str, value: Any) -> str:
    """Serialize on the caller so a bad payload fails its own call,
    never the shared flush batch.

    allow_nan=False: NaN/Infinity would poison json_extract for every
    later query on the store; reject at write time the way a real
    document DB does.
    """
    try:
        return json.dumps(value, separators=(",", ":"), allow_nan=False)
    except ValueError as exc:
        raise StateError(f"value for key {key!r} is not valid JSON: {exc}") from exc


class _PendingWrite:
    """One enqueued write op + the caller's loop/future to resolve."""

    __slots__ = ("op", "loop", "future", "enqueued", "ctx")

    def __init__(self, op: tuple, loop: asyncio.AbstractEventLoop,
                 future: asyncio.Future):
        self.op = op
        self.loop = loop
        self.future = future
        # monotonic enqueue time: the queue-wait half of the
        # state_queue_wait_seconds / state_commit_seconds latency split
        self.enqueued = time.monotonic()
        # the caller's trace context, captured on the event loop — the
        # writer thread records the state-write span with an explicit
        # trace_id since it has no ambient context of its own
        self.ctx = current_trace() if spans_active() else None


def _resolve(row: _PendingWrite, value: Any, exc: BaseException | None) -> None:
    def _set() -> None:
        if row.future.done():
            return
        if exc is None:
            row.future.set_result(value)
        else:
            row.future.set_exception(exc)
    try:
        row.loop.call_soon_threadsafe(_set)
    except RuntimeError:  # caller's loop already closed (shutdown)
        pass


def _resolve_batch(
    pairs: list[tuple[_PendingWrite, Any, BaseException | None]],
) -> None:
    """Resolve a whole batch with ONE loop wakeup per event loop.

    call_soon_threadsafe writes the self-pipe every call; doing it
    per-op made the loop wakeup the dominant cost of a coalesced flush.
    All callers normally share one loop, so this is one syscall per
    batch instead of one per write."""
    by_loop: dict[asyncio.AbstractEventLoop, list] = {}
    for row, value, exc in pairs:
        by_loop.setdefault(row.loop, []).append((row.future, value, exc))
    for loop, items in by_loop.items():
        def _set(items=items) -> None:
            for fut, value, exc in items:
                if fut.done():
                    continue
                if exc is None:
                    fut.set_result(value)
                else:
                    fut.set_exception(exc)
        try:
            loop.call_soon_threadsafe(_set)
        except RuntimeError:  # caller's loop already closed (shutdown)
            pass


class StagedTransaction:
    """Coordinator handle for one store's staged, uncommitted
    transaction — the per-shard half of the two-phase cross-shard
    commit in ``state/sharding.py``.

    ``SqliteStateStore.stage_transact`` returns one of these only
    after the writer thread has opened the transaction, validated
    every etag, and applied the ops; the transaction is then HELD OPEN
    with the writer thread parked on the coordinator's decision.
    Exactly one of :meth:`commit` / :meth:`rollback` must be awaited.
    The writer thread enforces a decision deadline
    (``SqliteStateStore._STAGE_DECISION_TIMEOUT``): past it the shard
    rolls back unilaterally and a late ``commit()`` raises
    ``StateError`` rather than pretending to have committed.
    """

    __slots__ = ("_loop", "_staged", "_done", "_lock", "_evt", "_decision")

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        #: resolves once the ops are applied inside the open
        #: transaction (or with the stage-phase failure)
        self._staged: asyncio.Future = loop.create_future()
        #: resolves with the final outcome: "committed"/"rolledback",
        #: or the commit/rollback-phase exception
        self._done: asyncio.Future = loop.create_future()
        self._lock = threading.Lock()
        self._evt = threading.Event()
        self._decision: str | None = None

    # -- coordinator side (event loop) ------------------------------------

    def _decide(self, decision: str) -> None:
        # first decision wins: the writer thread's timeout races a late
        # coordinator; the lock makes the race deterministic
        with self._lock:
            if self._decision is None:
                self._decision = decision
        self._evt.set()

    async def commit(self) -> None:
        """Commit the staged transaction. Raises the commit failure,
        or ``StateError`` if the shard already rolled back because the
        decision deadline passed."""
        self._decide("commit")
        outcome = await self._done
        if outcome != "committed":
            raise StateError(
                "staged transaction was rolled back before the commit "
                "decision arrived (decision deadline exceeded)")

    async def rollback(self) -> None:
        """Roll the staged transaction back; idempotent with the
        writer-side timeout rollback."""
        self._decide("rollback")
        await self._done

    # -- writer-thread side ------------------------------------------------

    def _await_decision(self, timeout: float) -> str:
        if not self._evt.wait(timeout):
            self._decide("timeout")
        with self._lock:
            return self._decision or "timeout"

    def _resolve_staged(self, exc: BaseException | None) -> None:
        self._post(self._staged, None, exc)

    def _finish(self, outcome: str | None, exc: BaseException | None) -> None:
        self._post(self._done, outcome, exc)

    def _post(self, fut: asyncio.Future, value: Any,
              exc: BaseException | None) -> None:
        def _set() -> None:
            if fut.done():
                return
            if exc is None:
                fut.set_result(value)
            else:
                fut.set_exception(exc)
        try:
            self._loop.call_soon_threadsafe(_set)
        except RuntimeError:  # coordinator's loop closed (shutdown)
            pass


class SqliteStateStore(StateStore):
    #: RETURNING needs sqlite >= 3.35 (2021); fall back to the
    #: two-statement form on older system libsqlite3 builds
    _HAS_RETURNING = sqlite3.sqlite_version_info >= (3, 35, 0)

    #: how long a staged cross-shard transaction may hold the commit
    #: slot waiting for the coordinator's decision before the writer
    #: thread rolls it back (class attr so tests can shrink it)
    _STAGE_DECISION_TIMEOUT = 30.0

    def __init__(self, name: str, path: str | pathlib.Path = ":memory:", *,
                 group_commit: bool = True, cache_size: int = 0,
                 shard: int | None = None, replication: bool = False,
                 repl_log_retain: int = 4096):
        super().__init__(name)
        self.path = str(path)
        #: True on replica-set members: every commit also appends a
        #: logical record to ``repl_log`` (same transaction), and the
        #: attached :attr:`_repl` session — when present — defers the
        #: caller's ack until the record reached its quorum.
        self.replication = bool(replication)
        if self.replication:
            group_commit = True  # the record stream IS the flusher's output
        #: shard index when this store is one partition of a sharded
        #: component (state/sharding.py); None = standalone. Only
        #: affects observability: the queue-depth gauge gains a
        #: ``shard`` label and thread names a ``.N`` suffix — latency
        #: histograms keep ``store=name`` so per-store series aggregate
        #: across the partition set.
        self.shard = shard
        thread_tag = name if shard is None else f"{name}.{shard}"
        self._is_file = self.path != ":memory:"
        if self._is_file:
            pathlib.Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        #: coalesce concurrent writes into one transaction (the broker's
        #: publish-queue discipline); off = one transaction per call,
        #: still executed off-loop — a debugging/comparison knob
        self.group_commit = bool(group_commit)
        #: bounded write-through LRU of (key -> doc, etag); 0 = off.
        #: Only safe while this process is the file's sole writer.
        self.cache_size = max(0, int(cache_size))

        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        # WAL + NORMAL: fsync at checkpoint, not per-commit — the
        # standard durability/throughput point for local engines
        self._conn.execute("PRAGMA synchronous=NORMAL")
        # Writes go through _exec_batch, whose own retry loop (sub-ms
        # backoff) replaces sqlite's busy handler: the built-in
        # handler's first sleep is 1 ms and escalates to 100 ms, which
        # under cross-process convoys on a shared file turns ~0.1 ms
        # transactions into multi-ms write p50s (the broker measured
        # this in BASELINE.md round 4). _exec_batch zeroes busy_timeout
        # around its BEGIN IMMEDIATE; everything else keeps the cushion.
        self._conn.execute("PRAGMA busy_timeout=5000")
        if self._is_file:
            # Decoupled checkpointing: never copy WAL→db inline on a
            # committing writer; the background thread PASSIVE-checkpoints.
            self._conn.execute("PRAGMA wal_autocheckpoint=0")
        self._conn.executescript(_SCHEMA)
        if self.replication:
            self._conn.executescript(_REPL_SCHEMA)
        self._conn.commit()

        # Replication bookkeeping. _repl_hwm/_repl_epoch mirror
        # repl_meta; they are mutated on the writer thread only, but
        # read from the event loop (lag gauges, stale-read bounds), so
        # the tiny lock keeps the pair coherent across threads.
        self._repl = None               # ReplicationSession once leader
        self._repl_lock = threading.Lock()
        self._repl_retain = max(1, int(repl_log_retain))
        self._repl_hwm = 0
        self._repl_epoch = 0
        if self.replication:
            row = self._conn.execute(
                "SELECT hwm, epoch FROM repl_meta WHERE id = 1").fetchone()
            self._repl_hwm, self._repl_epoch = int(row[0]), int(row[1])

        # Dedicated writer thread (owns self._conn after init) and, for
        # file stores, a dedicated reader thread with its own WAL
        # connection — reads never queue behind a flush or lock wait.
        # ":memory:" databases are private per connection, so there the
        # reader shares the writer's thread and connection.
        self._write_exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"state-w-{thread_tag}")
        if self._is_file:
            self._rconn = sqlite3.connect(self.path, check_same_thread=False)
            self._rconn.execute("PRAGMA busy_timeout=5000")
            self._read_exec = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"state-r-{thread_tag}")
        else:
            self._rconn = self._conn
            self._read_exec = self._write_exec

        self._dirty = False          # set on commit, cleared by checkpointer
        self._ckpt_stop = threading.Event()
        self._ckpt_thread: threading.Thread | None = None
        if self._is_file:
            self._ckpt_thread = threading.Thread(
                target=self._checkpoint_loop,
                name=f"state-ckpt-{thread_tag}", daemon=True)
            self._ckpt_thread.start()

        # Group-commit write queue (≙ the broker's publish queue):
        # callers enqueue here; one flush job on the writer thread
        # drains whatever accumulated into a single transaction.
        self._q_lock = threading.Lock()
        self._q_pending: list[_PendingWrite] = []
        self._q_flushing = False
        self._closed = False

        self._cache: collections.OrderedDict[str, tuple[str, str]] = \
            collections.OrderedDict()
        self._cache_lock = threading.Lock()

    # -- off-loop plumbing ------------------------------------------------

    async def _run_read(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            self._read_exec, fn, *args)

    def _checkpoint_loop(self) -> None:
        """Background PASSIVE WAL checkpointing on a dedicated
        connection (PASSIVE never blocks WAL readers/writers). Keeps
        the checkpoint's page-copy IO off the commit path entirely:
        with ``wal_autocheckpoint=0`` no commit ever pays it inline."""
        conn = None
        while not self._ckpt_stop.wait(0.25):
            if not self._dirty:
                continue
            self._dirty = False
            try:
                if conn is None:
                    conn = sqlite3.connect(self.path, timeout=1.0)
                conn.execute("PRAGMA wal_checkpoint(PASSIVE)")
            except sqlite3.Error:  # pragma: no cover - transient; retry next tick
                self._dirty = True
        if conn is not None:
            try:
                conn.execute("PRAGMA wal_checkpoint(PASSIVE)")
                conn.close()
            except sqlite3.Error:  # pragma: no cover
                pass

    def _begin_immediate(self, cur: sqlite3.Cursor) -> None:
        """Acquire the cross-process write lock with a fast retry loop
        (0.2→2 ms exponential backoff, 5 s deadline) instead of
        sqlite's built-in busy handler (1→100 ms sleeps)."""
        cur.execute("PRAGMA busy_timeout=0")
        delay = 0.0002
        deadline = time.monotonic() + 5.0
        try:
            while True:
                try:
                    cur.execute("BEGIN IMMEDIATE")
                    return
                except sqlite3.OperationalError as exc:
                    msg = str(exc).lower()
                    if "locked" not in msg and "busy" not in msg:
                        raise
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(delay)
                    delay = min(delay * 2, 0.002)
        finally:
            cur.execute("PRAGMA busy_timeout=5000")

    # -- write ops (writer thread, inside an open transaction) -----------

    _SET_SQL = (
        "INSERT INTO state(key, value, etag) VALUES(?, ?, ?) "
        "ON CONFLICT(key) DO UPDATE SET value=excluded.value, etag=excluded.etag"
    )

    def _reserve_etags(self, cur: sqlite3.Cursor, count: int) -> int:
        """Advance the store-global monotonic sequence by ``count`` in
        one UPDATE and return the first reserved value. The sequence
        guarantees a deleted-and-recreated key never reuses an old
        etag, so stale tokens from a previous incarnation of the key
        can't validate. Refused ops leave gaps in the sequence — etags
        are opaque and only need to be fresh, so gaps are free — and a
        rolled-back transaction rolls the reservation back with it."""
        if self._HAS_RETURNING:
            (n,) = cur.execute(
                "UPDATE etag_seq SET n = n + ? WHERE id = 1 RETURNING n",
                (count,)).fetchone()
        else:
            cur.execute("UPDATE etag_seq SET n = n + ? WHERE id = 1", (count,))
            (n,) = cur.execute("SELECT n FROM etag_seq WHERE id = 1").fetchone()
        return n - count + 1

    @staticmethod
    def _etags_needed(op: tuple) -> int:
        if op[0] == "set":
            return 1
        if op[0] == "transact":
            return sum(1 for o in op[1] if o[0] == "upsert")
        return 0

    def _apply_set(self, cur: sqlite3.Cursor, key: str, doc: str,
                   etag: str | None, mutations: list[tuple], alloc) -> str:
        # EtagMismatch raises before any write, so a refused op inside
        # a coalesced batch leaves the shared transaction untouched.
        if etag is not None:
            row = cur.execute("SELECT etag FROM state WHERE key = ?", (key,)).fetchone()
            if row is None or row[0] != etag:
                raise EtagMismatch(f"etag mismatch for key {key!r}")
        new_etag = alloc()
        cur.execute(self._SET_SQL, (key, doc, new_etag))
        mutations.append(("set", key, doc, new_etag))
        return new_etag

    def _apply_delete(self, cur: sqlite3.Cursor, key: str,
                      etag: str | None, mutations: list[tuple]) -> bool:
        row = cur.execute("SELECT etag FROM state WHERE key = ?", (key,)).fetchone()
        if row is None:
            if etag is not None:
                raise EtagMismatch(f"etag mismatch for key {key!r}")
            return False
        if etag is not None and row[0] != etag:
            raise EtagMismatch(f"etag mismatch for key {key!r}")
        cur.execute("DELETE FROM state WHERE key = ?", (key,))
        mutations.append(("delete", key))
        return True

    def _apply_transact(self, cur: sqlite3.Cursor, ops: list[tuple],
                        mutations: list[tuple], alloc) -> None:
        """Contract (matches the memory engine): all etags validate
        against the *pre-transaction* state, then ops apply in order.
        Validation is read-only, so a refused transact inside a
        coalesced batch has written nothing."""
        for _operation, key, _doc, etag in ops:
            if etag is None:
                continue
            row = cur.execute(
                "SELECT etag FROM state WHERE key = ?", (key,)).fetchone()
            if row is None or row[0] != etag:
                raise EtagMismatch(f"etag mismatch for key {key!r}")
        for operation, key, doc, _etag in ops:
            if operation == "upsert":
                self._apply_set(cur, key, doc, None, mutations, alloc)
            else:
                cur.execute("DELETE FROM state WHERE key = ?", (key,))
                mutations.append(("delete", key))
        return None

    def _apply_op(self, cur: sqlite3.Cursor, op: tuple,
                  mutations: list[tuple], alloc) -> Any:
        kind = op[0]
        if kind == "set":
            return self._apply_set(cur, op[1], op[2], op[3], mutations, alloc)
        if kind == "delete":
            return self._apply_delete(cur, op[1], op[2], mutations)
        return self._apply_transact(cur, op[1], mutations, alloc)

    # -- replication record stream (leader side, writer thread) -----------

    def _repl_append(self, cur: sqlite3.Cursor,
                     mutations: list[tuple],
                     tp: str | None = None) -> dict | None:
        """Append one logical record covering ``mutations`` to the
        write-ahead stream, INSIDE the data transaction — the record
        and the rows it describes commit or roll back together. The
        record carries the post-batch ``etag_seq`` value so followers
        keep allocating fresh etags after a failover, and the leader's
        epoch so stale-epoch zombies are refused downstream. ``tp`` is
        the committing write's traceparent: ship/apply/ack spans
        downstream key off it, tying replication work back to the
        request that caused it."""
        if not self.replication or not mutations:
            return None
        seq = self._repl_hwm + 1
        (etag_n,) = cur.execute(
            "SELECT n FROM etag_seq WHERE id = 1").fetchone()
        record = {"seq": seq, "epoch": self._repl_epoch,
                  "ops": mutations, "etag_n": etag_n, "ts": time.time()}
        if tp is not None:
            record["tp"] = tp
        cur.execute(
            "INSERT INTO repl_log(seq, epoch, record) VALUES (?, ?, ?)",
            (seq, self._repl_epoch,
             json.dumps(record, separators=(",", ":"))))
        cur.execute("UPDATE repl_meta SET hwm = ? WHERE id = 1", (seq,))
        # bounded log: a follower further behind than the retained
        # window catches up via snapshot instead
        cur.execute("DELETE FROM repl_log WHERE seq <= ?",
                    (seq - self._repl_retain,))
        return record

    def _repl_committed(self, record: dict | None) -> None:
        """Post-COMMIT bookkeeping for an appended record."""
        if record is not None:
            with self._repl_lock:
                self._repl_hwm = record["seq"]

    def _repl_fail_fast(self) -> BaseException | None:
        """A fenced member refuses new writes before touching the db —
        its stream can no longer reach quorum, so accepting the commit
        would only grow the divergent suffix a resync must discard."""
        repl = self._repl
        if repl is not None and getattr(repl, "fenced", False):
            return ReplicaFencedError(
                f"state store {self.name!r}: this member lost shard "
                "leadership (epoch fenced); retry against the new leader")
        return None

    # -- group-commit flush (writer thread) -------------------------------

    def _flush_writes(self) -> None:
        """Flush one accumulated batch in a single transaction.
        Re-submits itself if more arrived meanwhile, so reads sharing
        the executor (":memory:" stores) interleave FIFO instead of
        starving behind a drain loop."""
        with self._q_lock:
            batch = self._q_pending
            if not batch:
                self._q_flushing = False
                return
            self._q_pending = []
        # depth the queue reached before this flush drained it; sampled
        # once per batch on the writer thread so the event loop never
        # pays for the gauge
        if self.shard is None:
            metrics.set_gauge("state_write_queue_depth", len(batch),
                              store=self.name)
        else:
            # one gauge series per shard: saturation on a hot partition
            # must be visible as THAT shard's depth, not averaged away
            metrics.set_gauge("state_write_queue_depth", len(batch),
                              store=self.name, shard=self.shard)
        self._exec_batch(batch)
        with self._q_lock:
            if self._q_pending:
                try:
                    self._write_exec.submit(self._flush_writes)
                except RuntimeError:  # shutdown race: fail the stragglers
                    self._q_flushing = False
                    for row in self._q_pending:
                        _resolve(row, None,
                                 StateError(f"state store {self.name!r} is closed"))
                    self._q_pending = []
            else:
                self._q_flushing = False

    def _exec_batch(self, batch: list[_PendingWrite]) -> None:
        """One BEGIN IMMEDIATE…COMMIT covering every op in the batch.
        Per-op EtagMismatch is recorded for that caller alone (the op
        validated before writing, so the shared transaction is clean);
        ops apply in enqueue order, so an op sees the effects of the
        ops queued before it exactly as if each had committed alone."""
        fast = self._repl_fail_fast()
        if fast is not None:
            _resolve_batch([(row, None, fast) for row in batch])
            return
        results: list[tuple[Any, BaseException | None]] = [None] * len(batch)
        mutations: list[tuple] = []
        rec: dict | None = None
        batch_start = time.monotonic()
        if metrics.histograms_enabled:
            metrics.observe_many(
                "state_queue_wait_seconds",
                [batch_start - row.enqueued for row in batch], store=self.name)
        cur = self._conn.cursor()
        try:
            self._begin_immediate(cur)
            try:
                # one sequence bump for the whole batch, not one per op
                need = sum(self._etags_needed(row.op) for row in batch)
                seq = iter(range(self._reserve_etags(cur, need),
                                 2 ** 63)) if need else iter(())
                alloc = lambda: str(next(seq))  # noqa: E731
                i, n = 0, len(batch)
                while i < n:
                    op = batch[i].op
                    if op[0] == "set" and op[3] is None:
                        # fast path: a run of unconditional upserts
                        # becomes ONE executemany (C-loop, no per-op
                        # Python dispatch). ON CONFLICT applies rows in
                        # order, so a repeated key keeps last-write-wins
                        # exactly as the slow path would.
                        j = i
                        params = []
                        while (j < n and batch[j].op[0] == "set"
                               and batch[j].op[3] is None):
                            sop = batch[j].op
                            etag = alloc()
                            params.append((sop[1], sop[2], etag))
                            results[j] = (etag, None)
                            j += 1
                        cur.executemany(self._SET_SQL, params)
                        for key, doc, etag in params:
                            mutations.append(("set", key, doc, etag))
                        i = j
                        continue
                    try:
                        results[i] = (
                            self._apply_op(cur, op, mutations, alloc), None)
                    except EtagMismatch as exc:
                        results[i] = (None, exc)
                    i += 1
                # the first op that arrived with a trace keys the whole
                # record — records coalesce many writes, one traceparent
                rec = self._repl_append(
                    cur, mutations,
                    tp=next((row.ctx.header for row in batch
                             if row.ctx is not None), None))
                self._conn.commit()
            except BaseException:
                self._conn.rollback()
                raise
        except BaseException:
            # batch-wide failure (lock deadline, disk error): retry each
            # op alone so one poisoned op cannot fail its neighbours;
            # report per-caller — mirror of the broker's publish fallback
            for row in batch:
                self._exec_single_resolve(row)
            return
        self._dirty = True
        self._cache_apply(mutations)
        self._repl_committed(rec)
        mono_end = time.monotonic()
        metrics.observe("state_commit_seconds",
                        mono_end - batch_start, store=self.name)
        if spans_active():
            # per-caller state-write spans, recorded from the writer
            # thread with the queue-wait vs commit-service split the
            # critical-path extractor reads
            wall_end = time.time()
            service = mono_end - batch_start
            for row, (_value, exc) in zip(batch, results):
                if row.ctx is None:
                    continue
                record_span(
                    kind="internal", name=f"state-write {self.name}",
                    status=200 if exc is None else 409,
                    start=wall_end - (mono_end - row.enqueued),
                    duration=mono_end - row.enqueued,
                    attrs={"queue_wait": batch_start - row.enqueued,
                           "service": service, "store": self.name},
                    trace_id=row.ctx.trace_id, span_id=hex8(),
                    parent_id=row.ctx.span_id)
        pairs = [(row, value, exc)
                 for row, (value, exc) in zip(batch, results)]
        repl = self._repl
        if rec is not None and repl is not None:
            # ack-after-replication: the record is durable locally, but
            # the callers' futures resolve only once it reached the ack
            # quorum (or the quorum timeout fails them). A row refused
            # by its own etag keeps its own EtagMismatch either way.
            def _quorum_fail(qexc: BaseException) -> None:
                _resolve_batch([(row, None, exc if exc is not None else qexc)
                                for row, _value, exc in pairs])
            repl.on_commit(rec, lambda: _resolve_batch(pairs), _quorum_fail)
        else:
            _resolve_batch(pairs)

    def _exec_single(self, op: tuple) -> Any:
        """One op in its own transaction (writer thread); the
        group_commit=False path and the batch-failure fallback."""
        value, _rec = self._exec_single_repl(op)
        return value

    def _exec_single_repl(self, op: tuple) -> tuple[Any, dict | None]:
        fast = self._repl_fail_fast()
        if fast is not None:
            raise fast
        mutations: list[tuple] = []
        cur = self._conn.cursor()
        self._begin_immediate(cur)
        try:
            value = self._apply_op(cur, op, mutations,
                                   lambda: str(self._reserve_etags(cur, 1)))
            rec = self._repl_append(cur, mutations)
            self._conn.commit()
        except BaseException:
            self._conn.rollback()
            raise
        self._dirty = True
        self._cache_apply(mutations)
        self._repl_committed(rec)
        return value, rec

    def _exec_single_resolve(self, row: _PendingWrite) -> None:
        try:
            value, rec = self._exec_single_repl(row.op)
        except BaseException as exc:
            _resolve(row, None, exc)
            return
        repl = self._repl
        if rec is not None and repl is not None:
            repl.on_commit(rec,
                           lambda: _resolve(row, value, None),
                           lambda qexc: _resolve(row, None, qexc))
        else:
            _resolve(row, value, None)

    async def _submit_write(self, op: tuple) -> Any:
        if not self.group_commit:
            return await asyncio.get_running_loop().run_in_executor(
                self._write_exec, self._exec_single, op)
        loop = asyncio.get_running_loop()
        row = _PendingWrite(op, loop, loop.create_future())
        with self._q_lock:
            if self._closed:
                raise StateError(f"state store {self.name!r} is closed")
            self._q_pending.append(row)
            if not self._q_flushing:
                try:
                    self._write_exec.submit(self._flush_writes)
                except RuntimeError:
                    # executor shut down (write after close): fail this
                    # call cleanly and leave the flag consistent
                    self._q_pending.remove(row)
                    raise
                self._q_flushing = True
        return await row.future

    # -- read cache --------------------------------------------------------

    def _cache_get(self, key: str) -> tuple[str, str] | None:
        if not self.cache_size:
            return None
        with self._cache_lock:
            hit = self._cache.get(key)
            if hit is None:
                return None
            self._cache.move_to_end(key)
            return hit

    def _cache_apply(self, mutations: list[tuple]) -> None:
        """Write-through: apply committed mutations to the cache (writer
        thread, after COMMIT — a rolled-back batch never touches it)."""
        if not self.cache_size or not mutations:
            return
        with self._cache_lock:
            for m in mutations:
                if m[0] == "set":
                    _, key, doc, etag = m
                    self._cache[key] = (doc, etag)
                    self._cache.move_to_end(key)
                else:
                    self._cache.pop(m[1], None)
            while len(self._cache) > self.cache_size:
                self._cache.popitem(last=False)

    # -- core ops ----------------------------------------------------------

    async def get(self, key: str) -> StateItem | None:
        hit = self._cache_get(key)
        if hit is not None:
            # parse per hit: callers may mutate the returned value, and
            # the cache must stay isolated (value-isolation contract)
            doc, etag = hit
            return StateItem(key=key, value=json.loads(doc), etag=etag)
        row = await self._run_read(self._get_sync, key)
        if row is None:
            return None
        return StateItem(key=key, value=json.loads(row[0]), etag=row[1])

    def _get_sync(self, key: str):
        return self._rconn.execute(
            "SELECT value, etag FROM state WHERE key = ?", (key,)).fetchone()

    async def bulk_get(self, keys: list[str]) -> list[StateItem | None]:
        out: list[StateItem | None] = [None] * len(keys)
        misses: list[int] = []
        for i, key in enumerate(keys):
            hit = self._cache_get(key)
            if hit is not None:
                out[i] = StateItem(key=key, value=json.loads(hit[0]), etag=hit[1])
            else:
                misses.append(i)
        if misses:
            rows = await self._run_read(
                self._bulk_get_sync, [keys[i] for i in misses])
            for i, row in zip(misses, rows):
                if row is not None:
                    out[i] = StateItem(key=keys[i], value=json.loads(row[0]),
                                       etag=row[1])
        return out

    def _bulk_get_sync(self, keys: list[str]):
        return [self._get_sync(k) for k in keys]

    async def set(self, key: str, value: Any, *, etag: str | None = None) -> str:
        doc = _encode(key, value)
        return await self._submit_write(("set", key, doc, etag))

    async def delete(self, key: str, *, etag: str | None = None) -> bool:
        return await self._submit_write(("delete", key, etag))

    async def transact(self, ops: list[TransactionOp]) -> None:
        encoded = [
            (op.operation, op.key,
             _encode(op.key, op.value) if op.operation == "upsert" else None,
             op.etag)
            for op in ops
        ]
        await self._submit_write(("transact", encoded))

    # -- staged (two-phase) transactions ----------------------------------

    async def stage_transact(self, ops: list[TransactionOp]) -> StagedTransaction:
        """Open this store's transaction, validate every etag, apply
        ``ops``, and return with the transaction HELD OPEN awaiting
        :meth:`StagedTransaction.commit` / ``rollback``.

        This is the per-shard primitive of the sharded facade's
        cross-shard commit (state/sharding.py). While staged, the
        writer thread is parked — it IS the commit slot, so queued
        group-commit flushes on this store wait behind the decision.
        A stage-phase failure (EtagMismatch, lock deadline) rolls back
        before this coroutine returns and re-raises: a failed stage
        never leaves a transaction open."""
        encoded = [
            (op.operation, op.key,
             _encode(op.key, op.value) if op.operation == "upsert" else None,
             op.etag)
            for op in ops
        ]
        loop = asyncio.get_running_loop()
        txn = StagedTransaction(loop)
        with self._q_lock:
            if self._closed:
                raise StateError(f"state store {self.name!r} is closed")
            try:
                self._write_exec.submit(self._stage_job, encoded, txn)
            except RuntimeError:
                raise StateError(
                    f"state store {self.name!r} is closed") from None
        await txn._staged
        return txn

    def _stage_job(self, ops: list[tuple], txn: StagedTransaction) -> None:
        """Writer thread: BEGIN + validate + apply, park on the
        coordinator's decision, then COMMIT or ROLLBACK."""
        fast = self._repl_fail_fast()
        if fast is not None:
            txn._resolve_staged(fast)
            return
        cur = self._conn.cursor()
        mutations: list[tuple] = []
        try:
            self._begin_immediate(cur)
            try:
                need = sum(1 for o in ops if o[0] == "upsert")
                seq = iter(range(self._reserve_etags(cur, need),
                                 2 ** 63)) if need else iter(())
                self._apply_transact(cur, ops, mutations,
                                     lambda: str(next(seq)))
            except BaseException:
                self._conn.rollback()
                raise
        except BaseException as exc:
            txn._resolve_staged(exc)
            return
        txn._resolve_staged(None)
        decision = txn._await_decision(self._STAGE_DECISION_TIMEOUT)
        try:
            if decision == "commit":
                rec = None
                try:
                    rec = self._repl_append(cur, mutations)
                    self._conn.commit()
                except BaseException:
                    self._conn.rollback()
                    raise
                self._dirty = True
                self._cache_apply(mutations)
                self._repl_committed(rec)
                repl = self._repl
                if rec is not None and repl is not None:
                    repl.on_commit(rec,
                                   lambda: txn._finish("committed", None),
                                   lambda qexc: txn._finish(None, qexc))
                else:
                    txn._finish("committed", None)
            else:
                self._conn.rollback()
                txn._finish("rolledback", None)
        except BaseException as exc:  # pragma: no cover - disk-level failure
            txn._finish(None, exc)

    # -- replication: follower apply + leader catch-up (writer thread) ----
    # All of these run on the writer executor (state/replication.py
    # submits them via run_in_executor), so they serialize with the
    # group-commit flusher on self._conn — a snapshot read never
    # interleaves with a half-applied batch.

    def apply_repl_records(self, records: list[dict]) -> int:
        """Apply leader records in order (follower side). Returns the
        new high-water mark. Epoch rules: a record below the member's
        epoch is a zombie's — :class:`ReplicaFencedError`. A record at
        a HIGHER epoch whose seq this member already holds means our
        own suffix diverged (we were the fenced ex-leader) — a
        ``diverged`` :class:`ReplicationGapError` asks for a snapshot.
        A same-epoch duplicate is skipped (records are idempotent by
        seq); a seq beyond hwm+1 is a plain gap answered by log
        catch-up."""
        if not records:
            return self._repl_hwm
        cur = self._conn.cursor()
        mutations: list[tuple] = []
        hwm, epoch = self._repl_hwm, self._repl_epoch
        max_etag_n = 0
        self._begin_immediate(cur)
        try:
            for rec in records:
                seq, rec_epoch = int(rec["seq"]), int(rec["epoch"])
                if rec_epoch < epoch:
                    raise ReplicaFencedError(
                        f"record epoch {rec_epoch} is behind member epoch "
                        f"{epoch} (fenced ex-leader)")
                if seq <= hwm:
                    if rec_epoch > epoch:
                        raise ReplicationGapError(
                            f"seq {seq} already held at epoch {epoch} but "
                            f"offered at epoch {rec_epoch}: diverged suffix",
                            hwm=hwm, diverged=True)
                    continue
                if seq != hwm + 1:
                    raise ReplicationGapError(
                        f"record seq {seq} does not extend hwm {hwm}",
                        hwm=hwm)
                for m in rec["ops"]:
                    if m[0] == "set":
                        cur.execute(self._SET_SQL, (m[1], m[2], m[3]))
                    else:
                        cur.execute("DELETE FROM state WHERE key = ?", (m[1],))
                    mutations.append(tuple(m))
                cur.execute(
                    "INSERT OR REPLACE INTO repl_log(seq, epoch, record) "
                    "VALUES (?, ?, ?)",
                    (seq, rec_epoch, json.dumps(rec, separators=(",", ":"))))
                hwm, epoch = seq, rec_epoch
                max_etag_n = max(max_etag_n, int(rec.get("etag_n", 0)))
            cur.execute("UPDATE repl_meta SET hwm = ?, epoch = ? WHERE id = 1",
                        (hwm, epoch))
            cur.execute("DELETE FROM repl_log WHERE seq <= ?",
                        (hwm - self._repl_retain,))
            if max_etag_n:
                # never move the sequence backwards: a promoted follower
                # must allocate etags fresher than anything the old
                # leader ever handed out
                cur.execute("UPDATE etag_seq SET n = ? WHERE id = 1 AND n < ?",
                            (max_etag_n, max_etag_n))
            self._conn.commit()
        except BaseException:
            self._conn.rollback()
            raise
        with self._repl_lock:
            self._repl_hwm, self._repl_epoch = hwm, epoch
        self._dirty = True
        self._cache_apply(mutations)
        return hwm

    def read_repl_log(self, after_seq: int, limit: int = 512) -> list[dict] | None:
        """Records strictly after ``after_seq`` in order, or ``None``
        when the log was pruned past the gap (the caller ships a
        snapshot instead)."""
        hwm, _epoch = self.repl_position()
        if after_seq >= hwm:
            return []
        rows = self._conn.execute(
            "SELECT record FROM repl_log WHERE seq > ? ORDER BY seq LIMIT ?",
            (after_seq, limit)).fetchall()
        records = [json.loads(r[0]) for r in rows]
        if not records or records[0]["seq"] != after_seq + 1:
            return None
        return records

    def read_repl_epoch_at(self, seq: int) -> int | None:
        """Epoch of this member's log entry at ``seq``, or ``None``
        when no such entry exists (pruned, or past our hwm). The
        leader uses this for the log-matching check: a follower whose
        (hwm, epoch) doesn't match our entry at its hwm has a
        divergent suffix and must be reinstalled from a snapshot."""
        row = self._conn.execute(
            "SELECT epoch FROM repl_log WHERE seq = ?", (seq,)).fetchone()
        return None if row is None else int(row[0])

    def read_repl_snapshot(self) -> dict:
        """Full-state snapshot at the current position; consistent
        because it runs on the single writer thread."""
        rows = self._conn.execute(
            "SELECT key, value, etag FROM state ORDER BY key").fetchall()
        (etag_n,) = self._conn.execute(
            "SELECT n FROM etag_seq WHERE id = 1").fetchone()
        hwm, epoch = self.repl_position()
        return {"rows": [list(r) for r in rows], "hwm": hwm,
                "epoch": epoch, "etag_n": etag_n}

    def install_repl_snapshot(self, snap: dict) -> None:
        """Replace this member's entire state with a leader snapshot —
        the resync path for a diverged suffix or a pruned-log gap."""
        cur = self._conn.cursor()
        self._begin_immediate(cur)
        try:
            cur.execute("DELETE FROM state")
            cur.execute("DELETE FROM repl_log")
            cur.executemany(
                "INSERT INTO state(key, value, etag) VALUES (?, ?, ?)",
                [tuple(r) for r in snap["rows"]])
            cur.execute("UPDATE repl_meta SET hwm = ?, epoch = ? WHERE id = 1",
                        (int(snap["hwm"]), int(snap["epoch"])))
            cur.execute("UPDATE etag_seq SET n = ? WHERE id = 1",
                        (int(snap["etag_n"]),))
            self._conn.commit()
        except BaseException:
            self._conn.rollback()
            raise
        with self._repl_lock:
            self._repl_hwm = int(snap["hwm"])
            self._repl_epoch = int(snap["epoch"])
        self._dirty = True
        with self._cache_lock:
            self._cache.clear()

    def append_repl_barrier(self, epoch: int) -> dict:
        """A new leader's first act: append an empty record at its
        (higher) epoch — Raft's no-op leadership barrier. Makes the
        epoch durable on this member and gives followers a record whose
        epoch proves the leadership change before any data flows."""
        cur = self._conn.cursor()
        self._begin_immediate(cur)
        try:
            seq = self._repl_hwm + 1
            (etag_n,) = cur.execute(
                "SELECT n FROM etag_seq WHERE id = 1").fetchone()
            record = {"seq": seq, "epoch": int(epoch), "ops": [],
                      "etag_n": etag_n, "ts": time.time(), "barrier": True}
            cur.execute(
                "INSERT INTO repl_log(seq, epoch, record) VALUES (?, ?, ?)",
                (seq, int(epoch), json.dumps(record, separators=(",", ":"))))
            cur.execute("UPDATE repl_meta SET hwm = ?, epoch = ? WHERE id = 1",
                        (seq, int(epoch)))
            self._conn.commit()
        except BaseException:
            self._conn.rollback()
            raise
        with self._repl_lock:
            self._repl_hwm, self._repl_epoch = seq, int(epoch)
        self._dirty = True
        return record

    def repl_position(self) -> tuple[int, int]:
        """(high-water mark, epoch) — safe from any thread."""
        with self._repl_lock:
            return self._repl_hwm, self._repl_epoch

    # -- query -------------------------------------------------------------

    async def query(self, query: dict, *, key_prefix: str = "") -> QueryResponse:
        if not isinstance(query, dict):
            raise QueryError("query must be a JSON object")
        filt = query.get("filter")
        validate_filter(filt)
        where, params = compile_filter(filt)
        order, order_params = compile_sort(query.get("sort"))
        sql = f"SELECT key, value, etag FROM state WHERE ({where})"
        all_params = [*params]
        if key_prefix:
            sql += r" AND key LIKE ? ESCAPE '\'"
            all_params.append(_like_escape(key_prefix) + "%")
        sql += f" {order}"
        all_params.extend(order_params)

        # Page in the engine: same offset-token format as query.paginate,
        # but via LIMIT/OFFSET so unmatched pages never leave SQLite.
        page = query.get("page") or {}
        limit = page.get("limit")
        token = page.get("token")
        start = 0
        if token is not None:
            try:
                start = int(token)
            except (TypeError, ValueError):
                raise QueryError(f"bad page token {token!r}") from None
            if start < 0:
                raise QueryError(f"bad page token {token!r}")
        if limit is not None and (not isinstance(limit, int) or limit <= 0):
            raise QueryError("page.limit must be a positive integer")
        if limit is not None:
            sql += " LIMIT ? OFFSET ?"
            all_params.extend([limit + 1, start])  # +1 probes for a next page
        elif start:
            sql += " LIMIT -1 OFFSET ?"
            all_params.append(start)

        rows = await self._run_read(self._query_sync, sql, all_params)
        next_token = None
        if limit is not None and len(rows) > limit:
            rows = rows[:limit]
            next_token = str(start + limit)
        items = [StateItem(key=k, value=json.loads(v), etag=e) for k, v, e in rows]
        return QueryResponse(items=items, token=next_token)

    def _query_sync(self, sql: str, params: list[Any]):
        try:
            return self._rconn.execute(sql, params).fetchall()
        except sqlite3.Error as exc:
            raise QueryError(f"query failed: {exc}") from exc

    async def keys(self, *, prefix: str = "") -> list[str]:
        return await self._run_read(self._keys_sync, prefix)

    def _keys_sync(self, prefix: str) -> list[str]:
        if prefix:
            rows = self._rconn.execute(
                r"SELECT key FROM state WHERE key LIKE ? ESCAPE '\' ORDER BY key",
                (_like_escape(prefix) + "%",),
            ).fetchall()
        else:
            rows = self._rconn.execute(
                "SELECT key FROM state ORDER BY key").fetchall()
        return [r[0] for r in rows]

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Drain the write queue, stop the checkpointer, close both
        connections. Synchronous so out-of-band (no event loop) users
        and the component registry's sync-close path both work."""
        with self._q_lock:
            if self._closed:
                return
            self._closed = True
            if self._q_pending and not self._q_flushing:
                try:
                    self._write_exec.submit(self._flush_writes)
                    self._q_flushing = True
                except RuntimeError:  # pragma: no cover - already shut down
                    pass
        self._write_exec.shutdown(wait=True)
        with self._q_lock:
            stragglers, self._q_pending = self._q_pending, []
        for row in stragglers:  # pragma: no cover - shutdown race only
            _resolve(row, None, StateError(f"state store {self.name!r} is closed"))
        if self._read_exec is not self._write_exec:
            self._read_exec.shutdown(wait=True)
        self._ckpt_stop.set()
        if self._ckpt_thread is not None:
            self._ckpt_thread.join(timeout=5.0)
        if self._is_file:
            try:
                # fold the WAL back into the db so the file is complete
                # on its own (the checkpointer thread is gone now)
                self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
            except sqlite3.Error:  # pragma: no cover - best effort
                pass
        if self._rconn is not self._conn:
            self._rconn.close()
        self._conn.close()


def _shard_path(path: str, index: int) -> str:
    """Shard ``index``'s file for a component rooted at ``path``:
    ``tasks.db`` → ``tasks-shard0.db``, ``tasks-shard1.db``, …
    ``":memory:"`` passes through — every sqlite connection to it gets
    a private database, which is exactly one private shard."""
    if path == ":memory:":
        return path
    p = pathlib.Path(path)
    return str(p.with_name(f"{p.stem}-shard{index}{p.suffix}"))


def build_sharded_store(name: str, path: str | pathlib.Path = ":memory:", *,
                        shards: int, hash_seed: str = "",
                        group_commit: bool = True,
                        cache_size: int = 0) -> "ShardedStateStore":
    """N independent group-commit engines behind one facade.

    Each child is a full :class:`SqliteStateStore` (own writer/flusher
    threads, WAL, checkpointer) on its own ``-shardN`` file; the
    facade routes by rendezvous hash (state/sharding.py). The read
    cache budget is split across shards so the component's total
    memory stays what ``readCacheSize`` promised."""
    from tasksrunner.state.sharding import MAX_SHARDS, ShardedStateStore
    if shards < 1 or shards > MAX_SHARDS:
        # validate BEFORE constructing children: each child spins up
        # threads and connections that a late router error would leak
        raise ComponentError(
            f"state store {name!r}: shards must be in 1..{MAX_SHARDS}, "
            f"not {shards}")
    per_shard_cache = (max(1, cache_size // shards) if cache_size else 0)

    def _make_child(i: int) -> SqliteStateStore:
        return SqliteStateStore(
            name, _shard_path(str(path), i),
            group_commit=group_commit,
            cache_size=per_shard_cache,
            shard=i)

    facade = ShardedStateStore(
        name, [_make_child(i) for i in range(shards)], hash_seed=hash_seed)
    # online split (PR 20) mints engine N on its own -shardN file
    # through the same constructor the boot path used
    facade._child_factory = _make_child
    return facade


@driver("state.sqlite", "state.azure.cosmosdb", "state.postgresql")
def _sqlite_state(spec: ComponentSpec, metadata: dict[str, str]) -> StateStore:
    """Durable local engine; `databasePath` metadata picks the file
    (defaults to in-memory). Cloud-typed component files (cosmos/postgres)
    map here so they run unchanged in local mode. ``groupCommit``
    (default true) coalesces concurrent writes into one transaction;
    ``readCacheSize`` (default 0 = off) bounds the write-through LRU
    read cache — enable it only where this app is the file's sole
    writer.

    ``shards`` (default 1) partitions the component across N shard
    files by rendezvous key hash, each with its own writer/flusher/
    checkpointer — the write-throughput scaling knob. ``shards: 1``
    keeps today's single-file layout and code path bit-for-bit (a
    plain SqliteStateStore, no facade). ``hashSeed`` (default empty)
    perturbs the key→shard assignment; it must be identical on every
    replica opening the same files.

    ``replicas`` (default 1) turns each shard into a replica set of
    that many members with leased leadership, epoch fencing, and
    ack-after-replication (state/replication.py). ``ackQuorum``
    (default: majority) is the ack count a write needs including the
    leader; ``followerReads: true`` serves reads from followers when
    their lag is within ``maxLagRecords`` (default
    ``TASKSRUNNER_REPL_MAX_LAG_RECORDS``). ``replicas: 1`` is exactly
    today's unreplicated engine — no extra tables, no meta store."""
    shards = metadata_int(metadata, "shards", 1)
    replicas = metadata_int(metadata, "replicas", 1)
    path = metadata.get("databasePath", ":memory:")
    group_commit = metadata_bool(metadata, "groupCommit", True)
    cache_size = metadata_int(metadata, "readCacheSize", 0)
    if replicas > 1:
        from tasksrunner.state.replication import build_replicated_store
        ack_quorum = metadata_int(metadata, "ackQuorum", 0)
        max_lag = metadata_int(metadata, "maxLagRecords", 0)
        return build_replicated_store(
            spec.name, path, shards=shards, replicas=replicas,
            ack_quorum=ack_quorum or None,
            hash_seed=metadata.get("hashSeed", ""),
            group_commit=group_commit, cache_size=cache_size,
            follower_reads=metadata_bool(metadata, "followerReads", False),
            max_lag=max_lag or None,
        )
    if shards == 1:
        # no facade, no -shard0 rename: the single-shard layout stays
        # bit-for-bit today's (hashSeed is moot — one shard wins every
        # rendezvous regardless of seed)
        return SqliteStateStore(
            spec.name, path,
            group_commit=group_commit, cache_size=cache_size,
        )
    return build_sharded_store(
        spec.name, path, shards=shards,
        hash_seed=metadata.get("hashSeed", ""),
        group_commit=group_commit, cache_size=cache_size,
    )
