"""SQLite-backed state store — the durable local engine.

Fills the slot Cosmos DB fills in the reference (component
``state.azure.cosmosdb``, components/dapr-statestore-cosmos.yaml):
durable, queryable document state. The type alias means the reference's
cloud component file runs unchanged against this engine locally.

The filter/sort dialect (state/query.py) is compiled to SQL over
``json_extract`` so filtering happens in the engine, not in Python —
the framework-level analog of Cosmos executing the JSON query
server-side rather than the sidecar scanning keys.
"""

from __future__ import annotations

import json
import pathlib
import sqlite3
from typing import Any

from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import EtagMismatch, QueryError, StateError
from tasksrunner.state.base import QueryResponse, StateItem, StateStore, TransactionOp
from tasksrunner.state.query import validate_filter

_SCHEMA = """
CREATE TABLE IF NOT EXISTS state (
    key   TEXT PRIMARY KEY,
    value TEXT NOT NULL,
    etag  TEXT NOT NULL
) WITHOUT ROWID;
CREATE TABLE IF NOT EXISTS etag_seq (
    id  INTEGER PRIMARY KEY CHECK (id = 1),
    n   INTEGER NOT NULL
);
INSERT OR IGNORE INTO etag_seq(id, n) VALUES (1, 0);
"""


def _like_escape(prefix: str) -> str:
    return prefix.replace("\\", "\\\\").replace("%", "\\%").replace("_", "\\_")


def _param(value: Any) -> Any:
    """Bind a JSON scalar the way json_extract represents it."""
    if isinstance(value, bool):
        return int(value)
    return value


def compile_filter(filt: Any) -> tuple[str, list[Any]]:
    """Compile a validated filter to a WHERE fragment + params.

    Semantics must match state.query.matches exactly; the contract
    suite in tests/test_state.py runs both engines on the same cases.
    """
    if filt in (None, {}):
        return "1", []
    op, operand = next(iter(filt.items()))
    if op in ("AND", "OR"):
        parts, params = [], []
        for sub in operand:
            frag, p = compile_filter(sub)
            parts.append(f"({frag})")
            params.extend(p)
        return f" {op} ".join(parts), params
    path, expected = next(iter(operand.items()))
    col = "json_extract(value, ?)"
    jpath = "$." + path
    if op == "EQ":
        return f"{col} IS ?", [jpath, _param(expected)]
    if op == "NEQ":
        return f"{col} IS NOT ?", [jpath, _param(expected)]
    if op == "IN":
        if not expected:
            return "0", []
        placeholders = ", ".join("?" for _ in expected)
        frag = f"{col} IN ({placeholders})"
        params: list[Any] = [jpath, *(_param(v) for v in expected)]
        if any(v is None for v in expected):
            frag = f"({frag} OR json_extract(value, ?) IS NULL)"
            params.append(jpath)
        return frag, params
    raise QueryError(f"unknown filter operator {op!r}")


def compile_sort(sort_spec: list[dict] | None) -> tuple[str, list[Any]]:
    if not sort_spec:
        return "ORDER BY key", []
    clauses, params = [], []
    for clause in sort_spec:
        if not isinstance(clause, dict) or "key" not in clause:
            raise QueryError("each sort clause needs a key")
        order = str(clause.get("order", "ASC")).upper()
        if order not in ("ASC", "DESC"):
            raise QueryError(f"sort order must be ASC or DESC, not {clause.get('order')!r}")
        clauses.append(f"json_extract(value, ?) {order}")
        params.append("$." + clause["key"])
    return "ORDER BY " + ", ".join(clauses), params


class SqliteStateStore(StateStore):
    def __init__(self, name: str, path: str | pathlib.Path = ":memory:"):
        super().__init__(name)
        self.path = str(path)
        if self.path != ":memory:":
            pathlib.Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        # WAL + NORMAL: fsync at checkpoint, not per-commit — the
        # standard durability/throughput point for local engines
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.executescript(_SCHEMA)
        self._conn.commit()

    # -- core ops --------------------------------------------------------

    async def get(self, key: str) -> StateItem | None:
        row = self._conn.execute(
            "SELECT value, etag FROM state WHERE key = ?", (key,)
        ).fetchone()
        if row is None:
            return None
        return StateItem(key=key, value=json.loads(row[0]), etag=row[1])

    #: RETURNING needs sqlite >= 3.35 (2021); fall back to the
    #: two-statement form on older system libsqlite3 builds
    _HAS_RETURNING = sqlite3.sqlite_version_info >= (3, 35, 0)

    def _next_etag(self, cur: sqlite3.Cursor) -> str:
        # Store-global monotonic sequence: a deleted-and-recreated key
        # never reuses an old etag, so stale tokens from a previous
        # incarnation of the key can't validate.
        if self._HAS_RETURNING:
            (n,) = cur.execute(
                "UPDATE etag_seq SET n = n + 1 WHERE id = 1 RETURNING n").fetchone()
        else:
            cur.execute("UPDATE etag_seq SET n = n + 1 WHERE id = 1")
            (n,) = cur.execute("SELECT n FROM etag_seq WHERE id = 1").fetchone()
        return str(n)

    def _set_tx(self, cur: sqlite3.Cursor, key: str, value: Any, etag: str | None) -> str:
        if etag is not None:
            row = cur.execute("SELECT etag FROM state WHERE key = ?", (key,)).fetchone()
            if row is None or row[0] != etag:
                raise EtagMismatch(f"etag mismatch for key {key!r}")
        new_etag = self._next_etag(cur)
        try:
            # allow_nan=False: NaN/Infinity would poison json_extract for
            # every later query on the store; reject at write time the way
            # a real document DB does.
            doc = json.dumps(value, separators=(",", ":"), allow_nan=False)
        except ValueError as exc:
            raise StateError(f"value for key {key!r} is not valid JSON: {exc}") from exc
        cur.execute(
            "INSERT INTO state(key, value, etag) VALUES(?, ?, ?) "
            "ON CONFLICT(key) DO UPDATE SET value=excluded.value, etag=excluded.etag",
            (key, doc, new_etag),
        )
        return new_etag

    async def set(self, key: str, value: Any, *, etag: str | None = None) -> str:
        cur = self._conn.cursor()
        try:
            cur.execute("BEGIN IMMEDIATE")
            new_etag = self._set_tx(cur, key, value, etag)
            self._conn.commit()
            return new_etag
        except BaseException:
            self._conn.rollback()
            raise

    async def delete(self, key: str, *, etag: str | None = None) -> bool:
        cur = self._conn.cursor()
        try:
            cur.execute("BEGIN IMMEDIATE")
            row = cur.execute("SELECT etag FROM state WHERE key = ?", (key,)).fetchone()
            if row is None:
                if etag is not None:
                    raise EtagMismatch(f"etag mismatch for key {key!r}")
                self._conn.commit()
                return False
            if etag is not None and row[0] != etag:
                raise EtagMismatch(f"etag mismatch for key {key!r}")
            cur.execute("DELETE FROM state WHERE key = ?", (key,))
            self._conn.commit()
            return True
        except BaseException:
            self._conn.rollback()
            raise

    async def transact(self, ops: list[TransactionOp]) -> None:
        """Contract (matches the memory engine): all etags validate
        against the *pre-transaction* state, then ops apply in order."""
        cur = self._conn.cursor()
        try:
            cur.execute("BEGIN IMMEDIATE")
            for op in ops:
                if op.etag is None:
                    continue
                row = cur.execute(
                    "SELECT etag FROM state WHERE key = ?", (op.key,)
                ).fetchone()
                if row is None or row[0] != op.etag:
                    raise EtagMismatch(f"etag mismatch for key {op.key!r}")
            for op in ops:
                if op.operation == "upsert":
                    self._set_tx(cur, op.key, op.value, None)
                else:
                    cur.execute("DELETE FROM state WHERE key = ?", (op.key,))
            self._conn.commit()
        except BaseException:
            self._conn.rollback()
            raise

    # -- query -----------------------------------------------------------

    async def query(self, query: dict, *, key_prefix: str = "") -> QueryResponse:
        if not isinstance(query, dict):
            raise QueryError("query must be a JSON object")
        filt = query.get("filter")
        validate_filter(filt)
        where, params = compile_filter(filt)
        order, order_params = compile_sort(query.get("sort"))
        sql = f"SELECT key, value, etag FROM state WHERE ({where})"
        all_params = [*params]
        if key_prefix:
            sql += r" AND key LIKE ? ESCAPE '\'"
            all_params.append(_like_escape(key_prefix) + "%")
        sql += f" {order}"
        all_params.extend(order_params)

        # Page in the engine: same offset-token format as query.paginate,
        # but via LIMIT/OFFSET so unmatched pages never leave SQLite.
        page = query.get("page") or {}
        limit = page.get("limit")
        token = page.get("token")
        start = 0
        if token is not None:
            try:
                start = int(token)
            except (TypeError, ValueError):
                raise QueryError(f"bad page token {token!r}") from None
            if start < 0:
                raise QueryError(f"bad page token {token!r}")
        if limit is not None and (not isinstance(limit, int) or limit <= 0):
            raise QueryError("page.limit must be a positive integer")
        if limit is not None:
            sql += " LIMIT ? OFFSET ?"
            all_params.extend([limit + 1, start])  # +1 probes for a next page
        elif start:
            sql += " LIMIT -1 OFFSET ?"
            all_params.append(start)

        try:
            rows = self._conn.execute(sql, all_params).fetchall()
        except sqlite3.Error as exc:
            raise QueryError(f"query failed: {exc}") from exc
        next_token = None
        if limit is not None and len(rows) > limit:
            rows = rows[:limit]
            next_token = str(start + limit)
        items = [StateItem(key=k, value=json.loads(v), etag=e) for k, v, e in rows]
        return QueryResponse(items=items, token=next_token)

    async def keys(self, *, prefix: str = "") -> list[str]:
        if prefix:
            rows = self._conn.execute(
                r"SELECT key FROM state WHERE key LIKE ? ESCAPE '\' ORDER BY key",
                (_like_escape(prefix) + "%",),
            ).fetchall()
        else:
            rows = self._conn.execute("SELECT key FROM state ORDER BY key").fetchall()
        return [r[0] for r in rows]

    def close(self) -> None:
        self._conn.close()


@driver("state.sqlite", "state.azure.cosmosdb", "state.postgresql")
def _sqlite_state(spec: ComponentSpec, metadata: dict[str, str]) -> SqliteStateStore:
    """Durable local engine; `databasePath` metadata picks the file
    (defaults to in-memory). Cloud-typed component files (cosmos/postgres)
    map here so they run unchanged in local mode."""
    return SqliteStateStore(spec.name, metadata.get("databasePath", ":memory:"))
