"""Mesh-framed transport for the replication record stream.

The in-process replica sets (state/replication.py) wire members with
direct :class:`~tasksrunner.state.replication.LocalLink` calls; this
module carries the same three-verb protocol — ``append`` / ``install``
/ ``position`` — across processes over the mesh lane's frame format
(``[u32 frame_len][u32 header_len][header][body]``, invoke/mesh.py),
so a follower can live on another host and a ``kill -9`` of the leader
*process* is survivable, not just a leader *object* going away.

The lane inherits the invoke mesh's per-connection header codec: the
shipper sends the same JSON hello on connect, and when both ends are
v2 builds the three-verb headers travel struct-packed
(:class:`~tasksrunner.invoke.mesh.BinaryHeaderCodec` kinds 5/6)
instead of as JSON — a pre-v2 peer on either side degrades the
connection to the v1 JSON headers, exactly like the invoke lane, so
replication keeps flowing through a rolling upgrade.

Error mapping is explicit: a follower's
:class:`~tasksrunner.errors.ReplicationGapError` and
:class:`~tasksrunner.errors.ReplicaFencedError` are protocol signals
the leader's shipper must see typed (gap → catch-up or snapshot,
fenced → fence the session), so they travel as structured reply
headers (``kind: gap|fenced``) and are re-raised as the same classes
on the caller side. Everything else is an opaque transport failure
(OSError) the shipper retries with backoff.

Requests on one connection are strictly serial request/response — the
shipper is a single loop per follower, so multiplexing would buy
nothing here (unlike the invoke lane).
"""

from __future__ import annotations

import asyncio
import json
import logging

from tasksrunner.errors import ReplicaFencedError, ReplicationGapError
from tasksrunner.invoke.mesh import (
    MAX_FRAME,
    JsonHeaderCodec,
    _read_frame,
    connect_timeout,
    negotiate_client,
    negotiate_server,
    pack_frame,
)
from tasksrunner.state.replication import ReplicationNode, _batch_tp

logger = logging.getLogger(__name__)

#: per-request ceiling: a snapshot install on a slow disk is the worst
#: legitimate case; far below the invoke lane's 300 s — a hung peer
#: must fail the shipment (and eventually the ack quorum), not park it
REPL_REQUEST_TIMEOUT = 30.0


class ReplicationServer:
    """Exposes local follower members to remote leaders.

    One server per process; members register by ``(store, shard)``.
    The handler loop is serial per connection, mirroring the client's
    one-request-at-a-time shipper."""

    def __init__(self, *, host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        self.host = host
        self.port = port
        self._ssl = ssl_context
        self._nodes: dict[tuple[str, int], ReplicationNode] = {}
        self._server: asyncio.AbstractServer | None = None

    def register(self, node: ReplicationNode) -> None:
        self._nodes[(node.name, node.shard)] = node

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, ssl=self._ssl)
        self.port = self._server.sockets[0].getsockname()[1]

    async def aclose(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            # the FIRST frame picks the codec: a v2 shipper's hello, or
            # a legacy shipper's first real request (stays JSON)
            codec, first = await negotiate_server(reader, writer,
                                                  max_body=MAX_FRAME)
            while True:
                if first is not None:
                    header, body = first
                    first = None
                else:
                    header, body = await _read_frame(reader, codec,
                                                     max_body=MAX_FRAME)
                resp_header, resp_body = await self._dispatch(header, body)
                writer.writelines(pack_frame(codec, resp_header, resp_body))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # peer went away; its shipper reconnects
        finally:
            writer.close()
            try:
                await asyncio.shield(writer.wait_closed())
            except (ConnectionError, OSError):  # pragma: no cover
                pass

    async def _dispatch(self, header: dict,
                        body: bytes | None) -> tuple[dict, bytes]:
        node = self._nodes.get(
            (header.get("store"), int(header.get("shard", 0))))
        if node is None:
            return ({"ok": False, "kind": "error",
                     "error": f"no replica member for "
                              f"{header.get('store')!r} shard "
                              f"{header.get('shard')}"}, b"")
        op = header.get("op")
        try:
            if op == "append":
                hwm = await node.apply_records(json.loads(body or b"[]"))
                return {"ok": True}, json.dumps({"hwm": hwm}).encode()
            if op == "install":
                await node.install_snapshot(json.loads(body or b"{}"))
                return {"ok": True}, b"{}"
            if op == "position":
                hwm, epoch = node.position()
                return ({"ok": True},
                        json.dumps({"hwm": hwm, "epoch": epoch}).encode())
            if op == "describe":
                # elastic placement (PR 20): the migration tooling's
                # view of a remote member — position plus role, enough
                # for a cross-host catch-up poll without a leader-side
                # replicator to read _member_hwm from
                hwm, epoch = node.position()
                return ({"ok": True}, json.dumps({
                    "hwm": hwm, "epoch": epoch,
                    "member": node.node_id,
                    "leader": node.is_leader,
                    "needs_resync": node._needs_resync,
                }).encode())
            return ({"ok": False, "kind": "error",
                     "error": f"unknown replication op {op!r}"}, b"")
        except ReplicationGapError as exc:
            return ({"ok": False, "kind": "gap", "hwm": exc.hwm,
                     "diverged": exc.diverged}, b"")
        except ReplicaFencedError as exc:
            return {"ok": False, "kind": "fenced", "error": str(exc)}, b""
        except Exception as exc:
            logger.debug("replication server op %s failed", op, exc_info=True)
            return ({"ok": False, "kind": "error",
                     "error": f"{type(exc).__name__}: {exc}"}, b"")


class MeshFollowerLink:
    """Leader-side handle on a REMOTE follower — the cross-process
    drop-in for ``LocalLink`` (same verbs, same typed errors, same
    optional chaos gate on the lane)."""

    def __init__(self, store: str, shard: int, member: str,
                 host: str, port: int, *, ssl_context=None,
                 timeout: float = REPL_REQUEST_TIMEOUT):
        self.store = store
        self.shard = int(shard)
        self.member = member
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self.chaos = None  # ChaosPolicy | None
        self._ssl = ssl_context
        self._codec = JsonHeaderCodec
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._lock = asyncio.Lock()

    async def _chaos_gate(self) -> None:
        if self.chaos is not None:
            status = await self.chaos.before_call()
            if status is not None:
                self.chaos.raise_for_status(status)

    async def _teardown(self) -> None:
        writer, self._reader, self._writer = self._writer, None, None
        self._codec = JsonHeaderCodec
        if writer is not None:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _request(self, op: str, payload, tp: str | None = None) -> dict:
        async with self._lock:
            if self._writer is None:
                self._reader, self._writer = await asyncio.wait_for(
                    asyncio.open_connection(self.host, self.port,
                                            ssl=self._ssl),
                    connect_timeout())
                try:
                    self._codec, _ = await negotiate_client(
                        self._reader, self._writer,
                        timeout=connect_timeout())
                except (OSError, asyncio.IncompleteReadError,
                        ConnectionError, asyncio.TimeoutError):
                    await self._teardown()
                    raise
            header = {"op": op, "store": self.store, "shard": self.shard}
            if tp is not None:
                # the shipment's trace context: struct-packed by the v2
                # codec, a plain extra key under JSON v1 (legacy peers
                # ignore it — they degrade to no-context, not to error)
                header["tp"] = tp
            body = (b"" if payload is None
                    else json.dumps(payload, separators=(",", ":")).encode())
            try:
                self._writer.writelines(pack_frame(self._codec, header, body))
                await self._writer.drain()
                resp, resp_body = await asyncio.wait_for(
                    _read_frame(self._reader, self._codec), self.timeout)
            except (OSError, asyncio.IncompleteReadError, ConnectionError,
                    asyncio.TimeoutError):
                await self._teardown()
                raise
        if resp.get("ok"):
            return json.loads(resp_body) if resp_body else {}
        kind = resp.get("kind")
        if kind == "gap":
            raise ReplicationGapError(
                f"follower {self.member} reports a log gap",
                hwm=int(resp.get("hwm", 0)),
                diverged=bool(resp.get("diverged", False)))
        if kind == "fenced":
            raise ReplicaFencedError(
                resp.get("error") or f"follower {self.member}: fenced")
        raise OSError(
            f"replication peer {self.member} error: {resp.get('error')}")

    async def append(self, records: list[dict]) -> int:
        await self._chaos_gate()
        return int((await self._request(
            "append", records, tp=_batch_tp(records)))["hwm"])

    async def install(self, snapshot: dict) -> None:
        await self._chaos_gate()
        await self._request("install", snapshot)

    async def position(self) -> tuple[int, int]:
        reply = await self._request("position", None)
        return int(reply["hwm"]), int(reply["epoch"])

    async def describe(self) -> dict:
        """Role + position of the remote member (elastic-placement
        tooling; not on the shipment hot path, so no chaos gate)."""
        return await self._request("describe", None)

    async def aclose(self) -> None:
        await self._teardown()
