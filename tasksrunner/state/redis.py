"""Redis-backed state store (``state.redis``).

Parity slot: the reference's default local state store is the Redis
container that ``dapr init`` installs (docs/aca/04-aca-dapr-stateapi/
index.md:29-33); module 4 swaps it for Cosmos by editing component
YAML only. This driver fills the same slot over a real RESP socket.

Two behaviors the reference pins down:

* **plain Redis cannot serve the filter-query dialect** — the workshop
  calls this out explicitly (docs/aca/04-aca-dapr-stateapi/
  index.md:166-168: querying "requires Cosmos DB"); so
  ``supports_query = False`` here and ``query()`` raises, exactly the
  failure a user of the reference would hit.
* **etag concurrency**: each document carries an etag; compare-and-set
  runs as WATCH/MULTI/EXEC so a concurrent writer aborts the EXEC and
  the mismatch is detected, never lost (fixes the read-modify-write
  window SURVEY.md §5.2 notes in TasksStoreManager.cs:84-101).

Document layout: one Redis string per key holding
``{"v": <value>, "etag": "<n>"}``.
"""

from __future__ import annotations

import json
import uuid
from typing import Any

from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import EtagMismatch, QueryError, StateError
from tasksrunner.redisproto import CleanExit, RedisClient, as_str
from tasksrunner.state.base import QueryResponse, StateItem, StateStore


def _new_etag() -> str:
    return uuid.uuid4().hex[:16]


class RedisStateStore(StateStore):
    supports_query = False

    def __init__(self, name: str, host: str):
        super().__init__(name)
        self.client = RedisClient(host)

    # -- helpers

    @staticmethod
    def _decode(raw: bytes | None, key: str) -> StateItem | None:
        if raw is None:
            return None
        doc = json.loads(raw)
        return StateItem(key=key, value=doc["v"], etag=doc["etag"])

    @staticmethod
    def _encode(value: Any, etag: str) -> str:
        return json.dumps({"v": value, "etag": etag}, separators=(",", ":"))

    # -- StateStore API

    async def get(self, key: str) -> StateItem | None:
        return self._decode(await self.client.execute("GET", key), key)

    async def set(self, key: str, value: Any, *, etag: str | None = None) -> str:
        new_etag = _new_etag()
        if etag is None:
            await self.client.execute("SET", key, self._encode(value, new_etag))
            return new_etag
        # CAS: WATCH the key so a concurrent write voids the EXEC. A
        # mismatch exits via CleanExit — the UNWATCH already ran, so the
        # pooled connection is reused, not retired.
        while True:
            async with self.client.acquire() as conn:
                await conn.execute("WATCH", key)
                current = self._decode(await conn.execute("GET", key), key)
                if current is None or current.etag != etag:
                    await conn.execute("UNWATCH")
                    raise CleanExit(EtagMismatch(
                        f"{self.name}: etag mismatch on {key!r}"))
                await conn.execute("MULTI")
                await conn.execute("SET", key, self._encode(value, new_etag))
                if await conn.execute("EXEC") is not None:
                    return new_etag
            # EXEC aborted → someone wrote between WATCH and EXEC; re-read

    async def delete(self, key: str, *, etag: str | None = None) -> bool:
        if etag is None:
            return await self.client.execute("DEL", key) > 0
        while True:
            async with self.client.acquire() as conn:
                await conn.execute("WATCH", key)
                current = self._decode(await conn.execute("GET", key), key)
                if current is None:
                    await conn.execute("UNWATCH")
                    return False
                if current.etag != etag:
                    await conn.execute("UNWATCH")
                    raise CleanExit(EtagMismatch(
                        f"{self.name}: etag mismatch on {key!r}"))
                await conn.execute("MULTI")
                await conn.execute("DEL", key)
                if await conn.execute("EXEC") is not None:
                    return True

    async def bulk_get(self, keys: list[str]) -> list[StateItem | None]:
        if not keys:
            return []
        raws = await self.client.execute("MGET", *keys)
        return [self._decode(raw, key) for key, raw in zip(keys, raws)]

    async def query(self, query: dict, *, key_prefix: str = "") -> QueryResponse:
        raise QueryError(
            f"state store {self.name!r} (state.redis) does not support the "
            "filter-query dialect; use a query-capable store "
            "(state.sqlite / state.azure.cosmosdb) — the reference "
            "documents the same limitation for plain Redis "
            "(docs/aca/04-aca-dapr-stateapi/index.md:166-168)")

    async def keys(self, *, prefix: str = "") -> list[str]:
        # escape every MATCH metacharacter so the prefix is literal
        literal = (prefix.replace("\\", "\\\\").replace("*", "\\*")
                   .replace("?", "\\?").replace("[", "\\["))
        pattern = literal + "*" if prefix else "*"
        cursor, out = "0", []
        while True:
            reply = await self.client.execute(
                "SCAN", cursor, "MATCH", pattern, "COUNT", 512)
            cursor = as_str(reply[0])
            out.extend(as_str(k) for k in reply[1])
            if cursor == "0":
                break
        return sorted(out)

    def close(self) -> None:
        # pool sockets are torn down by GC/loop close; async close is
        # available for callers holding a loop
        pass

    async def aclose(self) -> None:
        await self.client.aclose()


@driver("state.redis")
def _redis_state(spec: ComponentSpec, metadata: dict[str, str]) -> RedisStateStore:
    """`redisHost` metadata (the reference's component shape,
    components/dapr-pubsub-redis.yaml:10-11) names the server."""
    host = metadata.get("redisHost")
    if not host:
        raise StateError(
            f"component {spec.name!r}: state.redis requires redisHost metadata")
    return RedisStateStore(spec.name, host)
