"""Key-prefix strategies for state isolation between apps.

The reference stores task state under ``"{app-id}||{taskId}"`` and
teaches the prefix strategies ``appid`` (default), ``name``, a constant
namespace, and ``none`` (docs/aca/04-aca-dapr-stateapi/index.md, "Key
Prefix Strategies"; SURVEY.md §5.4). The prefix is applied at the
sidecar layer — stores only ever see final keys — and is configured per
component via ``keyPrefix`` metadata.
"""

from __future__ import annotations

from tasksrunner.errors import ComponentError

SEPARATOR = "||"


class KeyPrefixer:
    """Computes the storage key for (app_id, user_key)."""

    def __init__(self, strategy: str = "appid", *, app_id: str | None = None,
                 component_name: str | None = None):
        self.strategy = strategy
        if strategy == "appid":
            self._prefix = f"{app_id}{SEPARATOR}" if app_id else ""
        elif strategy == "name":
            if not component_name:
                raise ComponentError("keyPrefix=name requires a component name")
            self._prefix = f"{component_name}{SEPARATOR}"
        elif strategy == "none":
            self._prefix = ""
        else:
            # any other literal acts as a constant namespace
            self._prefix = f"{strategy}{SEPARATOR}"

    @property
    def prefix(self) -> str:
        return self._prefix

    def apply(self, key: str) -> str:
        return self._prefix + key

    def strip(self, storage_key: str) -> str:
        if self._prefix and storage_key.startswith(self._prefix):
            return storage_key[len(self._prefix):]
        return storage_key


def prefixer_for(metadata: dict[str, str], *, app_id: str | None,
                 component_name: str) -> KeyPrefixer:
    return KeyPrefixer(
        metadata.get("keyPrefix", "appid"),
        app_id=app_id,
        component_name=component_name,
    )
