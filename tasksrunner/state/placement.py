"""Elastic shard placement: the epoched routing table and the load
signals that drive it.

PR 5's :class:`~tasksrunner.state.sharding.ShardRouter` answers *which
shard* a key belongs to — a pure function of ``(key, seed, shards)``,
frozen at component build. This module adds the mutable layer the
control loop needs to move shards while they serve:

:class:`PlacementMap`
    version + per-shard host assignment, layered over the HRW router.
    Every live migration or shard split commits by *replacing* the map
    with a successor whose ``epoch`` is strictly higher — one attribute
    store, atomic under asyncio — and every state request is validated
    against the current epoch (``ShardedStateStore.check_epoch``). A
    stale router therefore gets a 409-with-new-epoch redirect
    (:class:`~tasksrunner.errors.PlacementEpochError`), never a write
    applied at the wrong shard. Same fencing contract as the actor
    placement table (PR 7) and the shard lease (PR 9), one layer up.

:class:`ShardHeatTracker`
    per-shard write-rate EWMA plus a bounded hot-key sketch. The
    orchestrator's control loop (orchestrator/placement.py) merges
    these across replicas into the hot/cold ranking; hysteresis lives
    here too — a shard ranks hot only after staying above the
    threshold for a full ``TASKSRUNNER_RESHARD_HYSTERESIS_SECONDS``
    window, so a spike cannot trigger rebalance thrash.

The helpers at the bottom (:func:`merge_heat_docs`,
:func:`rank_shards`, :func:`plan_rebalance`) are the pure planning
half of the control loop, kept here so tests exercise them without an
orchestrator.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Iterable

from tasksrunner.errors import ComponentError

__all__ = [
    "PLACEMENT_EPOCH_HEADER", "PlacementMap", "ShardHeatTracker",
    "heat_threshold_default", "hysteresis_default",
    "pause_budget_default", "merge_heat_docs", "rank_shards",
    "plan_rebalance",
]

#: request header a routing-aware client sends with its cached epoch;
#: the sidecar echoes it on a 409 carrying the CURRENT epoch, so one
#: round trip both rejects the stale write and refreshes the cache
PLACEMENT_EPOCH_HEADER = "x-tasksrunner-placement-epoch"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def heat_threshold_default() -> float:
    return _env_float("TASKSRUNNER_RESHARD_HEAT_THRESHOLD", 50.0)


def hysteresis_default() -> float:
    return _env_float("TASKSRUNNER_RESHARD_HYSTERESIS_SECONDS", 10.0)


def pause_budget_default() -> float:
    return _env_float("TASKSRUNNER_RESHARD_PAUSE_BUDGET_SECONDS", 2.0)


class PlacementMap:
    """The epoched routing table for one sharded store.

    Immutable by convention: mutation happens by building a successor
    via :meth:`advanced` and publishing it with a single attribute
    store inside the fenced flip. ``assignment`` maps shard index →
    host/member label (``None`` entries mean "wherever the component
    was built" — the pre-elastic default); ``migration`` is the
    in-flight session's status document or ``None``.
    """

    __slots__ = ("epoch", "shards", "assignment", "migration")

    def __init__(self, *, shards: int, epoch: int = 1,
                 assignment: dict[int, str] | None = None,
                 migration: dict | None = None):
        if shards < 1:
            raise ComponentError(
                f"placement map needs >= 1 shard, not {shards}")
        self.epoch = int(epoch)
        self.shards = int(shards)
        self.assignment: dict[int, str] = dict(assignment or {})
        self.migration = migration

    def advanced(self, *, shards: int | None = None,
                 assignment: dict[int, str] | None = None,
                 migration: dict | None = None) -> "PlacementMap":
        """The successor map at ``epoch + 1`` — the only way the epoch
        moves, so it can never move backwards."""
        merged = dict(self.assignment)
        if assignment:
            merged.update(assignment)
        return PlacementMap(
            shards=self.shards if shards is None else shards,
            epoch=self.epoch + 1, assignment=merged, migration=migration)

    def with_migration(self, migration: dict | None) -> "PlacementMap":
        """Same epoch, updated in-flight status — status is telemetry,
        not routing, so publishing it must NOT invalidate routers."""
        return PlacementMap(shards=self.shards, epoch=self.epoch,
                            assignment=self.assignment, migration=migration)

    def to_doc(self) -> dict:
        return {
            "epoch": self.epoch,
            "shards": self.shards,
            "assignment": {str(k): v for k, v in self.assignment.items()},
            "migration": self.migration,
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "PlacementMap":
        return cls(
            shards=int(doc.get("shards", 1)),
            epoch=int(doc.get("epoch", 1)),
            assignment={int(k): v
                        for k, v in (doc.get("assignment") or {}).items()},
            migration=doc.get("migration"))


class ShardHeatTracker:
    """Per-shard write-rate EWMA + hysteresis + bounded hot-key sketch.

    ``note_write`` is on the facade's hot path, so it only bumps two
    counters; the EWMA fold happens in :meth:`sample`, called from the
    metadata/placement poll (and directly by tests). The hot-key
    sketch is lossy counting: the per-shard table is capped, and when
    full every count halves and zeros drop — heavy hitters survive,
    the long tail cannot grow the table.
    """

    #: per-shard hot-key table cap (halve-and-prune beyond this)
    KEY_CAP = 64

    def __init__(self, shards: int, *, halflife: float = 5.0,
                 threshold: float | None = None,
                 hysteresis: float | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.halflife = float(halflife)
        self.threshold = (heat_threshold_default()
                          if threshold is None else float(threshold))
        self.hysteresis = (hysteresis_default()
                           if hysteresis is None else float(hysteresis))
        self._clock = clock
        self._counts: list[int] = [0] * shards
        self._rates: list[float] = [0.0] * shards
        self._hot_since: list[float | None] = [None] * shards
        self._key_counts: list[dict[str, int]] = [{} for _ in range(shards)]
        self._last_sample = clock()

    @property
    def shards(self) -> int:
        return len(self._rates)

    def grow(self, extra: int = 1) -> None:
        """Ring grew (shard split): new shards start cold."""
        self._counts.extend([0] * extra)
        self._rates.extend([0.0] * extra)
        self._hot_since.extend([None] * extra)
        self._key_counts.extend({} for _ in range(extra))

    def note_write(self, shard: int, key: str | None = None) -> None:
        self._counts[shard] += 1
        if key is not None:
            table = self._key_counts[shard]
            table[key] = table.get(key, 0) + 1
            if len(table) > self.KEY_CAP:
                self._key_counts[shard] = {
                    k: c // 2 for k, c in table.items() if c // 2 > 0}

    def sample(self, now: float | None = None) -> list[float]:
        """Fold the counts accumulated since the last sample into the
        EWMA rates and advance the hysteresis clocks. Idempotent at
        zero elapsed time."""
        if now is None:
            now = self._clock()
        dt = now - self._last_sample
        if dt <= 0.0:
            return list(self._rates)
        self._last_sample = now
        # alpha → 1 as dt >> halflife: stale history decays away even
        # when the poller calls rarely
        alpha = 1.0 - 0.5 ** (dt / self.halflife)
        for i, count in enumerate(self._counts):
            inst = count / dt
            self._counts[i] = 0
            rate = self._rates[i] + alpha * (inst - self._rates[i])
            self._rates[i] = rate
            if rate >= self.threshold:
                if self._hot_since[i] is None:
                    self._hot_since[i] = now
            else:
                self._hot_since[i] = None
        return list(self._rates)

    def rates(self) -> list[float]:
        return list(self._rates)

    def hot_shards(self, now: float | None = None) -> list[int]:
        """Shards that have been above the threshold for the whole
        hysteresis window — the only ones the planner may act on."""
        if now is None:
            now = self._clock()
        return [i for i, since in enumerate(self._hot_since)
                if since is not None and now - since >= self.hysteresis]

    def hot_keys(self, shard: int, limit: int = 8) -> list[tuple[str, int]]:
        table = self._key_counts[shard]
        return sorted(table.items(), key=lambda kv: -kv[1])[:limit]

    def snapshot(self, now: float | None = None) -> dict:
        if now is None:
            now = self._clock()
        return {
            "rates": [round(r, 3) for r in self._rates],
            "hot": self.hot_shards(now),
            "threshold": self.threshold,
            "hysteresis_seconds": self.hysteresis,
            "top_keys": {
                str(i): [k for k, _ in self.hot_keys(i)]
                for i in range(self.shards) if self._key_counts[i]
            },
        }


# -- control-loop planning (pure functions over telemetry docs) -----------

def merge_heat_docs(docs: Iterable[dict]) -> list[float]:
    """Sum per-shard EWMA rates across replica telemetry docs (each
    replica owns its own store instance, so cluster heat is the sum)."""
    merged: list[float] = []
    for doc in docs:
        rates = (doc.get("heat") or {}).get("rates") or []
        if len(rates) > len(merged):
            merged.extend([0.0] * (len(rates) - len(merged)))
        for i, r in enumerate(rates):
            merged[i] += float(r)
    return merged


def rank_shards(rates: list[float], *,
                threshold: float | None = None) -> list[dict]:
    """Hot/cold ranking, hottest first — the admin/CLI view."""
    if threshold is None:
        threshold = heat_threshold_default()
    ranked = [
        {"shard": i, "rate": round(r, 3), "hot": r >= threshold}
        for i, r in enumerate(rates)
    ]
    ranked.sort(key=lambda row: -row["rate"])
    for rank, row in enumerate(ranked):
        row["rank"] = rank
    return ranked


def plan_rebalance(store_doc: dict, *,
                   threshold: float | None = None) -> dict | None:
    """One proposed action for one store's merged telemetry, or None.

    A shard that is hot because one key dominates cannot be cooled by
    moving it (the key moves with it) — that's the split case; a shard
    that is hot across many keys moves to the coldest assignment.
    Only shards past the hysteresis window (``heat.hot``) are
    considered, so the plan inherits the anti-thrash guarantee.
    """
    if threshold is None:
        threshold = heat_threshold_default()
    heat = store_doc.get("heat") or {}
    rates = [float(r) for r in (heat.get("rates") or [])]
    hot = [i for i in (heat.get("hot") or []) if i < len(rates)]
    if not hot:
        return None
    hottest = max(hot, key=lambda i: rates[i])
    top_keys = (heat.get("top_keys") or {}).get(str(hottest)) or []
    if len(top_keys) > 1:
        # hot *internally* — many warm keys: growing the ring streams
        # ~1/(N+1) of them to a fresh shard (the ISSUE's split case)
        action = "split"
    else:
        # one dominant key (or no sketch): splitting cannot separate
        # it from itself — relocate the shard to the coldest host
        action = "move"
    coldest = min(range(len(rates)), key=lambda i: rates[i])
    return {
        "store": store_doc.get("store"),
        "action": action,
        "shard": hottest,
        "rate": round(rates[hottest], 3),
        "coldest_shard": coldest,
        "reason": (f"shard {hottest} sustained "
                   f"{rates[hottest]:.1f} ops/s >= {threshold:.1f}"),
    }
