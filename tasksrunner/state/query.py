"""State query dialect: filter (EQ/NEQ/IN/AND/OR), sort, page.

This is the JSON dialect the reference's API service sends through
``DaprClient.QueryStateAsync`` (TasksStoreManager.cs:56-61 builds
``{"filter": {"EQ": {"taskCreatedBy": "<email>"}}}``; the overdue scan
does an EQ on a serialized datetime :125-130). Shape:

    {
      "filter": {"EQ": {"<json-path>": <value>}}
              | {"NEQ": {...}} | {"IN": {"<path>": [v, ...]}}
              | {"AND": [<filter>, ...]} | {"OR": [<filter>, ...]}
              | {},
      "sort":  [{"key": "<json-path>", "order": "ASC"|"DESC"}, ...],
      "page":  {"limit": N, "token": "<opaque>"}
    }

Paths address into the stored JSON document with dots
(``"taskCreatedBy"``, ``"address.city"``). Matching is on JSON values:
strings compare as strings — which preserves the reference's
datetime-serialization trap (Utilities/DateTimeConverter.cs: the query
only matches if the app serializes dates with the same format it
queries with). The framework keeps that contract visible rather than
papering over it.

Used directly by the in-memory store; the sqlite store compiles the
same dialect to SQL (state/sqlite.py) and must stay semantically
identical — tests/test_state.py runs the contract suite against both.
"""

from __future__ import annotations

import functools
import json
from typing import Any

from tasksrunner.errors import QueryError

_FILTER_OPS = ("EQ", "NEQ", "IN", "AND", "OR")


def get_path(doc: Any, path: str) -> Any:
    """Extract ``a.b.c`` from a JSON document; None if absent."""
    node = doc
    for part in path.split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        else:
            return None
    return node


def _single_entry(mapping: dict, op: str) -> tuple[str, Any]:
    if not isinstance(mapping, dict) or len(mapping) != 1:
        raise QueryError(f"{op} filter must hold exactly one path entry")
    return next(iter(mapping.items()))


def validate_filter(filt: Any) -> None:
    """Raise QueryError on malformed filters (shared by both engines)."""
    if filt in (None, {}):
        return
    if not isinstance(filt, dict) or len(filt) != 1:
        raise QueryError("filter must hold exactly one operator")
    op, operand = next(iter(filt.items()))
    if op not in _FILTER_OPS:
        raise QueryError(f"unknown filter operator {op!r} (expected one of {_FILTER_OPS})")
    if op in ("AND", "OR"):
        if not isinstance(operand, list) or not operand:
            raise QueryError(f"{op} expects a non-empty list of sub-filters")
        for sub in operand:
            validate_filter(sub)
    elif op == "IN":
        path, values = _single_entry(operand, op)
        if not isinstance(values, list):
            raise QueryError("IN expects a list of candidate values")
        for v in values:
            _require_scalar(v, op)
    else:
        _, v = _single_entry(operand, op)
        _require_scalar(v, op)


def _require_scalar(value: Any, op: str) -> None:
    # Containers can't bind as SQL parameters and document-store query
    # dialects compare scalars only; rejecting here keeps both engines
    # identical instead of one matching and one erroring.
    if isinstance(value, (dict, list)):
        raise QueryError(f"{op} comparison values must be scalars, not {type(value).__name__}")


def matches(doc: Any, filt: Any) -> bool:
    """Pure-Python filter evaluation."""
    if filt in (None, {}):
        return True
    op, operand = next(iter(filt.items()))
    if op == "AND":
        return all(matches(doc, sub) for sub in operand)
    if op == "OR":
        return any(matches(doc, sub) for sub in operand)
    path, expected = _single_entry(operand, op)
    actual = get_path(doc, path)
    if op == "EQ":
        return actual == expected
    if op == "NEQ":
        return actual != expected
    if op == "IN":
        return actual in expected
    raise QueryError(f"unknown filter operator {op!r}")


def _sort_rank(v: Any) -> int:
    """Type rank matching SQLite's storage-class order (NULL < numeric
    < text < everything-else), so both query engines sort mixed-type
    fields identically."""
    if v is None:
        return 0
    if isinstance(v, (bool, int, float)):
        return 1
    if isinstance(v, str):
        return 2
    return 3  # containers sort last, as JSON text


def _sort_cmp(a: Any, b: Any) -> int:
    """Total order over heterogeneous JSON values, aligned with the
    sqlite engine's ORDER BY json_extract semantics."""
    if a == b:
        return 0
    ra, rb = _sort_rank(a), _sort_rank(b)
    if ra != rb:
        return -1 if ra < rb else 1
    if ra == 3:
        # containers have no natural order; canonical JSON text gives a
        # stable one instead of a TypeError mid-query
        a, b = json.dumps(a, sort_keys=True), json.dumps(b, sort_keys=True)
        if a == b:
            return 0
    return -1 if a < b else 1


def sort_items(items: list, sort_spec: list[dict] | None, *, doc=lambda it: it.value) -> list:
    if not sort_spec:
        return items
    for clause in sort_spec:
        if not isinstance(clause, dict) or "key" not in clause:
            raise QueryError("each sort clause needs a key")
        order = str(clause.get("order", "ASC")).upper()
        if order not in ("ASC", "DESC"):
            raise QueryError(f"sort order must be ASC or DESC, not {clause.get('order')!r}")
    out = list(items)
    # apply clauses right-to-left so the leftmost is the primary key
    for clause in reversed(sort_spec):
        path = clause["key"]
        reverse = str(clause.get("order", "ASC")).upper() == "DESC"
        out.sort(
            key=functools.cmp_to_key(
                lambda x, y, p=path: _sort_cmp(get_path(doc(x), p), get_path(doc(y), p))
            ),
            reverse=reverse,
        )
    return out


def paginate(items: list, page: dict | None) -> tuple[list, str | None]:
    """Index-token paging: token is the stringified next offset."""
    if not page:
        return items, None
    limit = page.get("limit")
    token = page.get("token")
    start = 0
    if token is not None:
        try:
            start = int(token)
        except (TypeError, ValueError):
            raise QueryError(f"bad page token {token!r}") from None
        if start < 0:
            raise QueryError(f"bad page token {token!r}")
    if limit is None:
        return items[start:], None
    if not isinstance(limit, int) or limit <= 0:
        raise QueryError("page.limit must be a positive integer")
    chunk = items[start : start + limit]
    next_token = str(start + limit) if start + limit < len(items) else None
    return chunk, next_token


def run_query(items: list, query: dict, *, doc=lambda it: it.value):
    """Full pipeline over materialised items (memory-store path)."""
    if not isinstance(query, dict):
        raise QueryError("query must be a JSON object")
    filt = query.get("filter")
    validate_filter(filt)
    hits = [it for it in items if matches(doc(it), filt)]
    hits = sort_items(hits, query.get("sort"), doc=doc)
    return paginate(hits, query.get("page"))
