from tasksrunner.state.base import StateItem, StateStore, TransactionOp
from tasksrunner.state.keyprefix import KeyPrefixer
from tasksrunner.state.memory import InMemoryStateStore
from tasksrunner.state.redis import RedisStateStore
from tasksrunner.state.sqlite import SqliteStateStore

__all__ = [
    "StateItem",
    "StateStore",
    "TransactionOp",
    "KeyPrefixer",
    "InMemoryStateStore",
    "RedisStateStore",
    "SqliteStateStore",
]
