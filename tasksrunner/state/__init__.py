from tasksrunner.state.base import StateItem, StateStore, TransactionOp
from tasksrunner.state.keyprefix import KeyPrefixer
from tasksrunner.state.memory import InMemoryStateStore
from tasksrunner.state.redis import RedisStateStore
from tasksrunner.state.sharding import ShardedStateStore, ShardRouter
from tasksrunner.state.sqlite import (
    SqliteStateStore, StagedTransaction, build_sharded_store,
)

__all__ = [
    "StateItem",
    "StateStore",
    "TransactionOp",
    "KeyPrefixer",
    "InMemoryStateStore",
    "RedisStateStore",
    "ShardedStateStore",
    "ShardRouter",
    "SqliteStateStore",
    "StagedTransaction",
    "build_sharded_store",
]
