"""The sidecar process: Dapr-shaped HTTP API over a Runtime.

Route surface replicated from the reference's sidecar usage
(SURVEY.md §1 L2):

* ``POST/GET/DELETE /v1.0/state/{store}[/{key}]``, ``/query``,
  ``/transaction`` — docs/aca/04-aca-dapr-stateapi/index.md:41-46;
* ``POST /v1.0/publish/{pubsub}/{topic}`` — docs module 5 :60-66;
* ``POST /v1.0/bindings/{name}`` — docs module 6 :60-74;
* ``ANY /v1.0/invoke/{app-id}/method/{path}`` — docs module 3 :107-127;
* ``GET /v1.0/secrets/{store}/{key}`` (+ ``/bulk``);
* ``GET /v1.0/healthz``, ``GET /v1.0/metadata``.

Run it beside an app process (``python -m tasksrunner sidecar ...`` or
via the orchestrator) exactly as ``dapr run`` does
(snippets/dapr-run-backend-api.md:4-16): the app talks to
``localhost:<sidecar-port>``, never to peers directly.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time

from aiohttp import web

from tasksrunner.errors import TasksRunnerError, ValidationError
from tasksrunner.invoke.headers import inward_headers, outward_headers
from tasksrunner.observability import flightrec
from tasksrunner.observability.admission import AdmissionController
from tasksrunner.observability.metrics import metrics, render_prometheus
from tasksrunner.observability.probes import EventLoopLagProbe
from tasksrunner.observability.tracing import (
    BAGGAGE_HEADER,
    TRACEPARENT_HEADER,
    ensure_trace,
    trace_scope,
)
from tasksrunner.runtime import Runtime
from tasksrunner.state.base import StateItem
from tasksrunner.state.placement import PLACEMENT_EPOCH_HEADER

logger = logging.getLogger(__name__)


def _json_error(exc: Exception) -> web.Response:
    if isinstance(exc, json.JSONDecodeError):
        # malformed request body is the caller's error, not ours
        return web.json_response(
            {"error": f"malformed JSON body: {exc}"}, status=400)
    status = exc.http_status if isinstance(exc, TasksRunnerError) else 500
    if not isinstance(exc, TasksRunnerError):
        logger.exception("unhandled sidecar error")
    headers = None
    current_epoch = getattr(exc, "current_epoch", None)
    if current_epoch is not None:
        # placement 409: carry the live epoch so the caller refreshes
        # its routing cache from the rejection itself (no extra probe)
        headers = {PLACEMENT_EPOCH_HEADER: str(current_epoch)}
    return web.json_response({"error": str(exc) or type(exc).__name__},
                             status=status, headers=headers)


from tasksrunner.security import (  # noqa: E402 (re-export)
    TOKEN_ENV,
    TOKEN_HEADER,
    hash_token,
    load_token_map,
)


def shed_response(admission) -> web.Response:
    """The 429 a saturated replica answers instead of queueing work.

    ``Retry-After`` scales with the saturation score, so clients back
    off harder the deeper the overload; resiliency policies honor it
    (resiliency/policy.py) and well-behaved external callers should
    too.
    """
    return web.json_response(
        {"error": "replica saturated; retry later"},
        status=429,
        headers={"Retry-After": str(admission.retry_after_seconds())})


def build_sidecar_app(runtime: Runtime, *, api_token: str | None = None,
                      peer_tokens: set[str] | None = None,
                      admission=None) -> web.Application:
    if api_token is None:
        api_token = os.environ.get(TOKEN_ENV) or None
    if peer_tokens is None:
        # per-app-token mode: the orchestrator's map carries sha256
        # DIGESTS, so this sidecar can authenticate inbound peers
        # without holding (or being able to replay) their tokens
        peer_tokens = set(load_token_map().values())

    routes = web.RouteTableDef()

    def _traced(handler=None, *, allow_peer: bool = False,
                exempt: bool = False):
        # app↔sidecar API token (≙ Dapr's dapr-api-token / the
        # reference's identity posture, SURVEY.md §5.10): when a token
        # is configured, every building-block call must carry it —
        # healthz stays open for probes. A PEER app's token is honored
        # only by handlers wrapped with allow_peer=True (service
        # invocation): acceptance is a property of the handler actually
        # executing, not of the request path, so routing and auth can
        # never diverge. Another app's identity must not unlock this
        # app's state, pub/sub, bindings, or secrets.
        def deco(handler):
            route_label = handler.__name__
            # bound once per route at decoration time — request
            # observations are a closure call, no label resolution
            record_latency = metrics.recorder(
                "sidecar_request_latency_seconds", route=route_label)
            # admission is None when TASKSRUNNER_ADMISSION is off, so
            # the disabled path pays exactly one bool test per request
            sheddable = admission is not None and not exempt

            async def wrapped(request: web.Request):
                if api_token is not None:
                    supplied = request.headers.get(TOKEN_HEADER)
                    peer_ok = (
                        allow_peer and supplied is not None
                        and hash_token(supplied) in peer_tokens)
                    if supplied != api_token and not peer_ok:
                        return web.json_response(
                            {"error": "missing or bad api token"}, status=401)
                # after auth — saturation state is not for anonymous eyes
                if sheddable and admission.shedding:
                    metrics.inc("admission_shed_total", route=route_label)
                    return shed_response(admission)
                ctx = ensure_trace(request.headers.get(TRACEPARENT_HEADER),
                                   request.headers.get(BAGGAGE_HEADER))
                started = time.perf_counter()
                status = 500
                with trace_scope(ctx):
                    try:
                        resp = await handler(request)
                        status = resp.status
                        return resp
                    except Exception as exc:  # noqa: BLE001 - mapped to status
                        resp = _json_error(exc)
                        status = resp.status
                        return resp
                    finally:
                        elapsed = time.perf_counter() - started
                        record_latency(elapsed)
                        # black-box skeleton: one if + one deque append
                        flightrec.note_request(
                            name=route_label, trace_id=ctx.trace_id,
                            status=status, duration=elapsed)
            return wrapped
        return deco if handler is None else deco(handler)

    # -- state ----------------------------------------------------------

    def _check_placement(request: web.Request) -> None:
        # elastic placement: a routing-aware client stamps the epoch it
        # routed with; mismatch → 409 with the live epoch in the reply
        # header (_json_error), BEFORE the operation touches any shard
        raw = request.headers.get(PLACEMENT_EPOCH_HEADER)
        if raw is None:
            return
        try:
            epoch = int(raw)
        except ValueError:
            raise ValidationError(
                f"bad {PLACEMENT_EPOCH_HEADER} header: {raw!r}") from None
        runtime.check_placement_epoch(request.match_info["store"], epoch)

    @routes.post("/v1.0/state/{store}")
    @_traced
    async def save_state(request: web.Request):
        items = await request.json()
        if not isinstance(items, list):
            raise ValidationError("state save body must be a list of {key, value}")
        _check_placement(request)
        await runtime.save_state(request.match_info["store"], items)
        return web.Response(status=204)

    @routes.get("/v1.0/state/{store}/{key}")
    @_traced
    async def get_state(request: web.Request):
        _check_placement(request)
        item: StateItem | None = await runtime.get_state(
            request.match_info["store"], request.match_info["key"])
        if item is None:
            return web.Response(status=204)  # Dapr returns empty for missing keys
        return web.json_response(item.value, headers={"etag": item.etag})

    @routes.delete("/v1.0/state/{store}/{key}")
    @_traced
    async def delete_state(request: web.Request):
        etag = request.headers.get("if-match")
        _check_placement(request)
        await runtime.delete_state(request.match_info["store"],
                                   request.match_info["key"], etag=etag)
        return web.Response(status=204)

    @routes.post("/v1.0/state/{store}/bulk")
    @_traced
    async def bulk_get_state(request: web.Request):
        body = await request.json()
        keys = body.get("keys") if isinstance(body, dict) else body
        if not isinstance(keys, list):
            raise ValidationError("bulk get body must be {\"keys\": [...]}")
        _check_placement(request)
        result = await runtime.bulk_get_state(request.match_info["store"], keys)
        return web.json_response(result)

    @routes.post("/v1.0/state/{store}/query")
    @_traced
    async def query_state(request: web.Request):
        body = await request.json()
        _check_placement(request)
        result = await runtime.query_state(
            request.match_info["store"], body)
        return web.json_response(result)

    @routes.post("/v1.0/state/{store}/transaction")
    @_traced
    async def transact_state(request: web.Request):
        body = await request.json()
        _check_placement(request)
        await runtime.transact_state(
            request.match_info["store"], body.get("operations", []))
        return web.Response(status=204)

    # -- secrets ---------------------------------------------------------

    @routes.get("/v1.0/secrets/{store}/bulk")
    @_traced
    async def bulk_secrets(request: web.Request):
        return web.json_response(runtime.bulk_secrets(request.match_info["store"]))

    @routes.get("/v1.0/secrets/{store}/{key}")
    @_traced
    async def get_secret(request: web.Request):
        return web.json_response(
            runtime.get_secret(request.match_info["store"],
                               request.match_info["key"]))

    # -- pub/sub ---------------------------------------------------------

    @routes.post("/v1.0/publish/{pubsub}/{topic}")
    @_traced
    async def publish(request: web.Request):
        body = await request.read()
        data = json.loads(body) if body else None
        raw = request.query.get("metadata.rawPayload") == "true"
        msg_id = await runtime.publish(
            request.match_info["pubsub"], request.match_info["topic"], data,
            raw=raw)
        return web.json_response({"messageId": msg_id})

    # -- bindings --------------------------------------------------------

    @routes.post("/v1.0/bindings/{name}")
    @_traced
    async def invoke_binding(request: web.Request):
        body = await request.json()
        resp = await runtime.invoke_output_binding(
            request.match_info["name"],
            body.get("operation", "create"),
            body.get("data"),
            body.get("metadata") or {},
        )
        payload = resp.data
        if isinstance(payload, (bytes, bytearray)):
            payload = payload.decode("utf-8", "replace")
        return web.json_response({"data": payload, "metadata": resp.metadata})

    # -- service invocation ----------------------------------------------

    @routes.route("*", "/v1.0/invoke/{app_id}/method/{path:.*}")
    @_traced(allow_peer=True)
    async def invoke(request: web.Request):
        target = request.match_info["app_id"]
        path = request.match_info["path"]
        body = await request.read()
        # filtering policy shared with the mesh lane (invoke/headers.py)
        # — the transports must stay indistinguishable to the app
        fwd_headers = inward_headers(dict(request.headers))
        status, headers, resp_body = await runtime.invoke(
            target, path, http_method=request.method,
            query=request.query_string, headers=fwd_headers, body=body)
        # forward the app's response headers (redirect locations,
        # cookies, etags...) — HTTP mode must not lose what the direct
        # transport delivers; only hop-by-hop headers are dropped
        return web.Response(status=status, body=resp_body,
                            headers=outward_headers(headers))

    # -- actors ----------------------------------------------------------

    # Routes registered only when the gate is on: with TASKSRUNNER_ACTORS
    # unset the sidecar's route table is byte-identical to before this
    # subsystem existed, so the off path adds zero routing or dispatch
    # cost (the <1% overhead budget measured by bench.py --actor-bench).
    # TASKSRUNNER_WORKFLOWS also opens the gate: workflow instances ARE
    # actors, and a replica that does not own an instance forwards the
    # turn to the owner THROUGH these routes — without them every
    # cross-replica workflow operation would 404 at the owner's door.
    from tasksrunner.envflag import env_flag
    if (env_flag("TASKSRUNNER_ACTORS", default=False)
            or env_flag("TASKSRUNNER_WORKFLOWS", default=False)):

        @routes.route("*", "/v1.0/actors/{atype}/{aid}/method/{m}")
        @_traced(allow_peer=True)
        async def invoke_actor(request: web.Request):
            # allow_peer: a peer replica forwarding a turn to the owner
            # authenticates with its own app token, like /v1.0/invoke
            body = await request.read()
            data = json.loads(body) if body else None
            forwarded = request.headers.get(
                "x-tasksrunner-actor-forward") == "1"
            result = await runtime.invoke_actor(
                request.match_info["atype"], request.match_info["aid"],
                request.match_info["m"], data, forwarded=forwarded)
            return web.json_response({"result": result})

        @routes.post("/v1.0/actors/{atype}/{aid}/reminders/{name}")
        @_traced(allow_peer=True)
        async def register_actor_reminder(request: web.Request):
            body = await request.json()
            if not isinstance(body, dict) or "dueSeconds" not in body:
                raise ValidationError(
                    'reminder body must be {"dueSeconds": n, '
                    '"periodSeconds"?: n, "data"?: ...}')
            forwarded = request.headers.get(
                "x-tasksrunner-actor-forward") == "1"
            await runtime.register_actor_reminder(
                request.match_info["atype"], request.match_info["aid"],
                request.match_info["name"],
                due_seconds=float(body["dueSeconds"]),
                period_seconds=(float(body["periodSeconds"])
                                if body.get("periodSeconds") is not None
                                else None),
                data=body.get("data"), forwarded=forwarded)
            return web.Response(status=204)

        @routes.delete("/v1.0/actors/{atype}/{aid}/reminders/{name}")
        @_traced(allow_peer=True)
        async def unregister_actor_reminder(request: web.Request):
            forwarded = request.headers.get(
                "x-tasksrunner-actor-forward") == "1"
            await runtime.unregister_actor_reminder(
                request.match_info["atype"], request.match_info["aid"],
                request.match_info["name"], forwarded=forwarded)
            return web.Response(status=204)

        @routes.get("/v1.0/actors/{atype}/{aid}/state")
        @_traced
        async def get_actor_state(request: web.Request):
            doc = await runtime.get_actor_state(
                request.match_info["atype"], request.match_info["aid"])
            return web.json_response(doc)

        @routes.get("/v1.0/actors")
        @_traced(exempt=True)
        async def actor_placement(request: web.Request):
            # admin/ps surface: this replica's summary + the global
            # placement table computed from the shared store.
            # Admission-exempt like /v1.0/metadata — it is an operator
            # observability read, most needed during overload/failover.
            if runtime.actors is None:
                return web.json_response({"replica": None, "placement": []})
            return web.json_response({
                "replica": runtime.actors.summary(),
                "placement": await runtime.actors.placement_table(),
            })

    # -- workflows -------------------------------------------------------

    # Dapr-shaped workflow routes ({component} is accepted for wire
    # compatibility and ignored — the engine is the only backend).
    # Gated like the actor routes: flag off = route table unchanged.
    if env_flag("TASKSRUNNER_WORKFLOWS", default=False):
        from tasksrunner.errors import WorkflowError

        def _wf_plane():
            if runtime.workflows is None:
                raise WorkflowError(
                    "the workflow plane is not running on this replica "
                    "(no @app.workflow registered?)")
            return runtime.workflows

        @routes.post("/v1.0/workflows/{component}/{name}/start")
        @_traced
        async def start_workflow(request: web.Request):
            body = await request.read()
            data = json.loads(body) if body else None
            instance = await _wf_plane().start(
                request.match_info["name"], data,
                instance=request.query.get("instanceID") or None)
            return web.json_response({"instanceID": instance})

        @routes.get("/v1.0/workflows/{component}/{instance}")
        @_traced
        async def workflow_status(request: web.Request):
            return web.json_response(
                await _wf_plane().status(request.match_info["instance"]))

        @routes.get("/v1.0/workflows/{component}/{instance}/history")
        @_traced
        async def workflow_history(request: web.Request):
            return web.json_response({
                "instance": request.match_info["instance"],
                "history": await _wf_plane().history(
                    request.match_info["instance"]),
            })

        @routes.post("/v1.0/workflows/{component}/{instance}/terminate")
        @_traced
        async def terminate_workflow(request: web.Request):
            body = await request.read()
            data = json.loads(body) if body else {}
            await _wf_plane().terminate(
                request.match_info["instance"],
                reason=str((data or {}).get("reason") or "terminated"))
            return web.Response(status=202)

        @routes.post("/v1.0/workflows/{component}/{instance}"
                     "/raiseEvent/{event}")
        @_traced
        async def raise_workflow_event(request: web.Request):
            body = await request.read()
            data = json.loads(body) if body else None
            await _wf_plane().raise_event(
                request.match_info["instance"],
                request.match_info["event"], data=data,
                id=request.query.get("eventID") or None)
            return web.Response(status=202)

        @routes.get("/v1.0/workflows")
        @_traced(exempt=True)
        async def list_workflows(request: web.Request):
            # operator surface, admission-exempt like /v1.0/actors
            if runtime.workflows is None:
                return web.json_response({"instances": []})
            return web.json_response(
                {"instances": await runtime.workflows.list()})

    # -- traces ----------------------------------------------------------

    @routes.get("/v1.0/traces/{trace_id}")
    @_traced(exempt=True)
    async def get_trace(request: web.Request):
        # this replica's slice of one trace, served from the local span
        # db — what the orchestrator's /admin/traces/{id} fans out to
        # for cross-host assembly. Admission-exempt: an operator pulls
        # traces exactly when the replica is in trouble.
        from tasksrunner.observability import spans as spans_mod
        rec = spans_mod.recorder()
        path = rec.path if rec is not None else os.environ.get(spans_mod.ENV_VAR)
        if not path or not os.path.exists(path):
            return web.json_response({"spans": []})
        if rec is not None:
            await asyncio.to_thread(rec.flush)  # serve the buffered tail too
        rows = await asyncio.to_thread(
            spans_mod.trace_spans, path, request.match_info["trace_id"])
        return web.json_response({"spans": rows})

    # -- meta ------------------------------------------------------------

    @routes.get("/v1.0/healthz")
    async def healthz(request: web.Request):
        return web.Response(status=204)

    @routes.get("/v1.0/metadata")
    @_traced(exempt=True)
    async def metadata(request: web.Request):
        # token-gated like every building-block route: the component
        # inventory and metrics are exactly what the token protects.
        # Admission-exempt: the autoscaler reads its scale signals from
        # here — shedding it would blind the control loop exactly when
        # it needs to scale out (healthz and /metrics bypass _traced
        # entirely and are exempt the same way).
        return web.json_response(runtime.metadata())

    @routes.get("/metrics")
    async def prometheus_metrics(request: web.Request):
        # Prometheus text exposition at the conventional scrape path.
        # Token check done by hand (same policy as _traced) so the
        # scrape itself never shows up in its own request histogram.
        if api_token is not None:
            if request.headers.get(TOKEN_HEADER) != api_token:
                return web.json_response(
                    {"error": "missing or bad api token"}, status=401)
        body = render_prometheus(metrics)
        # aiohttp's content_type kwarg rejects parameters, so the
        # versioned exposition type goes through the headers dict
        return web.Response(
            body=body.encode(),
            headers={"Content-Type": "text/plain; version=0.0.4; charset=utf-8"})

    app = web.Application(client_max_size=16 * 1024 * 1024)
    app.add_routes(routes)
    return app


class Sidecar:
    """Runtime + HTTP server + peer mesh listener, with lifecycle
    management. The HTTP surface is the app-facing API; the mesh port
    (invoke/mesh.py) is the sidecar↔sidecar lane peers prefer — both
    dispatch into the same Runtime under the same token policy."""

    def __init__(self, runtime: Runtime, *, host: str = "127.0.0.1", port: int = 3500,
                 admission: AdmissionController | None = None):
        self.runtime = runtime
        self.host = host
        self.port = port
        self.mesh_port: int | None = None
        # AppHost passes its shared controller (wired to App.inflight);
        # a standalone sidecar builds its own from the environment
        self.admission = (admission if admission is not None
                          else AdmissionController.from_env())
        self._http = build_sidecar_app(runtime, admission=self.admission)
        self._runner: web.AppRunner | None = None
        self._mesh = None
        self._lag_probe = EventLoopLagProbe()

    async def start(self) -> None:
        from tasksrunner.envflag import env_flag
        from tasksrunner.hosting import _access_log
        from tasksrunner.invoke.mesh import MeshServer

        self._runner = web.AppRunner(self._http, access_log=_access_log())
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        from tasksrunner.hosting import _bind_or_explain
        await _bind_or_explain(site, "sidecar", self.host, self.port)
        if self.port == 0:  # pick the real ephemeral port
            self.port = self._runner.addresses[0][1]
        # advertised in actor placement records so peers can forward
        # turns to this replica; must be set before runtime.start()
        # boots the actor runtime
        self.runtime.actor_address = (self.host, self.port)
        if env_flag("TASKSRUNNER_MESH"):
            self._mesh = MeshServer(self.runtime, host=self.host)
            await self._mesh.start()
            self.mesh_port = self._mesh.port
        await self.runtime.start()
        self._lag_probe.start()
        if self.admission is not None:
            self.admission.start()
        # always-on black box (TASKSRUNNER_FLIGHTREC=0 opts out); a
        # clean stop() suppresses the atexit dump via mark_clean
        flightrec.configure_flightrec(self.runtime.app_id)
        logger.info("sidecar for %s listening on %s:%d (mesh :%s)",
                    self.runtime.app_id, self.host, self.port, self.mesh_port)

    async def stop(self) -> None:
        flightrec.mark_clean()
        if self.admission is not None:
            await self.admission.stop()
        await self._lag_probe.stop()
        await self.runtime.stop()
        if self._mesh is not None:
            await self._mesh.stop()
            self._mesh = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
