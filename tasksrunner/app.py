"""Application framework: routing, subscriptions, binding handlers.

The layer the three sample services are written against — the analog of the
reference's ASP.NET controller layer, reduced to the surface the
workshop actually uses:

* HTTP routes with path params (``TasksController`` routes
  ``api/tasks``, ``api/tasks/{id}`` — Controllers/TasksController.cs:7-76);
* declarative topic subscriptions (``[Topic("dapr-pubsub-servicebus",
  "tasksavedtopic")]`` — Controllers/TasksNotifierController.cs:23-25)
  discovered by the sidecar through a ``/tasksrunner/subscribe``
  handshake (≙ MapSubscribeHandler's ``/dapr/subscribe``,
  Processor Program.cs:33);
* input-binding handlers dispatched by route (cron: route = component
  name; queue: route from component metadata — SURVEY.md §3.3-3.4);
* CloudEvents unwrap on delivery (≙ UseCloudEvents, Program.cs:29).

Handlers are ``async def handler(request) -> Response | dict | list |
str | bytes | int | None | (status, body)``.
"""

from __future__ import annotations

import asyncio
import contextlib
import inspect
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable
from urllib.parse import parse_qsl

from tasksrunner import cloudevents
from tasksrunner.errors import TasksRunnerError, ValidationError
from tasksrunner.observability.spans import record_span
from tasksrunner.observability.tracing import (
    BAGGAGE_HEADER,
    TRACEPARENT_HEADER,
    ensure_trace,
    trace_scope,
)

logger = logging.getLogger(__name__)


@dataclass
class Request:
    method: str
    path: str
    query: dict[str, str] = field(default_factory=dict)
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    path_params: dict[str, str] = field(default_factory=dict)

    def json(self) -> Any:
        if not self.body:
            return None
        return json.loads(self.body)

    @property
    def data(self) -> Any:
        """Body with any CloudEvents envelope removed (≙ UseCloudEvents)."""
        if not self.body:
            return None
        return cloudevents.unwrap(self.body, self.headers.get("content-type"))


@dataclass
class Response:
    status: int = 200
    body: Any = None
    headers: dict[str, str] = field(default_factory=dict)

    def encode(self) -> tuple[int, dict[str, str], bytes]:
        headers = dict(self.headers)
        if self.body is None:
            return self.status, headers, b""
        if isinstance(self.body, (bytes, bytearray)):
            headers.setdefault("content-type", "application/octet-stream")
            return self.status, headers, bytes(self.body)
        if isinstance(self.body, str):
            headers.setdefault("content-type", "text/plain; charset=utf-8")
            return self.status, headers, self.body.encode()
        headers.setdefault("content-type", "application/json")
        return self.status, headers, json.dumps(self.body).encode()


def _normalize(result: Any) -> Response:
    if isinstance(result, Response):
        return result
    if result is None:
        return Response(status=204)
    if isinstance(result, int):
        return Response(status=result)
    if isinstance(result, tuple) and len(result) == 2 and isinstance(result[0], int):
        return Response(status=result[0], body=result[1])
    return Response(status=200, body=result)


Handler = Callable[..., Awaitable[Any]]


@dataclass
class _Route:
    method: str
    segments: list[str]  # literal (lowercased) or "{param}"
    handler: Handler
    kind: str = "http"  # http | subscription | binding

    def match(self, method: str, path: str) -> dict[str, str] | None:
        if self.method != "*" and method.upper() != self.method:
            return None
        parts = [p for p in path.split("/") if p != ""]
        if len(parts) != len(self.segments):
            return None
        params: dict[str, str] = {}
        for seg, part in zip(self.segments, parts):
            if seg.startswith("{") and seg.endswith("}"):
                params[seg[1:-1]] = part
            elif seg != part.lower():
                return None
        return params


@dataclass
class SubscriptionEntry:
    pubsub_name: str
    topic: str
    route: str


@dataclass
class BindingEntry:
    name: str
    route: str


class App:
    """One service: an app-id plus its routes and declarative hooks."""

    def __init__(self, app_id: str):
        self.app_id = app_id
        self._routes: list[_Route] = []
        #: (METHOD, "/lowercased/path") → route, for routes without
        #: path params — O(1) dispatch on the hot path; param routes
        #: fall back to the match loop
        self._exact_routes: dict[tuple[str, str], _Route] = {}
        #: ("/prefix/", reader) mounts from App.static
        self._static_mounts: list[tuple[str, Any]] = []
        self.subscriptions: list[SubscriptionEntry] = []
        self.binding_routes: list[BindingEntry] = []
        #: actor type → turn handler, registered with @app.actor(...)
        self.actors: dict[str, Handler] = {}
        #: WorkflowEngine once the first @app.workflow / @app.activity
        #: registered (it hosts the ``_Workflow`` actor type above)
        self.workflow_engine: Any = None
        self._startup_hooks: list[Callable[[], Awaitable[None]]] = []
        self._shutdown_hooks: list[Callable[[], Awaitable[None]]] = []
        #: set by the serving harness; the app's handle to its sidecar
        #: (≙ the injected DaprClient)
        self.client: Any = None
        #: free-form per-app state (≙ DI singletons)
        self.state: dict[str, Any] = {}
        #: live request counters, maintained by handle() itself so
        #: every dispatch path (HTTP server, sidecar direct channel,
        #: in-proc cluster) feeds the http-concurrency autoscale rule
        #: identically (served at GET /tasksrunner/stats)
        self.inflight = 0
        self.requests_total = 0

    # -- registration ----------------------------------------------------

    def route(self, path: str, *, methods: list[str] | str = "GET",
              kind: str = "http") -> Callable[[Handler], Handler]:
        if isinstance(methods, str):
            methods = [methods]

        def register(handler: Handler) -> Handler:
            for method in methods:
                segments = [
                    s if s.startswith("{") else s.lower()
                    for s in path.split("/") if s != ""
                ]
                route = _Route(method=method.upper(), segments=segments,
                               handler=handler, kind=kind)
                self._routes.append(route)
                if not any(s.startswith("{") for s in segments) \
                        and route.method != "*":
                    exact_path = "/" + "/".join(segments)
                    # first-registered-wins, exactly like the scan
                    # loop: if an EARLIER parameterised/wildcard route
                    # already matches this literal path, the O(1) table
                    # must not let the newer literal route shadow it
                    shadowed = any(
                        earlier.match(route.method, exact_path) is not None
                        for earlier in self._routes[:-1])
                    if not shadowed:
                        self._exact_routes.setdefault(
                            (route.method, exact_path), route)
            return handler

        return register

    def get(self, path: str):
        return self.route(path, methods="GET")

    def post(self, path: str):
        return self.route(path, methods="POST")

    def put(self, path: str):
        return self.route(path, methods="PUT")

    def delete(self, path: str):
        return self.route(path, methods="DELETE")

    def static(self, prefix: str, directory) -> None:
        """Serve files under ``directory`` at ``prefix`` (≙ ASP.NET's
        UseStaticFiles over wwwroot/, which the reference frontend
        relies on for its asset tree). GET/HEAD only. Like
        UseStaticFiles, a miss falls through to route dispatch, so
        routes under the prefix stay reachable."""
        import mimetypes
        import pathlib

        root = pathlib.Path(directory).resolve()
        prefix = "/" + prefix.strip("/")
        mount_key = prefix if prefix == "/" else prefix + "/"

        async def read_file(rel: str) -> Response | None:
            try:
                target = (root / rel).resolve()
                # resolve() collapses any ../ — anything that escapes
                # the root is a traversal attempt, treated as a miss
                if not target.is_relative_to(root) or not target.is_file():
                    return None
                ctype = (mimetypes.guess_type(target.name)[0]
                         or "application/octet-stream")
                # disk I/O off the event loop: a multi-MB asset must
                # not stall concurrent requests/probes on this app
                data = await asyncio.to_thread(target.read_bytes)
            except (OSError, ValueError):
                # TOCTOU (file deleted / permissions changed between
                # check and read) or NUL bytes in a decoded path: a
                # plain miss, not an unhandled 500
                return None
            return Response(status=200, body=data,
                            headers={"content-type": ctype})

        self._static_mounts.append((mount_key, read_file))

    def subscribe(self, pubsub: str, topic: str, route: str | None = None):
        """≙ [Topic(pubsub, topic)] on an action method. Multiple
        subscriptions may share one route (the reference stacks a cloud
        and a local [Topic] attribute on the same action —
        TasksNotifierController.cs:23-25)."""
        route = route or f"/events/{pubsub}/{topic}"

        def register(handler: Handler) -> Handler:
            self.subscriptions.append(
                SubscriptionEntry(pubsub_name=pubsub, topic=topic, route=route)
            )
            existing = next(
                (r for r in self._routes
                 if r.kind == "subscription" and r.match("POST", route) is not None),
                None,
            )
            if existing is not None:
                if existing.handler is not handler:
                    raise ValidationError(
                        f"route {route!r} is already bound to a different "
                        "subscription handler; stacking topics on one route "
                        "requires the same handler"
                    )
                return handler
            return self.route(route, methods="POST", kind="subscription")(handler)

        return register

    def binding(self, name: str, route: str | None = None):
        """Handler for an input binding; route defaults to /<name>
        (the cron convention — SURVEY.md §3.3)."""
        route = route or f"/{name}"

        def register(handler: Handler) -> Handler:
            self.binding_routes.append(BindingEntry(name=name, route=route))
            return self.route(route, methods="POST", kind="binding")(handler)

        return register

    def actor(self, actor_type: str):
        """Register the turn handler for one actor type (≙ a Dapr actor
        class). The handler receives an ``ActorTurn`` and runs with the
        one-at-a-time guarantee: never two concurrent turns for the
        same actor id, cluster-wide. It must be ``async def`` — a sync
        handler would block the owning replica's event loop for every
        actor it hosts (see the actor-turn-discipline lint rule)::

            @app.actor("Counter")
            async def counter(turn):
                turn.state["n"] = turn.state.get("n", 0) + 1
                return turn.state["n"]
        """
        def register(handler: Handler) -> Handler:
            if not inspect.iscoroutinefunction(handler):
                raise ValidationError(
                    f"actor turn handlers must be 'async def' "
                    f"({actor_type}: {getattr(handler, '__name__', handler)!r} "
                    "is synchronous)")
            if actor_type in self.actors:
                raise ValidationError(
                    f"actor type {actor_type!r} is already registered")
            self.actors[actor_type] = handler
            return handler

        return register

    def _workflow_engine(self):
        """Lazily build the workflow engine and host its actor type —
        importing tasksrunner.workflows only when an app actually
        registers a workflow keeps the plain-app import graph flat."""
        engine = self.workflow_engine
        if engine is None:
            from tasksrunner.workflows import (
                WORKFLOW_ACTOR_TYPE,
                WorkflowEngine,
            )
            engine = self.workflow_engine = WorkflowEngine(self)
            self.actors[WORKFLOW_ACTOR_TYPE] = engine.handle_turn
        return engine

    def workflow(self, name: str):
        """Register a deterministic orchestrator function (replayed
        from history — observe the world only through ``ctx``; the
        workflow-determinism lint rule enforces this)::

            @app.workflow("checkout")
            async def checkout(ctx, order):
                paid = await ctx.call_activity("charge", order)
                ctx.register_compensation("refund", paid)
                await ctx.call_activity("ship", order)
                return {"paid": paid}
        """
        def register(handler: Handler) -> Handler:
            if not inspect.iscoroutinefunction(handler):
                raise ValidationError(
                    f"workflow orchestrators must be 'async def' "
                    f"({name}: {getattr(handler, '__name__', handler)!r} "
                    "is synchronous)")
            self._workflow_engine().register_workflow(name, handler)
            return handler

        return register

    def activity(self, name: str, *, retry=None, timeout: float | None = None):
        """Register an activity — the effectful half of a workflow.
        ``retry`` takes a :class:`~tasksrunner.resiliency.RetrySpec`
        (defaulting to a bounded exponential policy), ``timeout`` a
        per-attempt deadline in seconds::

            @app.activity("charge", retry=RetrySpec(max_retries=5))
            async def charge(ctx, order):
                ctx.stage_effect(f"charge||{ctx.instance}", order)
                return {"charged": order["amount"]}
        """
        def register(handler: Handler) -> Handler:
            if not inspect.iscoroutinefunction(handler):
                raise ValidationError(
                    f"workflow activities must be 'async def' "
                    f"({name}: {getattr(handler, '__name__', handler)!r} "
                    "is synchronous)")
            self._workflow_engine().register_activity(
                name, handler, retry=retry, timeout=timeout)
            return handler

        return register

    def on_startup(self, fn: Callable[[], Awaitable[None]]):
        self._startup_hooks.append(fn)
        return fn

    def on_shutdown(self, fn: Callable[[], Awaitable[None]]):
        self._shutdown_hooks.append(fn)
        return fn

    # -- lifecycle -------------------------------------------------------

    async def startup(self) -> None:
        for hook in self._startup_hooks:
            await hook()

    async def shutdown(self) -> None:
        for hook in self._shutdown_hooks:
            await hook()

    # -- dispatch --------------------------------------------------------

    def _matches_user_route(self, method: str, path: str) -> bool:
        return any(r.match(method, path) is not None for r in self._routes)

    def openapi(self) -> dict:
        """Minimal OpenAPI 3.1 document generated from the route table
        (≙ the reference API's AddOpenApi/MapOpenApi, Backend.Api
        Program.cs:16 + Microsoft.AspNetCore.OpenApi in the csproj).
        Served at GET /openapi.json on every app."""
        paths: dict[str, dict] = {}
        for route in self._routes:
            if route.kind != "http":
                continue
            template = "/" + "/".join(route.segments)
            entry = paths.setdefault(template, {})
            params = [
                {"name": seg[1:-1], "in": "path", "required": True,
                 "schema": {"type": "string"}}
                for seg in route.segments
                if seg.startswith("{") and seg.endswith("}")
            ]
            op: dict = {
                "operationId": f"{route.method.lower()}_{route.handler.__name__}",
                "responses": {"200": {"description": "success"}},
            }
            if route.handler.__doc__:
                op["description"] = route.handler.__doc__.strip()
            if params:
                op["parameters"] = params
            entry[route.method.lower()] = op
        return {
            "openapi": "3.1.0",
            "info": {"title": self.app_id, "version": "1.0.0"},
            "paths": dict(sorted(paths.items())),
        }

    def subscription_doc(self) -> list[dict]:
        """The /tasksrunner/subscribe handshake document."""
        return [
            {"pubsubname": s.pubsub_name, "topic": s.topic, "route": s.route}
            for s in self.subscriptions
        ]

    async def handle(self, method: str, path: str, *, query: str = "",
                     headers: dict[str, str] | None = None,
                     body: bytes = b"") -> Response:
        self.inflight += 1
        self.requests_total += 1
        try:
            return await self._handle(method, path, query=query,
                                      headers=headers, body=body)
        finally:
            self.inflight -= 1

    async def _handle_actor(self, method: str, clean_path: str,
                            body: bytes,
                            headers: dict[str, str] | None = None,
                            ) -> Response:
        """The sidecar-facing actor channel (reserved, like
        /tasksrunner/subscribe): GET /tasksrunner/actors advertises the
        hosted types; PUT /tasksrunner/actors/{type}/{id}/{method} runs
        one turn. Only the OWNING replica's runtime calls the PUT — the
        one-at-a-time lock is held there, not here."""
        from tasksrunner.actors.turn import ActorTurn

        if method.upper() == "GET" and clean_path == "/tasksrunner/actors":
            return Response(body=sorted(self.actors))
        parts = [p for p in clean_path.split("/") if p != ""]
        # ["tasksrunner", "actors", type, id, method] — ids keep case
        if method.upper() != "PUT" or len(parts) != 5:
            return Response(status=404, body={
                "error": f"no actor route for {method} {clean_path}"})
        actor_type, actor_id, turn_method = parts[2], parts[3], parts[4]
        handler = self.actors.get(actor_type)
        if handler is None:
            return Response(status=404, body={
                "error": f"app {self.app_id!r} hosts no actor type "
                         f"{actor_type!r}"})
        doc = json.loads(body) if body else {}
        turn = ActorTurn(
            actor_type=actor_type, actor_id=actor_id, method=turn_method,
            data=doc.get("data"), state=doc.get("state") or {},
            kind=doc.get("kind") or "turn", reminder=doc.get("reminder"),
        )
        # The owning runtime's _execute_turn sends its turn span's
        # traceparent over the app channel; adopting it here nests the
        # handler's ACTOR span under the turn span. Without the header
        # (older runtime, direct call) the ambient context still flows.
        headers = headers or {}
        traceparent = headers.get(TRACEPARENT_HEADER)
        if traceparent:
            scope = trace_scope(ensure_trace(
                traceparent, headers.get(BAGGAGE_HEADER)))
        else:
            scope = contextlib.nullcontext()
        started = time.time()
        with scope:
            try:
                result = await handler(turn)
                out = {"state": turn.state, "result": result}
                # staged atomics ride the response only when used, keeping
                # the wire doc identical to the pre-workflow protocol for
                # plain actors (old sidecars ignore unknown keys anyway)
                if turn.effects:
                    out["effects"] = turn.effects
                if turn.reminder_sets:
                    out["reminders_set"] = turn.reminder_sets
                if turn.reminder_clears:
                    out["reminders_clear"] = turn.reminder_clears
                resp = Response(body=out)
            except TasksRunnerError as exc:
                resp = Response(status=exc.http_status, body={"error": str(exc)})
            except Exception:
                logger.exception("actor turn %s/%s.%s failed",
                                 actor_type, actor_id, turn_method)
                resp = Response(status=500, body={"error": "internal error"})
            record_span(
                kind="server",
                name=f"ACTOR {actor_type}/{actor_id}.{turn_method}",
                status=resp.status, start=started,
                duration=time.time() - started,
            )
        return resp

    async def _handle(self, method: str, path: str, *, query: str = "",
                      headers: dict[str, str] | None = None,
                      body: bytes = b"") -> Response:
        headers = {k.lower(): v for k, v in (headers or {}).items()}
        clean_path = path.split("?", 1)[0]

        if method.upper() == "GET" and clean_path in ("/tasksrunner/subscribe", "/dapr/subscribe"):
            return Response(body=self.subscription_doc())
        if clean_path == "/tasksrunner/healthz":
            # non-shadowable liveness: the sidecar's startup handshake
            # must not be gated on an app's custom /healthz (an app that
            # reports 503 until warm would otherwise never finish
            # starting — readiness and liveness are different questions)
            return Response(status=204)
        if clean_path == "/healthz" and not self._matches_user_route(method, clean_path):
            # builtin liveness default; an app may register its own
            # /healthz to report real health (the orchestrator's
            # liveness probe then sees it)
            return Response(status=204)
        if method.upper() == "GET" and clean_path == "/openapi.json":
            return Response(body=self.openapi())
        if clean_path.startswith("/tasksrunner/actors"):
            return await self._handle_actor(method, clean_path, body, headers)

        if method.upper() in ("GET", "HEAD"):
            for mount_prefix, read_file in self._static_mounts:
                if clean_path.startswith(mount_prefix):
                    resp = await read_file(clean_path[len(mount_prefix):])
                    if resp is not None:
                        return resp  # miss falls through to routing

        # static routes dispatch O(1) and take precedence over
        # parameterised ones (standard router precedence)
        route = self._exact_routes.get((
            method.upper(),
            "/" + "/".join(p.lower() for p in clean_path.split("/") if p)))
        params: dict[str, str] | None = {} if route is not None else None
        if route is None:
            for candidate in self._routes:
                params = candidate.match(method, clean_path)
                if params is not None:
                    route = candidate
                    break
        if route is not None and params is not None:
            request = Request(
                method=method.upper(), path=clean_path,
                query=dict(parse_qsl(query)), headers=headers,
                body=body, path_params=params,
            )
            # Adopt the caller's trace context (same move the HTTP app
            # server makes at ingress — in-proc and sidecar modes must
            # trace identically).
            ctx = ensure_trace(headers.get(TRACEPARENT_HEADER),
                               headers.get(BAGGAGE_HEADER))
            with trace_scope(ctx):
                started = time.time()
                try:
                    result = route.handler(request)
                    if inspect.isawaitable(result):
                        result = await result
                    resp = _normalize(result)
                except TasksRunnerError as exc:
                    resp = Response(status=exc.http_status, body={"error": str(exc)})
                except Exception:
                    logger.exception("unhandled error in %s %s", method, clean_path)
                    resp = Response(status=500, body={"error": "internal error"})
                record_span(
                    kind="consumer" if route.kind in ("subscription", "binding")
                    else "server",
                    name=f"{method.upper()} {clean_path}", status=resp.status,
                    start=started, duration=time.time() - started,
                )
                return resp
        return Response(status=404, body={"error": f"no route for {method} {clean_path}"})
