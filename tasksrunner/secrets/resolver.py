"""Secret resolution chain for component metadata.

Implements the reference's dev→prod promotion path (SURVEY.md §2.4,
§5.6): inline plaintext values work as-is; ``secretKeyRef``/``secretRef``
entries resolve against a named secret-store component; refs without a
named store fall back to the runtime's default store (env vars), so the
same component file works locally with exported variables.
"""

from __future__ import annotations

from tasksrunner.component.spec import ComponentSpec, SecretRef
from tasksrunner.errors import SecretError
from tasksrunner.secrets.base import SecretStore
from tasksrunner.secrets.local import EnvSecretStore


class SecretResolver:
    """Maps store names → ``SecretStore`` instances and resolves specs."""

    def __init__(self, *, default_store: SecretStore | None = None):
        self._stores: dict[str, SecretStore] = {}
        self.default_store = default_store or EnvSecretStore()

    def add_store(self, store: SecretStore) -> None:
        self._stores[store.name] = store

    def store(self, name: str | None) -> SecretStore:
        if name is None:
            return self.default_store
        try:
            return self._stores[name]
        except KeyError:
            raise SecretError(f"secret store {name!r} is not registered") from None

    def resolve_value(self, value: str | SecretRef) -> str:
        if isinstance(value, str):
            return value
        return self.store(value.store).get(value.key)

    def resolve_metadata(self, spec: ComponentSpec) -> dict[str, str]:
        """Return the spec's metadata with every SecretRef materialised."""
        out: dict[str, str] = {}
        for key, value in spec.metadata.items():
            try:
                out[key] = self.resolve_value(value)
            except SecretError as exc:
                raise SecretError(
                    f"component {spec.name!r}: cannot resolve metadata {key!r}: {exc}"
                ) from exc
        return out
