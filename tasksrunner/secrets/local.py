"""Local secret-store drivers: env-var, file-backed, and static.

These are the framework's stand-ins for the reference's Azure Key Vault
store (type ``secretstores.azure.keyvault``,
aca-components/containerapps-secretstore-kv.yaml) — same contract, local
backends, exactly as Redis stands in for Cosmos locally in the
reference.
"""

from __future__ import annotations

import json
import os
import pathlib

import yaml

from tasksrunner.errors import SecretError, SecretNotFound
from tasksrunner.secrets.base import SecretStore


class EnvSecretStore(SecretStore):
    """Secrets from process environment variables.

    ``prefix`` namespaces lookups (key ``api-key`` with prefix ``TR_``
    reads ``TR_API_KEY``); dashes map to underscores, case-insensitive —
    so component files can keep cloud-style kebab-case secret names.
    """

    def __init__(self, name: str = "envsecretstore", *, prefix: str = ""):
        super().__init__(name)
        self.prefix = prefix

    def _envname(self, key: str) -> str:
        return (self.prefix + key).replace("-", "_").upper()

    def get(self, key: str) -> str:
        env = self._envname(key)
        if env in os.environ:
            return os.environ[env]
        # Exact-name fallback only for unprefixed stores — a prefix is a
        # namespace boundary and must not leak the whole environment.
        if not self.prefix and key in os.environ:
            return os.environ[key]
        raise SecretNotFound(f"secret {key!r} not in environment (looked for {env})")

    def keys(self) -> list[str]:
        if not self.prefix:
            return sorted(os.environ)
        pfx = self._envname("")
        return sorted(k[len(pfx):].lower().replace("_", "-") for k in os.environ if k.startswith(pfx))


class FileSecretStore(SecretStore):
    """Secrets from a JSON or YAML file of flat key→value pairs.

    Nested objects are flattened with ``:`` separators the way the
    reference's .NET config flattens (``SendGrid:ApiKey``), so one file
    can serve both config-style and secret-style lookups.
    """

    def __init__(self, name: str, path: str | pathlib.Path, *, nested_separator: str = ":"):
        super().__init__(name)
        self.path = pathlib.Path(path)
        self.nested_separator = nested_separator
        self._data = self._load()

    def _load(self) -> dict[str, str]:
        try:
            text = self.path.read_text()
        except OSError as exc:
            raise SecretError(f"cannot read secret file {self.path}: {exc}") from exc
        try:
            if self.path.suffix in (".yaml", ".yml"):
                raw = yaml.safe_load(text) or {}
            else:
                raw = json.loads(text or "{}")
        except (yaml.YAMLError, json.JSONDecodeError) as exc:
            raise SecretError(f"cannot parse secret file {self.path}: {exc}") from exc
        if not isinstance(raw, dict):
            raise SecretError(f"secret file {self.path} must hold a mapping")
        flat: dict[str, str] = {}

        def walk(prefix: str, node) -> None:
            if isinstance(node, dict):
                for k, v in node.items():
                    walk(f"{prefix}{self.nested_separator}{k}" if prefix else str(k), v)
            else:
                flat[prefix] = "" if node is None else str(node)

        walk("", raw)
        return flat

    def reload(self) -> None:
        self._data = self._load()

    def get(self, key: str) -> str:
        try:
            return self._data[key]
        except KeyError:
            raise SecretNotFound(f"secret {key!r} not in {self.path}") from None

    def keys(self) -> list[str]:
        return sorted(self._data)


class StaticSecretStore(SecretStore):
    """In-memory secrets — the test double, and the backing store for
    inline ``secrets:`` lists in cloud-dialect component files."""

    def __init__(self, name: str, data: dict[str, str] | None = None):
        super().__init__(name)
        self._data = dict(data or {})

    def set(self, key: str, value: str) -> None:
        self._data[key] = value

    def get(self, key: str) -> str:
        try:
            return self._data[key]
        except KeyError:
            raise SecretNotFound(f"secret {key!r} not in store {self.name!r}") from None

    def keys(self) -> list[str]:
        return sorted(self._data)
