"""Secret-store building block interface.

API surface mirrors the reference's secret API (sidecar route
``GET /v1.0/secrets/{store}/{key}``, returning ``{key: value}``) and the
Key Vault-backed component ``secretstoreakv``
(aca-components/containerapps-secretstore-kv.yaml:1-7).
"""

from __future__ import annotations

import abc


class SecretStore(abc.ABC):
    """A named source of secrets.

    Implementations are synchronous: secret reads happen at component
    init and on the (rare) secret API path, never in a hot loop.
    """

    def __init__(self, name: str):
        self.name = name

    @abc.abstractmethod
    def get(self, key: str) -> str:
        """Return the secret value or raise ``SecretNotFound``."""

    @abc.abstractmethod
    def keys(self) -> list[str]:
        """List available secret names (bulk-secret API)."""

    def bulk(self) -> dict[str, str]:
        return {k: self.get(k) for k in self.keys()}

    def close(self) -> None:  # pragma: no cover - default no-op
        pass
