from tasksrunner.secrets.base import SecretStore
from tasksrunner.secrets.local import EnvSecretStore, FileSecretStore, StaticSecretStore
from tasksrunner.secrets.resolver import SecretResolver

__all__ = [
    "SecretStore",
    "EnvSecretStore",
    "FileSecretStore",
    "StaticSecretStore",
    "SecretResolver",
]
