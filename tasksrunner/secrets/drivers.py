"""Component drivers for the secret-store building block.

Registered type names follow the reference's taxonomy
(``secretstores.azure.keyvault`` in
aca-components/containerapps-secretstore-kv.yaml) with local engines;
the azure type is aliased to the env-var store so the reference's
component file loads unchanged in local mode.
"""

from __future__ import annotations

from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.secrets.local import EnvSecretStore, FileSecretStore, StaticSecretStore


@driver("secretstores.local.env", "secretstores.azure.keyvault")
def _env_secret_store(spec: ComponentSpec, metadata: dict[str, str]) -> EnvSecretStore:
    return EnvSecretStore(spec.name, prefix=metadata.get("prefix", ""))


@driver("secretstores.local.file")
def _file_secret_store(spec: ComponentSpec, metadata: dict[str, str]) -> FileSecretStore:
    return FileSecretStore(
        spec.name,
        metadata["secretsFile"],
        nested_separator=metadata.get("nestedSeparator", ":"),
    )


@driver("secretstores.local.static")
def _static_secret_store(spec: ComponentSpec, metadata: dict[str, str]) -> StaticSecretStore:
    return StaticSecretStore(spec.name, metadata)
