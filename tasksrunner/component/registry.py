"""Driver registry + per-app component registry.

``driver`` registers a factory for a component ``type`` string
(``state.sqlite``, ``pubsub.memory``, ``bindings.cron``...). A
``ComponentRegistry`` holds the specs visible to one app-id and
instantiates them lazily with secrets resolved.

Type aliasing lets the reference's cloud-typed component files
(``state.azure.cosmosdb``, ``pubsub.azure.servicebus``,
``bindings.azure.storagequeues``...) run unchanged against local-parity
drivers — the framework analog of the reference's "swap Redis in for
Cosmos locally" move (docs/aca/04-aca-dapr-stateapi/index.md:29-33),
inverted: we keep the cloud file and swap the engine underneath.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import (
    ComponentNotFound,
    ComponentScopeError,
    DriverNotFound,
)
from tasksrunner.secrets.base import SecretStore
from tasksrunner.secrets.resolver import SecretResolver

#: factory(spec, resolved_metadata) -> component instance
DriverFactory = Callable[[ComponentSpec, dict[str, str]], Any]

_DRIVERS: dict[str, DriverFactory] = {}


def driver(type_name: str, *aliases: str) -> Callable[[DriverFactory], DriverFactory]:
    """Register a component driver for one or more ``type`` strings."""

    def register(factory: DriverFactory) -> DriverFactory:
        for t in (type_name, *aliases):
            _DRIVERS[t] = factory
        return factory

    return register


def resolve_driver(type_name: str) -> DriverFactory:
    try:
        return _DRIVERS[type_name]
    except KeyError:
        known = ", ".join(sorted(_DRIVERS))
        raise DriverNotFound(
            f"no driver for component type {type_name!r} (known: {known})"
        ) from None


def registered_types() -> list[str]:
    return sorted(_DRIVERS)


class ComponentRegistry:
    """Instantiated components for one app identity.

    Mirrors a sidecar's view of its resources directory: only specs in
    scope are visible; the same YAML served to two app-ids yields two
    scoped views (SURVEY.md §2.4 scope column).

    Secret-store components are instantiated eagerly at construction and
    wired into the resolver, because every other component's secretRef
    resolution may depend on them (reference: ``secretStoreComponent``
    indirection, aca-components/containerapps-bindings-in-storagequeue.yaml:3-8).
    """

    def __init__(
        self,
        specs: list[ComponentSpec],
        *,
        app_id: str | None = None,
        secret_resolver: SecretResolver | None = None,
        chaos: Any = None,
    ):
        self.app_id = app_id
        self.resolver = secret_resolver or SecretResolver()
        #: ChaosPolicies when fault injection is active (TASKSRUNNER_CHAOS=1
        #: and a Chaos doc in scope); None means _build returns bare
        #: driver instances — the production path allocates no wrappers.
        self.chaos = chaos
        self._specs: dict[str, ComponentSpec] = {}
        self._instances: dict[str, Any] = {}

        for spec in specs:
            if spec.in_scope(app_id):
                self._specs[spec.name] = spec

        # Pass 1: secret stores first (see docstring). Inline `secrets:`
        # lists need no store here — parse_cloud_schema already
        # materialised refs against them at parse time.
        for spec in self._specs.values():
            if spec.block == "secretstores":
                store = self._build(spec)
                if isinstance(store, SecretStore):
                    self.resolver.add_store(store)
                self._instances[spec.name] = store

    # -- construction ----------------------------------------------------

    def _build(self, spec: ComponentSpec) -> Any:
        factory = resolve_driver(spec.type)
        metadata = self.resolver.resolve_metadata(spec)
        instance = factory(spec, metadata)
        if self.chaos is not None:
            from tasksrunner.chaos.wrappers import wrap_component

            instance = wrap_component(instance, spec, self.chaos)
        return instance

    # -- lookup ----------------------------------------------------------

    def spec(self, name: str) -> ComponentSpec:
        try:
            return self._specs[name]
        except KeyError:
            raise ComponentNotFound(
                f"component {name!r} is not registered"
                + (f" for app {self.app_id!r}" if self.app_id else "")
            ) from None

    def get(self, name: str, *, block: str | None = None) -> Any:
        """Return (building lazily) the component instance ``name``.

        ``block`` asserts the building-block family — asking the state
        API for a pubsub component is a 400, as in the reference.
        """
        spec = self.spec(name)
        if block is not None and spec.block != block:
            raise ComponentNotFound(
                f"component {name!r} is {spec.type!r}, not a {block} component"
            )
        if name not in self._instances:
            self._instances[name] = self._build(spec)
        return self._instances[name]

    def names(self, block: str | None = None) -> list[str]:
        return sorted(
            n for n, s in self._specs.items() if block is None or s.block == block
        )

    def check_scope(self, name: str, app_id: str) -> None:
        """Explicit scope check for multi-tenant registries."""
        spec = self.spec(name)
        if not spec.in_scope(app_id):
            raise ComponentScopeError(
                f"component {name!r} is not scoped to app {app_id!r}"
            )

    # -- lifecycle -------------------------------------------------------

    async def close(self) -> None:
        """Close every instantiated component (sync or async close).

        One failing close must not leak the rest; errors are collected
        and re-raised together after everything has been attempted.
        """
        errors: list[Exception] = []
        for name, instance in list(self._instances.items()):
            closer = getattr(instance, "aclose", None) or getattr(instance, "close", None)
            if closer is None:
                continue
            try:
                result = closer()
                if inspect.isawaitable(result):
                    await result
            except Exception as exc:
                exc.add_note(f"while closing component {name!r}")
                errors.append(exc)
        self._instances.clear()
        if errors:
            raise ExceptionGroup("component close failures", errors)
