"""Component specification and the two YAML schemas that produce it.

The reference configures every pluggable backend through named, typed,
scoped component files in two schema dialects:

* the "local" dialect (``apiVersion``/``kind: Component``/``spec``),
  e.g. ``/root/reference/components/dapr-statestore-cosmos.yaml:1-18``;
* the "cloud" dialect (flattened: ``componentType``/``version``/
  ``metadata``/``secrets``/``scopes``), e.g.
  ``/root/reference/aca-components/containerapps-statestore-cosmos.yaml:1-11``.

The core invariant (SURVEY.md §1 L1): application code refers to
components **by name only**; swapping the file swaps the backend with
zero code change. Both dialects parse into one ``ComponentSpec``.

Secrets may appear three ways, mirroring the reference's dev→prod
promotion path (SURVEY.md §2.4 end):

* inline plaintext ``value`` (local dev);
* ``secretKeyRef: {name, key}`` (local dialect) resolved against the
  store named by ``auth.secretStore``;
* ``secretRef: <name>`` (cloud dialect) resolved against the file's own
  ``secrets:`` list first, then against ``secretStoreComponent``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping

from tasksrunner.errors import ComponentError


def scalar_str(value: Any) -> str:
    """Render a YAML scalar as the string a component driver expects.

    Component metadata is string-typed; unquoted YAML booleans must
    come out as ``"true"``/``"false"`` (not Python's ``"True"``) or
    drivers checking ``== "true"`` silently misread them.
    """
    if value is None:
        return ""
    if value is True:
        return "true"
    if value is False:
        return "false"
    return str(value)


def metadata_bool(metadata: Mapping[str, Any], key: str, default: bool) -> bool:
    """Coerce a string-typed metadata value to bool, failing loudly.

    Component metadata is YAML-sourced strings (``scalar_str``); every
    driver re-parsing booleans ad hoc invites the ``== "True"`` class of
    silent misread this module's docstring warns about — parse here.
    """
    raw = metadata.get(key)
    if raw is None or raw == "":
        return default
    val = str(raw).strip().lower()
    if val in ("1", "true", "yes", "on"):
        return True
    if val in ("0", "false", "no", "off"):
        return False
    raise ComponentError(f"metadata {key!r} must be a boolean, not {raw!r}")


def metadata_int(metadata: Mapping[str, Any], key: str, default: int) -> int:
    """Coerce a string-typed metadata value to int, failing loudly."""
    raw = metadata.get(key)
    if raw is None or raw == "":
        return default
    try:
        return int(str(raw).strip())
    except ValueError:
        raise ComponentError(
            f"metadata {key!r} must be an integer, not {raw!r}") from None


@dataclass(frozen=True)
class SecretRef:
    """A deferred secret lookup: resolve ``key`` in secret store ``store``.

    ``store`` may be ``None``, meaning "the spec declared a ref but
    named no secret store" — resolution then uses the runtime's default
    secret store, or fails loudly.
    """

    key: str
    store: str | None = None


@dataclass
class ComponentSpec:
    """A parsed, schema-neutral component definition."""

    name: str
    type: str
    version: str = "v1"
    #: Metadata values: plain strings, or SecretRef for deferred secrets.
    metadata: dict[str, str | SecretRef] = field(default_factory=dict)
    #: App-ids allowed to use this component. Empty = visible to all.
    scopes: list[str] = field(default_factory=list)
    #: Inline secrets carried by the cloud dialect's ``secrets:`` list.
    inline_secrets: dict[str, str] = field(default_factory=dict)
    #: Default secret store for refs that don't name one.
    secret_store: str | None = None
    #: Where this spec was loaded from (diagnostics only).
    source: str | None = None

    def in_scope(self, app_id: str | None) -> bool:
        """Whether ``app_id`` may use this component.

        ``None`` (no app identity, e.g. tests driving the registry
        directly) sees everything, like `dapr run` without app-id.
        """
        if not self.scopes or app_id is None:
            return True
        return app_id in self.scopes

    @property
    def block(self) -> str:
        """Building-block family: the first dot-segment of ``type``.

        ``state.sqlite`` → ``state``; ``bindings.cron`` → ``bindings``;
        matches the reference's type taxonomy (state.*, pubsub.*,
        bindings.*, secretstores.*).
        """
        return self.type.split(".", 1)[0]


def _metadata_items(raw: Any, *, where: str) -> list[Mapping[str, Any]]:
    if raw is None:
        return []
    if not isinstance(raw, list):
        raise ComponentError(f"{where}: metadata must be a list of items")
    for item in raw:
        if not isinstance(item, Mapping) or "name" not in item:
            raise ComponentError(f"{where}: each metadata item needs a name")
    return raw


def _parse_scopes(raw: Any, *, where: str) -> list[str]:
    if raw is None:
        return []
    if not isinstance(raw, list) or not all(isinstance(s, str) for s in raw):
        raise ComponentError(f"{where}: scopes must be a list of app-ids")
    return list(raw)


def parse_local_schema(doc: Mapping[str, Any], *, default_name: str, source: str | None = None) -> ComponentSpec:
    """Parse the local dialect (``kind: Component`` + ``spec``)."""
    where = source or default_name
    meta = doc.get("metadata") or {}
    name = meta.get("name") or default_name
    spec = doc.get("spec")
    if not isinstance(spec, Mapping) or "type" not in spec:
        raise ComponentError(f"{where}: missing spec.type")

    auth = doc.get("auth") or {}
    secret_store = auth.get("secretStore")

    metadata: dict[str, str | SecretRef] = {}
    for item in _metadata_items(spec.get("metadata"), where=where):
        key = str(item["name"])
        if "secretKeyRef" in item:
            ref = item["secretKeyRef"] or {}
            metadata[key] = SecretRef(
                key=str(ref.get("key") or ref.get("name") or key),
                store=secret_store,
            )
        elif "value" in item:
            metadata[key] = scalar_str(item["value"])
        else:
            raise ComponentError(f"{where}: metadata item {key!r} needs value or secretKeyRef")

    return ComponentSpec(
        name=str(name),
        type=str(spec["type"]),
        version=str(spec.get("version", "v1")),
        metadata=metadata,
        scopes=_parse_scopes(doc.get("scopes"), where=where),
        secret_store=secret_store,
        source=source,
    )


def parse_cloud_schema(doc: Mapping[str, Any], *, default_name: str, source: str | None = None) -> ComponentSpec:
    """Parse the cloud dialect (flattened ``componentType`` schema).

    The cloud dialect carries no component name in-file (the deploy
    command names it); ``default_name`` (filename stem or manifest key)
    supplies it.
    """
    where = source or default_name
    ctype = doc.get("componentType")
    if not ctype:
        raise ComponentError(f"{where}: missing componentType")

    secret_store = doc.get("secretStoreComponent")

    inline_secrets: dict[str, str] = {}
    for item in doc.get("secrets") or []:
        if not isinstance(item, Mapping) or "name" not in item:
            raise ComponentError(f"{where}: each secrets item needs a name")
        inline_secrets[str(item["name"])] = scalar_str(item.get("value", ""))

    metadata: dict[str, str | SecretRef] = {}
    for item in _metadata_items(doc.get("metadata"), where=where):
        key = str(item["name"])
        if "secretRef" in item:
            ref_name = str(item["secretRef"])
            if ref_name in inline_secrets:
                metadata[key] = inline_secrets[ref_name]
            else:
                metadata[key] = SecretRef(key=ref_name, store=secret_store)
        elif "value" in item:
            metadata[key] = scalar_str(item["value"])
        else:
            raise ComponentError(f"{where}: metadata item {key!r} needs value or secretRef")

    return ComponentSpec(
        name=str(doc.get("name") or default_name),
        type=str(ctype),
        version=str(doc.get("version", "v1")),
        metadata=metadata,
        scopes=_parse_scopes(doc.get("scopes"), where=where),
        inline_secrets=inline_secrets,
        secret_store=secret_store,
        source=source,
    )


def parse_component(doc: Mapping[str, Any], *, default_name: str, source: str | None = None) -> ComponentSpec:
    """Dispatch on schema dialect."""
    if not isinstance(doc, Mapping):
        raise ComponentError(f"{source or default_name}: component document must be a mapping")
    if "componentType" in doc:
        return parse_cloud_schema(doc, default_name=default_name, source=source)
    if doc.get("kind") == "Component" or "spec" in doc:
        return parse_local_schema(doc, default_name=default_name, source=source)
    raise ComponentError(
        f"{source or default_name}: unrecognised component schema "
        "(expected kind: Component or componentType)"
    )
