from tasksrunner.component.spec import ComponentSpec, SecretRef
from tasksrunner.component.loader import load_components, load_component_file
from tasksrunner.component.registry import ComponentRegistry, driver

__all__ = [
    "ComponentSpec",
    "SecretRef",
    "load_components",
    "load_component_file",
    "ComponentRegistry",
    "driver",
]
