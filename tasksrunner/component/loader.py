"""Load component specs from a resources directory.

Equivalent surface: `dapr run --resources-path ./components` loading
every YAML in the folder (reference: snippets/dapr-run-backend-api.md),
and `az containerapp env dapr-component set --yaml` loading a single
cloud-dialect file whose component name comes from the CLI
(docs/aca/04-aca-dapr-stateapi/index.md).
"""

from __future__ import annotations

import pathlib
from typing import Iterable

import yaml

from tasksrunner.component.spec import ComponentSpec, parse_component
from tasksrunner.errors import ComponentError
from tasksrunner.chaos.spec import is_chaos_doc
from tasksrunner.resiliency.spec import is_resiliency_doc

_YAML_SUFFIXES = {".yaml", ".yml"}


def load_component_file(path: str | pathlib.Path, *, name: str | None = None) -> list[ComponentSpec]:
    """Parse one YAML file (may hold multiple ``---`` documents)."""
    path = pathlib.Path(path)
    try:
        text = path.read_text()
    except OSError as exc:
        raise ComponentError(f"cannot read component file {path}: {exc}") from exc

    try:
        docs = list(yaml.safe_load_all(text))
    except yaml.YAMLError as exc:
        raise ComponentError(
            f"component file {path} is not valid YAML: {exc}") from exc

    specs: list[ComponentSpec] = []
    for doc in docs:
        if doc is None:
            continue
        if is_resiliency_doc(doc) or is_chaos_doc(doc):
            # Resiliency and Chaos documents share the resources
            # directory (tasksrunner/resiliency/spec.py and
            # tasksrunner/chaos/spec.py load them)
            continue
        specs.append(parse_component(doc, default_name=name or path.stem, source=str(path)))
    return specs


def load_components(
    resources_path: str | pathlib.Path,
    *,
    app_id: str | None = None,
) -> list[ComponentSpec]:
    """Load every component YAML under ``resources_path``.

    ``app_id`` filters by scope the way the sidecar only loads
    components visible to its app. Duplicate names are an error — the
    name is the app-facing identity and must be unambiguous.
    """
    root = pathlib.Path(resources_path)
    if not root.is_dir():
        raise ComponentError(f"resources path {root} is not a directory")

    specs: list[ComponentSpec] = []
    for path in sorted(root.iterdir()):
        if path.suffix.lower() not in _YAML_SUFFIXES or not path.is_file():
            continue
        specs.extend(load_component_file(path))

    seen: dict[str, ComponentSpec] = {}
    for spec in specs:
        if spec.name in seen:
            raise ComponentError(
                f"duplicate component name {spec.name!r} "
                f"({seen[spec.name].source} and {spec.source})"
            )
        seen[spec.name] = spec

    if app_id is not None:
        specs = [s for s in specs if s.in_scope(app_id)]
    return specs


def dump_components(specs: Iterable[ComponentSpec]) -> str:
    """Render specs back to local-dialect YAML (diagnostics / what-if)."""
    docs = []
    for s in specs:
        meta_items = []
        for key, value in s.metadata.items():
            if isinstance(value, str):
                meta_items.append({"name": key, "value": value})
            else:
                meta_items.append(
                    {"name": key, "secretKeyRef": {"name": value.key, "key": value.key}}
                )
        doc: dict = {
            "apiVersion": "tasksrunner/v1",
            "kind": "Component",
            "metadata": {"name": s.name},
            "spec": {"type": s.type, "version": s.version, "metadata": meta_items},
        }
        if s.scopes:
            doc["scopes"] = list(s.scopes)
        if s.secret_store:
            doc["auth"] = {"secretStore": s.secret_store}
        docs.append(doc)
    return yaml.safe_dump_all(docs, sort_keys=False)
