"""The demo workload service: the scorer hosted as a runtime app.

EXTENSION ONLY (see package docstring) — this is the pattern for
hosting compute on tasksrunner: a model served by an ordinary ``App``
that participates in the same building blocks as every other service.

* ``POST /score`` — synchronous inference: task JSON in, priority
  class + confidence out (service-invocation callable:
  ``client.invoke_method("priority-scorer", "score", ...)`` — and over
  processes that lane rides the binary mesh codec like every other
  invoke). Responses echo the request's ``taskId`` so callers can
  match concurrent scores to their tasks.
* subscribes to ``tasksavedtopic`` — every saved task is scored
  asynchronously and the score written to the ``scores`` state
  component, exactly how the Tasks Tracker processor consumes the
  same topic.
* ``GET /scores/{task_id}`` — read a stored score back.
* ``GET /ml/stats`` — serving-plane introspection: queue depth,
  per-bucket batch counts, and the jit cache size (flat after warmup
  == zero recompiles; the bench and tests assert on it).

Serving runs on the continuous-batching engine
(:mod:`tasksrunner.ml.batching`): requests queue, micro-batches
assemble under the ``TASKSRUNNER_ML_MAX_DELAY_MS`` budget, batch
shapes pad up a fixed bucket ladder, and each bucket jit-compiles
exactly once at startup warmup. Params are device-put once — fully
replicated over a 1-D data mesh when >1 device is visible, with the
batch dimension sharded over the mesh for bucket sizes the device
count divides. The batcher's tokens-in-flight ratio registers with
the admission controller, so floods shed 429+Retry-After at the front
door, and its ``ml_*`` histograms feed the target-p99 autoscale rule.

During warmup both lanes answer a retryable not-ready — 503 with a
``Retry-After`` header. The runtime turns that header into a
redelivery backoff (pubsub ``Nack``), so the broker stops hot-looping
deliveries while XLA compiles and no attempt budget is burned before
``compiled`` is populated.
"""

from __future__ import annotations

import asyncio
import logging

from tasksrunner.app import App, Response
from tasksrunner.errors import SaturatedError

logger = logging.getLogger(__name__)

PRIORITY_LABELS = ["backlog", "low", "normal", "high", "urgent"]

#: seconds the not-ready paths ask clients/brokers to stay away; one
#: beat is enough — warmup is seconds, and redeliveries only need to
#: stop arriving *every retry_delay tick*
WARMUP_RETRY_AFTER = 1


def make_app(*, pubsub: str = "taskspubsub", topic: str = "tasksavedtopic",
             state_store: str = "scores") -> App:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tasksrunner.envflag import env_flag
    from tasksrunner.ml.batching import BatcherConfig, MicroBatcher
    from tasksrunner.ml.model import (
        ModelConfig, forward, hash_token_ids, init_params, replicate_params,
        serving_mesh,
    )
    from tasksrunner.observability import admission

    cfg = ModelConfig(n_classes=len(PRIORITY_LABELS))
    app = App("priority-scorer")
    compiled = {}

    bcfg = BatcherConfig.from_env()
    if not env_flag("TASKSRUNNER_ML_BATCHING"):
        bcfg = bcfg.serial()

    def _place_tokens(tokens: np.ndarray):
        """Shard the batch dimension over the data mesh when it
        divides evenly, else replicate. Either way each bucket keeps
        exactly one (shape, sharding) pair, so the jit cache stays one
        entry per bucket."""
        mesh = compiled.get("mesh")
        if mesh is None:
            return tokens
        spec = P("dp", None) if tokens.shape[0] % mesh.size == 0 else P(None, None)
        return jax.device_put(tokens, NamedSharding(mesh, spec))

    def _run_batch(rows: list[np.ndarray], bucket: int) -> list[dict]:
        tokens = np.zeros((bucket, cfg.seq_len), np.int32)
        for i, row in enumerate(rows):
            tokens[i] = row
        probs = np.asarray(compiled["fn"](compiled["params"],
                                          _place_tokens(tokens)))
        out = []
        for i in range(len(rows)):
            idx = int(probs[i].argmax())
            out.append({
                "priority": PRIORITY_LABELS[idx],
                "confidence": round(float(probs[i, idx]), 4),
            })
        return out

    batcher = MicroBatcher(
        _run_batch, config=bcfg,
        tokens_of=lambda row: int((row != 0).sum()) or 1)

    @app.on_startup
    async def load_model():
        def build():
            params = init_params(cfg, jax.random.PRNGKey(0))
            mesh = serving_mesh()
            # device-resident once: replicated over the data mesh (or
            # committed to the single device) — serving calls never
            # re-feed weights
            params = (replicate_params(params, mesh) if mesh is not None
                      else jax.device_put(params))
            # softmax inside the jit region: one device→host transfer
            # per batch, of exactly the probabilities
            fn = jax.jit(lambda p, t: jax.nn.softmax(
                forward(p, t, cfg=cfg), axis=-1))
            compiled["mesh"] = mesh
            # warm every bucket so no request ever pays an XLA compile
            # — after this loop the jit cache must stay flat
            for bucket in bcfg.buckets:
                fn(params, _place_tokens(
                    np.zeros((bucket, cfg.seq_len), np.int32))
                   ).block_until_ready()
            return params, fn

        # compile off the event loop: the server/sidecar are already up,
        # and probes + the 503 not-ready paths must answer during the
        # (potentially tens of seconds) XLA compile
        params, fn = await asyncio.to_thread(build)
        batcher.start()
        admission.register_signal("ml_tokens_in_flight", batcher.saturation)
        compiled["params"], compiled["fn"] = params, fn

    @app.on_shutdown
    async def unload_model():
        admission.unregister_signal("ml_tokens_in_flight")
        await batcher.stop()

    def _not_ready() -> Response:
        # registered and serving, but the jit warmup hasn't finished: a
        # retryable not-ready with a backoff hint, never an opaque 500
        # — the Retry-After is what keeps broker redeliveries from
        # hot-looping against a loading model
        return Response(503, {"error": "model loading, retry shortly"},
                        headers={"Retry-After": str(WARMUP_RETRY_AFTER)})

    def _shed(exc: SaturatedError) -> Response:
        return Response(429, {"error": str(exc)},
                        headers={"Retry-After": str(int(exc.retry_after or 1))})

    def _encode(task: dict) -> np.ndarray:
        text = " ".join(
            str(task.get(k, "")) for k in
            ("taskName", "taskCreatedBy", "taskAssignedTo") if task.get(k))
        return np.asarray(hash_token_ids(text or "empty", cfg), np.int32)

    async def _score(task: dict) -> dict:
        return await batcher.submit(_encode(task))

    @app.post("/score")
    async def score(req):
        if "fn" not in compiled:
            return _not_ready()
        try:
            task = req.json()
        except ValueError:
            return 400, {"error": "body must be JSON"}
        if not isinstance(task, dict):
            return 400, {"error": "body must be a task object"}
        try:
            result = await _score(task)
        except SaturatedError as exc:
            return _shed(exc)
        if task.get("taskId") is not None:
            result = {**result, "taskId": str(task["taskId"])}
        return result

    @app.subscribe(pubsub=pubsub, topic=topic, route="/on-task-saved")
    async def on_task_saved(req):
        if "fn" not in compiled:
            return _not_ready()  # Retry-After → broker backs off
        task = req.data  # CloudEvents envelope unwrapped
        if not isinstance(task, dict) or not task.get("taskId"):
            return 200  # not a task event; ack and move on
        try:
            result = await _score(task)
        except SaturatedError as exc:
            return _shed(exc)  # Retry-After → broker backs off
        await app.client.save_state(state_store, str(task["taskId"]), result)
        logger.info("scored task %s: %s (%.2f)", task["taskId"],
                    result["priority"], result["confidence"])
        return 200

    @app.get("/scores/{task_id}")
    async def get_score(req):
        value = await app.client.get_state(state_store, req.path_params["task_id"])
        if value is None:
            return 404, {"error": f"no score for {req.path_params['task_id']}"}
        return value

    @app.get("/ml/stats")
    async def ml_stats(req):
        stats = batcher.stats()
        stats["ready"] = "fn" in compiled
        fn = compiled.get("fn")
        stats["jit_cache_size"] = int(fn._cache_size()) if fn is not None else 0
        return stats

    return app
