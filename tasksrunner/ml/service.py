"""The demo workload service: the scorer hosted as a runtime app.

EXTENSION ONLY (see package docstring) — this is the pattern for
hosting compute on tasksrunner: a model served by an ordinary ``App``
that participates in the same building blocks as every other service.

* ``POST /score`` — synchronous inference: task JSON in, priority
  class + confidence out (service-invocation callable:
  ``client.invoke_method("priority-scorer", "score", ...)``).
* subscribes to ``tasksavedtopic`` — every saved task is scored
  asynchronously and the score written to the ``scores`` state
  component, exactly how the Tasks Tracker processor consumes the
  same topic.
* ``GET /scores/{task_id}`` — read a stored score back.

The model jits once at startup (TPU: first call compiles, the rest
replay the executable); scoring batches of one are still MXU matmuls
in bfloat16.
"""

from __future__ import annotations

import asyncio
import logging

from tasksrunner.app import App

logger = logging.getLogger(__name__)

PRIORITY_LABELS = ["backlog", "low", "normal", "high", "urgent"]


def make_app(*, pubsub: str = "taskspubsub", topic: str = "tasksavedtopic",
             state_store: str = "scores") -> App:
    import jax

    from tasksrunner.ml.model import (
        ModelConfig, forward, hash_tokens, init_params,
    )

    cfg = ModelConfig(n_classes=len(PRIORITY_LABELS))
    app = App("priority-scorer")
    compiled = {}

    @app.on_startup
    async def load_model():
        def build():
            params = init_params(cfg, jax.random.PRNGKey(0))
            fn = jax.jit(lambda p, t: forward(p, t, cfg=cfg))
            # warm the cache so the first request doesn't pay compilation
            fn(params, hash_tokens(["warmup"], cfg)).block_until_ready()
            return params, fn

        # compile off the event loop: the server/sidecar are already up,
        # and probes + the 503 not-ready paths must answer during the
        # (potentially tens of seconds) XLA compile
        compiled["params"], compiled["fn"] = await asyncio.to_thread(build)

    def _score_sync(task: dict) -> dict:
        text = " ".join(
            str(task.get(k, "")) for k in
            ("taskName", "taskCreatedBy", "taskAssignedTo") if task.get(k))
        logits = compiled["fn"](compiled["params"], hash_tokens([text or "empty"], cfg))
        probs = jax.nn.softmax(logits[0])
        idx = int(logits[0].argmax())
        return {
            "priority": PRIORITY_LABELS[idx],
            "confidence": round(float(probs[idx]), 4),
        }

    async def _score(task: dict) -> dict:
        # off the event loop: with a real model an inference takes long
        # enough to stall every concurrent request/delivery/probe on
        # this app (JAX releases the GIL during device compute)
        return await asyncio.to_thread(_score_sync, task)

    @app.post("/score")
    async def score(req):
        if not compiled:
            # registered and serving, but the jit warmup hasn't
            # finished: a retryable not-ready, never an opaque 500
            return 503, {"error": "model loading, retry shortly"}
        try:
            task = req.json()
        except ValueError:
            return 400, {"error": "body must be JSON"}
        if not isinstance(task, dict):
            return 400, {"error": "body must be a task object"}
        return await _score(task)

    @app.subscribe(pubsub=pubsub, topic=topic, route="/on-task-saved")
    async def on_task_saved(req):
        if not compiled:
            return 503  # non-2xx: broker redelivers after the warmup
        task = req.data  # CloudEvents envelope unwrapped
        if not isinstance(task, dict) or not task.get("taskId"):
            return 200  # not a task event; ack and move on
        result = await _score(task)
        await app.client.save_state(state_store, str(task["taskId"]), result)
        logger.info("scored task %s: %s (%.2f)", task["taskId"],
                    result["priority"], result["confidence"])
        return 200

    @app.get("/scores/{task_id}")
    async def get_score(req):
        value = await app.client.get_state(state_store, req.path_params["task_id"])
        if value is None:
            return 404, {"error": f"no score for {req.path_params['task_id']}"}
        return value

    return app
