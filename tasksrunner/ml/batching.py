"""Continuous-batching engine for the ML serving plane.

EXTENSION ONLY (see package docstring) — this is the scheduling layer
that turns batch-of-one scoring into device-occupancy-shaped serving,
the compile-once/serve-many framing from the TVM / Julia-to-TPU line
of work: fix the set of compiled shapes up front, keep the executable
hot, and reduce throughput to a queueing problem the runtime's
admission/autoscale loop can already see.

Three mechanisms, one class:

* **Micro-batch assembly under a latency budget.** Requests enter a
  queue; the worker flushes a batch on ``max_batch`` OR when the
  *oldest* queued request has waited ``max_delay_ms`` — whichever
  comes first. Each request resolves through its own future, so one
  poisoned request fails alone, never its batchmates.
* **Padding-bucket shape discipline.** Assembled batches are padded up
  to a fixed ladder (default 1/2/4/8/16/32). The model function is
  warmed once per bucket at startup, so ``jax.jit`` compiles each
  shape exactly once and no request ever pays an XLA compile. The jit
  cache size is surfaced via the owner's stats route — tests and the
  bench assert it stays flat after warmup.
* **Saturation signalling.** Queue depth and tokens-in-flight are
  published as gauges; ``saturation()`` reports the worst ratio
  against ``max_queue`` / ``max_tokens`` and is registered with
  :mod:`tasksrunner.observability.admission` by the serving app, so a
  flood sheds 429+Retry-After at the front door before the queue grows
  unbounded. ``submit`` itself sheds with
  :class:`~tasksrunner.errors.SaturatedError` once the queue is full —
  the last line of defense when admission is off.

The engine is model-agnostic: it schedules opaque items through a
caller-supplied ``run_batch(items, bucket) -> results`` executed in a
worker thread (JAX releases the GIL during device compute, so the
event loop keeps serving while a batch runs).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import time
from dataclasses import dataclass, replace
from typing import Any, Callable, Sequence

from tasksrunner.errors import SaturatedError
from tasksrunner.ids import hex8
from tasksrunner.observability.metrics import (
    MetricsRegistry, metrics as default_metrics,
)
from tasksrunner.observability.spans import active as spans_active, record_span
from tasksrunner.observability.tracing import (
    TraceContext,
    current_trace,
    trace_scope,
)

logger = logging.getLogger(__name__)

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)
DEFAULT_MAX_BATCH = 32
DEFAULT_MAX_DELAY_MS = 5.0
DEFAULT_MAX_QUEUE = 256
DEFAULT_MAX_TOKENS = 8192


def parse_buckets(raw: str) -> tuple[int, ...]:
    """``"1,2,4,8"`` → ``(1, 2, 4, 8)`` — sorted, deduplicated,
    positives only. Falls back to :data:`DEFAULT_BUCKETS` on garbage
    rather than refusing to serve."""
    try:
        buckets = sorted({int(part) for part in raw.split(",") if part.strip()})
    except ValueError:
        logger.warning("ignoring malformed bucket ladder %r; using %s",
                       raw, DEFAULT_BUCKETS)
        return DEFAULT_BUCKETS
    buckets = tuple(b for b in buckets if b > 0)
    return buckets or DEFAULT_BUCKETS


def _env_number(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r; using %s", name, raw, default)
        return default


@dataclass(frozen=True)
class BatcherConfig:
    """Knobs for one :class:`MicroBatcher` (env: ``TASKSRUNNER_ML_*``)."""

    max_batch: int = DEFAULT_MAX_BATCH
    max_delay_ms: float = DEFAULT_MAX_DELAY_MS
    buckets: tuple[int, ...] = DEFAULT_BUCKETS
    max_queue: int = DEFAULT_MAX_QUEUE
    max_tokens: int = DEFAULT_MAX_TOKENS

    def __post_init__(self) -> None:
        # max_batch can never exceed the largest compiled shape — a
        # bigger assembly would force a compile outside the ladder
        object.__setattr__(self, "buckets", tuple(sorted(set(self.buckets))))
        object.__setattr__(
            self, "max_batch", max(1, min(self.max_batch, self.buckets[-1])))

    @classmethod
    def from_env(cls) -> BatcherConfig:
        return cls(
            max_batch=int(_env_number(
                "TASKSRUNNER_ML_MAX_BATCH", DEFAULT_MAX_BATCH)),
            max_delay_ms=_env_number(
                "TASKSRUNNER_ML_MAX_DELAY_MS", DEFAULT_MAX_DELAY_MS),
            buckets=parse_buckets(os.environ.get(
                "TASKSRUNNER_ML_BUCKETS",
                ",".join(map(str, DEFAULT_BUCKETS)))),
            max_queue=int(_env_number(
                "TASKSRUNNER_ML_MAX_QUEUE", DEFAULT_MAX_QUEUE)),
            max_tokens=int(_env_number(
                "TASKSRUNNER_ML_MAX_TOKENS", DEFAULT_MAX_TOKENS)),
        )

    def serial(self) -> BatcherConfig:
        """The batch-of-one variant (``TASKSRUNNER_ML_BATCHING=off``
        and the bench baseline): same queue/shed semantics, no
        assembly, single compiled shape."""
        return replace(self, max_batch=1, buckets=(1,), max_delay_ms=0.0)


class _Pending:
    __slots__ = ("item", "tokens", "enqueued", "future", "ctx")

    def __init__(self, item: Any, tokens: int, enqueued: float,
                 future: asyncio.Future,
                 ctx: TraceContext | None = None) -> None:
        self.item = item
        self.tokens = tokens
        self.enqueued = enqueued
        self.future = future
        #: the submitter's trace context — the batch worker runs on its
        #: own task, so the ambient context is gone by execution time
        self.ctx = ctx


class MicroBatcher:
    """Request queue + micro-batch assembly + padding buckets.

    ``run_batch(items, bucket)`` receives the assembled items (length
    <= bucket) and the bucket to pad to; it runs in a worker thread
    and returns one result per item, in order. A result that is an
    ``Exception`` instance fails that item's future alone (per-request
    error isolation inside a shared batch); ``run_batch`` raising
    fails only that batch's futures — the engine itself survives both.
    """

    def __init__(
        self,
        run_batch: Callable[[list[Any], int], Sequence[Any]],
        *,
        config: BatcherConfig | None = None,
        tokens_of: Callable[[Any], int] | None = None,
        registry: MetricsRegistry | None = None,
    ) -> None:
        self.config = config if config is not None else BatcherConfig()
        self._run_batch = run_batch
        self._tokens_of = tokens_of if tokens_of is not None else (lambda _: 1)
        self._registry = registry if registry is not None else default_metrics
        self._queue: asyncio.Queue[_Pending] = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self._tokens_in_flight = 0
        self._submitted = 0
        self._completed = 0
        self._shed = 0
        self._batch_counts: dict[int, int] = {}

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        while not self._queue.empty():
            pending = self._queue.get_nowait()
            self._account_done([pending])
            if not pending.future.done():
                pending.future.set_exception(
                    RuntimeError("batcher stopped before the request ran"))

    @property
    def running(self) -> bool:
        return self._task is not None and not self._task.done()

    # -- submission ------------------------------------------------------

    async def submit(self, item: Any) -> Any:
        """Enqueue one item; resolves with its result once the batch it
        lands in has executed. Sheds with :class:`SaturatedError`
        (429 + Retry-After) when the queue is full."""
        if not self.running:
            raise RuntimeError("MicroBatcher.submit before start()")
        if self._queue.qsize() >= self.config.max_queue:
            self._shed += 1
            self._registry.inc("ml_shed_total")
            exc = SaturatedError(
                f"inference queue full ({self.config.max_queue} pending)")
            exc.retry_after = 1.0
            raise exc
        pending = _Pending(item, max(1, int(self._tokens_of(item))),
                           time.monotonic(),
                           asyncio.get_running_loop().create_future(),
                           ctx=current_trace() if spans_active() else None)
        self._submitted += 1
        self._tokens_in_flight += pending.tokens
        self._queue.put_nowait(pending)
        self._publish_depth()
        return await pending.future

    # -- saturation ------------------------------------------------------

    def saturation(self) -> float:
        """Worst ratio across the batcher's capacity signals, on the
        admission-controller scale (>= 1.0 → shed at the front door)."""
        score = 0.0
        if self.config.max_tokens > 0:
            score = max(score, self._tokens_in_flight / self.config.max_tokens)
        if self.config.max_queue > 0:
            score = max(score, self._queue.qsize() / self.config.max_queue)
        return score

    def stats(self) -> dict[str, Any]:
        return {
            "submitted": self._submitted,
            "completed": self._completed,
            "shed": self._shed,
            "queue_depth": self._queue.qsize(),
            "tokens_in_flight": self._tokens_in_flight,
            "batches": {str(k): v for k, v in sorted(self._batch_counts.items())},
            "buckets": list(self.config.buckets),
            "max_batch": self.config.max_batch,
            "max_delay_ms": self.config.max_delay_ms,
        }

    # -- the worker ------------------------------------------------------

    def bucket_for(self, size: int) -> int:
        """Smallest ladder entry >= size (sizes above the ladder are
        impossible: max_batch is clamped to the top bucket)."""
        for bucket in self.config.buckets:
            if bucket >= size:
                return bucket
        return self.config.buckets[-1]

    async def _run(self) -> None:
        while True:
            batch = [await self._queue.get()]
            # the budget runs from the OLDEST request's enqueue, so a
            # request that already waited behind a slow batch isn't
            # charged a fresh window on top
            deadline = batch[0].enqueued + self.config.max_delay_ms / 1000.0
            while len(batch) < self.config.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(
                        self._queue.get(), remaining))
                except asyncio.TimeoutError:
                    break
            # whatever accumulated while the previous batch held the
            # device rides along for free (this is the "continuous"
            # part — no idle gap, no extra waiting)
            while len(batch) < self.config.max_batch and not self._queue.empty():
                batch.append(self._queue.get_nowait())
            self._publish_depth()
            await self._execute(batch)

    async def _execute(self, batch: list[_Pending]) -> None:
        bucket = self.bucket_for(len(batch))
        label = str(bucket)
        now = time.monotonic()
        wall = time.time()
        waits = [now - p.enqueued for p in batch]
        # the batch execution is its own trace root — N request traces
        # converge on it, so it can't live inside any one of them; each
        # request's ml-request span carries the batch trace id instead
        batch_ctx = TraceContext.new() if spans_active() else None
        scope = (trace_scope(batch_ctx) if batch_ctx is not None
                 else contextlib.nullcontext())
        with scope:
            self._registry.observe("ml_batch_size", float(len(batch)))
            self._registry.observe_many(
                "ml_queue_wait_seconds", waits, bucket=label,
                traces=[p.ctx.trace_id if p.ctx is not None else None
                        for p in batch])
            started = time.monotonic()
            try:
                results = await asyncio.to_thread(
                    self._run_batch, [p.item for p in batch], bucket)
            except Exception as exc:
                logger.exception("inference batch of %d (bucket %d) failed",
                                 len(batch), bucket)
                self._record_spans(batch, waits, bucket, batch_ctx,
                                   wall, time.monotonic() - started, 500)
                self._account_done(batch)
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(exc)
                return
            service = time.monotonic() - started
            # observed inside the batch scope: a slow batch's exemplar
            # resolves to the ml-batch trace
            self._registry.observe("ml_infer_latency_seconds",
                                   service, bucket=label)
            self._record_spans(batch, waits, bucket, batch_ctx,
                               wall, service, 200)
        self._registry.inc("ml_batches_total", bucket=label)
        self._batch_counts[bucket] = self._batch_counts.get(bucket, 0) + 1
        self._account_done(batch)
        if len(results) != len(batch):
            mismatch = RuntimeError(
                f"run_batch returned {len(results)} results for "
                f"{len(batch)} items")
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(mismatch)
            return
        for p, result in zip(batch, results):
            if p.future.done():
                continue  # the caller gave up waiting; nothing to tell
            if isinstance(result, Exception):
                p.future.set_exception(result)
            else:
                p.future.set_result(result)

    def _record_spans(self, batch: list[_Pending], waits: list[float],
                      bucket: int, batch_ctx: TraceContext | None,
                      wall: float, service: float, status: int) -> None:
        """One ml-batch span (its own trace) plus one ml-request span in
        each submitter's trace, splitting queue wait from device
        occupancy. Explicit trace ids throughout — the worker task has
        no submitter context, and N traces converge on one batch."""
        if batch_ctx is None:
            return
        record_span(
            kind="internal", name="ml-batch", status=status, start=wall,
            duration=service, attrs={"bucket": bucket, "size": len(batch)},
            trace_id=batch_ctx.trace_id, span_id=batch_ctx.span_id)
        for p, wait in zip(batch, waits):
            if p.ctx is None:
                continue
            record_span(
                kind="internal", name="ml-request", status=status,
                start=wall - wait, duration=wait + service,
                attrs={"queue_wait": wait, "service": service,
                       "bucket": bucket, "batch_trace": batch_ctx.trace_id},
                trace_id=p.ctx.trace_id, span_id=hex8(),
                parent_id=p.ctx.span_id)

    def _account_done(self, batch: list[_Pending]) -> None:
        self._completed += len(batch)
        self._tokens_in_flight -= sum(p.tokens for p in batch)
        self._publish_depth()

    def _publish_depth(self) -> None:
        self._registry.set_gauge("ml_queue_depth", float(self._queue.qsize()))
        self._registry.set_gauge("ml_tokens_in_flight",
                                 float(self._tokens_in_flight))
