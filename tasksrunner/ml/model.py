"""Task-priority scorer: a small TPU-first transformer encoder.

EXTENSION ONLY — the reference has no model to port (SURVEY.md §7.1);
this exists to back the harness contract and demonstrate hosting
compute services on the runtime.

TPU-first design notes:

* all matmuls run in bfloat16 with float32 accumulation
  (``preferred_element_type``) so they land on the MXU at full tile
  throughput; params are kept in float32 and cast per-step;
* static shapes everywhere; the whole train step is one ``jax.jit``
  region — no Python control flow inside;
* parallelism is expressed as shardings over a ``Mesh(("dp","tp"))``
  or ``Mesh(("dp","sp","tp"))``: batch on ``dp``, feature/head
  dimensions on ``tp``, sequence on ``sp``; XLA inserts the
  collectives (psum for tp-reduced matmuls, gradient all-reduce over
  dp) — nothing is hand-scheduled;
* attention uses plain ``jnp.einsum`` so XLA can fuse QK^T → softmax
  → V into its flash-style schedule on TPU — except under sequence
  parallelism (an ``sp`` axis of size > 1), where the attention core
  switches to ring attention (tasksrunner/ml/ring.py): K/V blocks
  rotate by ``ppermute`` over the ICI ring and no device ever holds
  the full sequence.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 8192       # hashed token ids
    seq_len: int = 32
    d_model: int = 128
    n_heads: int = 4
    d_ff: int = 512
    n_layers: int = 2
    n_classes: int = 5      # priority buckets

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_heads


def init_params(cfg: ModelConfig, key: jax.Array) -> dict[str, Any]:
    keys = jax.random.split(key, 3 + cfg.n_layers)

    def dense(k, shape):
        fan_in = shape[0]
        return (jax.random.normal(k, shape, jnp.float32)
                / jnp.sqrt(jnp.float32(fan_in)))

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(keys[3 + i], 6)
        layers.append({
            "wq": dense(lk[0], (cfg.d_model, cfg.d_model)),
            "wk": dense(lk[1], (cfg.d_model, cfg.d_model)),
            "wv": dense(lk[2], (cfg.d_model, cfg.d_model)),
            "wo": dense(lk[3], (cfg.d_model, cfg.d_model)),
            "w1": dense(lk[4], (cfg.d_model, cfg.d_ff)),
            "w2": dense(lk[5], (cfg.d_ff, cfg.d_model)),
            "ln1": jnp.ones((cfg.d_model,), jnp.float32),
            "ln2": jnp.ones((cfg.d_model,), jnp.float32),
        })
    return {
        "embed": 0.02 * jax.random.normal(keys[0], (cfg.vocab, cfg.d_model), jnp.float32),
        "pos": 0.02 * jax.random.normal(keys[1], (cfg.seq_len, cfg.d_model), jnp.float32),
        "head": dense(keys[2], (cfg.d_model, cfg.n_classes)),
        "layers": layers,
    }


def act_dtype() -> jnp.dtype:
    """Residual-stream / activation dtype, resolved at trace time.

    bf16 by default: params stay f32 masters and every contraction
    still accumulates f32 on the MXU, but activations written to HBM
    (residual stream, FF intermediate, attention q/k/v/ctx and their
    saved-for-backward residuals) are half the bytes — on a v5e the
    step is HBM-bound in several phases, so this is the single largest
    MFU lever (BASELINE.md roofline). ``TASKSRUNNER_ACT_F32=1``
    restores full-f32 activations for A/B runs."""
    from tasksrunner.envflag import env_flag
    return (jnp.float32 if env_flag("TASKSRUNNER_ACT_F32", default=False)
            else jnp.bfloat16)


def _matmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """bf16 × bf16 → f32 accumulate on the MXU, result stored in the
    activation dtype (the f32 accumulation happens in-register; only
    the downcast result pays HBM bytes)."""
    out = jnp.matmul(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                     preferred_element_type=jnp.float32)
    return out.astype(act_dtype())


def _layernorm(x: jax.Array, scale: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)  # moments in f32 on the VPU, always
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + 1e-6) * scale
    return out.astype(act_dtype())


def _use_ring(mesh: Mesh | None) -> bool:
    return (mesh is not None and "sp" in mesh.axis_names
            and mesh.shape["sp"] > 1)


def use_flash() -> bool:
    """Single-chip attention core toggle: the Pallas flash kernel
    (tasksrunner/ml/flash.py, default) vs the plain einsum pair.
    Resolved at trace time — set TASKSRUNNER_FLASH=0 before jitting
    to compare (bench.py reports both)."""
    from tasksrunner.envflag import env_flag

    return env_flag("TASKSRUNNER_FLASH")


def _attention(x: jax.Array, layer: dict, cfg: ModelConfig,
               mesh: Mesh | None = None) -> jax.Array:
    b, s, _ = x.shape
    h, dh = cfg.n_heads, cfg.d_head

    def heads(w):
        return _matmul(x, w).reshape(b, s, h, dh)

    q, k, v = heads(layer["wq"]), heads(layer["wk"]), heads(layer["wv"])
    if _use_ring(mesh):
        from tasksrunner.ml.ring import ring_attention
        ctx = ring_attention(q, k, v, mesh=mesh)          # [b, s, h, dh]
    elif use_flash():
        from tasksrunner.ml.flash import flash_attention
        ctx = flash_attention(q, k, v)                    # Pallas kernel
    else:
        logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.bfloat16),
                            k.astype(jnp.bfloat16),
                            preferred_element_type=jnp.float32)
        probs = jax.nn.softmax(logits / jnp.sqrt(jnp.float32(dh)), axis=-1)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(jnp.bfloat16),
                         v.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
    return _matmul(ctx.reshape(b, s, h * dh), layer["wo"])


def forward(params: dict, tokens: jax.Array, *, cfg: ModelConfig,
            mesh: Mesh | None = None) -> jax.Array:
    """tokens [batch, seq] int32 → class logits [batch, n_classes].

    ``mesh`` only changes which attention core runs (ring under an
    ``sp`` axis); everything else is plain GSPMD — the same code jits
    single-chip and multi-chip."""
    x = (params["embed"][tokens] + params["pos"][None, :, :]).astype(act_dtype())
    for layer in params["layers"]:
        x = x + _attention(_layernorm(x, layer["ln1"]), layer, cfg, mesh)
        y = _layernorm(x, layer["ln2"])
        y = _matmul(jax.nn.gelu(_matmul(y, layer["w1"])), layer["w2"])
        x = x + y
    pooled = jnp.mean(x.astype(jnp.float32), axis=1)  # f32 reduction
    # final logits stay full f32 (no act_dtype downcast): bf16 here
    # saves no HBM — this IS the output — and would quantize the
    # log_softmax inputs
    return jnp.matmul(pooled.astype(jnp.bfloat16),
                      params["head"].astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)


def loss_fn(params: dict, tokens: jax.Array, labels: jax.Array, *,
            cfg: ModelConfig, mesh: Mesh | None = None) -> jax.Array:
    logits = forward(params, tokens, cfg=cfg, mesh=mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


# -- sharding ------------------------------------------------------------

def param_specs(cfg: ModelConfig) -> dict[str, Any]:
    """PartitionSpecs over Mesh(("dp","tp")): feature dims on tp,
    replicated over dp (gradients psum over dp automatically)."""
    layer = {
        "wq": P(None, "tp"), "wk": P(None, "tp"), "wv": P(None, "tp"),
        "wo": P("tp", None),
        "w1": P(None, "tp"), "w2": P("tp", None),
        "ln1": P(None), "ln2": P(None),
    }
    return {
        "embed": P(None, "tp"),
        "pos": P(None, None),
        "head": P(None, None),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


def shard_params(params: dict, mesh: Mesh, cfg: ModelConfig) -> dict:
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, specs,
        is_leaf=lambda x: isinstance(x, jax.Array) or not isinstance(x, (dict, list)),
    )


def make_train_step(cfg: ModelConfig, mesh: Mesh | None = None, *,
                    learning_rate: float = 1e-3):
    """One SGD step as a single jit region. With a mesh, inputs are
    batch-sharded over dp (and sequence-sharded over sp when the mesh
    has that axis), params tp-sharded; XLA inserts the collectives —
    except the ring attention core, which hand-places its ppermutes."""

    def step(params, tokens, labels):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, labels, cfg=cfg, mesh=mesh))(params)
        new_params = jax.tree.map(
            lambda p, g: (p - learning_rate * g).astype(p.dtype), params, grads)
        return new_params, loss

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,))

    specs = param_specs(cfg)
    param_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))
    seq_axis = "sp" if "sp" in mesh.axis_names else None
    data_sh = NamedSharding(mesh, P("dp", seq_axis))
    label_sh = NamedSharding(mesh, P("dp"))
    return jax.jit(
        step,
        in_shardings=(param_sh, data_sh, label_sh),
        out_shardings=(param_sh, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def hash_token_ids(text: str, cfg: ModelConfig) -> list[int]:
    """Deterministic hashed tokenizer for one text (no external
    vocab): words → buckets in [1, vocab); 0 is padding. Pure Python —
    the serving encode path runs it per request on the event loop
    without touching a device."""
    import zlib
    ids = [1 + (zlib.crc32(w.lower().encode()) % (cfg.vocab - 1))
           for w in text.split()][: cfg.seq_len]
    return ids + [0] * (cfg.seq_len - len(ids))


def hash_tokens(texts: list[str], cfg: ModelConfig) -> jnp.ndarray:
    """Batched :func:`hash_token_ids`, committed as a device array."""
    return jnp.asarray([hash_token_ids(t, cfg) for t in texts], jnp.int32)


# -- serving placement ---------------------------------------------------

def serving_mesh(devices: list | None = None) -> Mesh | None:
    """A 1-D data mesh over every visible device for the serving path,
    or None single-chip. Inference has no tp-worthy weights at this
    model size: the win is batch-dimension data parallelism, so the
    mesh is just ``("dp",)``."""
    import numpy as np
    devices = jax.devices() if devices is None else devices
    if len(devices) <= 1:
        return None
    return Mesh(np.asarray(devices), ("dp",))


def replicate_params(params: dict, mesh: Mesh) -> dict:
    """Device-put every leaf once, fully replicated over the mesh —
    after this no serving call ever re-feeds weights."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), params)
