"""Ring attention: sequence-parallel attention over a mesh axis.

EXTENSION ONLY (see tasksrunner/ml/model.py) — the reference has no
sequence dimension anywhere (SURVEY.md §5.7); this exists so the demo
workload's multi-chip path exercises a real long-context strategy.

The TPU-native design (after the published ring-attention recipe):
each device holds one sequence block of Q, K, V. K/V blocks rotate
around the ring with ``lax.ppermute`` (neighbor exchange rides the ICI
torus — never a global all-gather), while each device accumulates its
Q-block's attention over every visiting K/V block using the
numerically-stable flash-style running (max, numerator, denominator)
triple. Peak memory per device is O(block²) instead of O(seq²), and
compute overlaps the ppermute transfers under XLA's async collectives.

Composition with the other axes: batch stays on ``dp``, heads stay on
``tp`` — the ring runs over ``sp`` only, so head-parallel and
sequence-parallel compose orthogonally (each device ring-exchanges
only its local heads' K/V slices).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _block_update(q, k_blk, v_blk, m, num, den, *, scale):
    """Fold one visiting K/V block into the running softmax state.

    q:            [b, sq, h, dh]   this device's queries (fixed)
    k_blk/v_blk:  [b, sk, h, dh]   the visiting block
    m/num/den:    running max [b,h,sq], numerator [b,h,sq,dh],
                  denominator [b,h,sq]
    """
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.bfloat16), k_blk.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32) * scale
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
    correction = jnp.exp(m - m_new)
    probs = jnp.exp(logits - m_new[..., None])
    num = num * correction[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", probs.astype(jnp.bfloat16),
        v_blk.astype(jnp.bfloat16), preferred_element_type=jnp.float32)
    den = den * correction + jnp.sum(probs, axis=-1)
    return m_new, num, den


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def _block_update_fast(q, k_blk, v_blk, m, num, den, scale):
    """The block update with a fused Pallas forward (one kernel:
    logits + running max + correction + both accumulators, all in
    VMEM) and the einsum implementation's VJP for the backward —
    numerically the same computation (both contract in bf16), so the
    recompute-for-backward trade is sound and the ring stays fully
    differentiable."""
    from tasksrunner.ml.flash import ring_block_update

    return ring_block_update(q, k_blk, v_blk, m, num, den, scale=scale)


def _block_update_fwd(q, k_blk, v_blk, m, num, den, scale):
    out = _block_update_fast(q, k_blk, v_blk, m, num, den, scale)
    return out, (q, k_blk, v_blk, m, num, den)


def _block_update_bwd(scale, res, cotangents):
    _, vjp = jax.vjp(
        lambda *args: _block_update(*args, scale=scale), *res)
    return vjp(cotangents)


_block_update_fast.defvjp(_block_update_fwd, _block_update_bwd)


def _ring_attention_local(q, k, v, *, axis_name: str, scale: float,
                          use_pallas: bool):
    """Per-device body (runs under shard_map): q/k/v are the local
    [b, s_block, h, dh] shards; returns the local context block."""
    n = jax.lax.axis_size(axis_name)
    b, sq, h, dh = q.shape
    init = (
        k, v,
        jnp.full((b, h, sq), -jnp.inf, jnp.float32),
        jnp.zeros((b, h, sq, dh), jnp.float32),
        jnp.zeros((b, h, sq), jnp.float32),
    )
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        k_blk, v_blk, m, num, den = carry
        if use_pallas:
            m, num, den = _block_update_fast(
                q, k_blk, v_blk, m, num, den, scale)
        else:
            m, num, den = _block_update(
                q, k_blk, v_blk, m, num, den, scale=scale)
        # rotate AFTER consuming: after n steps every device has seen
        # every block exactly once and K/V are home again
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, num, den), None

    (_, _, _, num, den), _ = jax.lax.scan(step, init, None, length=n)
    ctx = num / den[..., None]                      # [b, h, sq, dh]
    return jnp.transpose(ctx, (0, 2, 1, 3))         # [b, sq, h, dh]


def ring_attention(q, k, v, *, mesh: Mesh, axis_name: str = "sp",
                   scale: float | None = None):
    """Bidirectional (encoder) ring attention.

    q/k/v: [batch, seq, heads, d_head] — global arrays; batch may be
    sharded on "dp", heads on "tp"; seq is sharded on ``axis_name``
    and never materialised whole on any device.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis_name!r} axis: {mesh.axis_names}")
    # only name axes the mesh actually has; absent ones replicate
    batch_axis = "dp" if "dp" in mesh.axis_names else None
    head_axis = "tp" if "tp" in mesh.axis_names else None
    spec = P(batch_axis, axis_name, head_axis, None)
    from tasksrunner.ml.model import use_flash
    body = functools.partial(_ring_attention_local,
                             axis_name=axis_name, scale=scale,
                             use_pallas=use_flash())
    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
