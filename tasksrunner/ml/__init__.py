"""OPTIONAL ML extension — NOT a ported capability.

The reference (chsakell/aca-dotnet-workshop) contains no numerical or
accelerator workload whatsoever: no tensors, kernels, training loops,
or collectives (SURVEY.md §0, §5.7, §7.1; BASELINE.json "no CUDA, no
NCCL, no training loop ... Target: N/A"). Everything under
``tasksrunner.ml`` is therefore an *extension*: a demo "workload
service" placed behind the same building-block APIs every other
service uses, proving that compute-bearing services slot into the
runtime like any other app.

The workload is a small JAX transformer that scores task priority from
the task's text fields, written TPU-first (bfloat16 matmuls for the
MXU, static shapes, jit-compiled, dp×sp×tp sharding over a
``jax.sharding.Mesh`` with ring attention on the sp axis —
tasksrunner/ml/ring.py). ``tasksrunner.ml.service.make_app`` hosts it
as a real runtime app: ``POST /score`` over service invocation, async
scoring of ``tasksavedtopic`` events into a state store. It exists to
exercise the framework's harness contract (__graft_entry__.py,
bench.py) and as the pattern for users who want to host models on
tasksrunner.
"""

from tasksrunner.ml.model import (
    ModelConfig,
    init_params,
    forward,
    loss_fn,
    make_train_step,
    shard_params,
)

__all__ = [
    "ModelConfig",
    "init_params",
    "forward",
    "loss_fn",
    "make_train_step",
    "shard_params",
]
