"""Platform pinning for the virtual-CPU-mesh workflows.

On this machine the axon TPU plugin prepends itself to
``jax.config.jax_platforms``, so even with ``JAX_PLATFORMS=cpu`` in the
environment the single real chip wins. Tests and the driver's
multichip dry-run both want the virtual N-device CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``) instead; this
helper pins the cpu platform, tolerating an already-initialised
backend (in which case whatever platform won stays).
"""

from __future__ import annotations


def pin_cpu_platform() -> bool:
    """Best-effort pin of jax to the cpu platform. Returns True if the
    pin was applied (or already in effect)."""
    try:
        import jax
    except ImportError:  # pragma: no cover - jax is in the image
        return False
    try:
        jax.config.update("jax_platforms", "cpu")
        return True
    except RuntimeError:
        # backends already initialised; too late to change
        return jax.default_backend() == "cpu"
