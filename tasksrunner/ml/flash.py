"""Pallas flash-attention kernels for the ML extension.

EXTENSION ONLY (see tasksrunner/ml/model.py) — the reference has no
numerical workload (SURVEY.md §0); these kernels back the harness
contract's compute path.

Design (per /opt/skills/guides/pallas_guide.md):

* One grid program per (batch, head-block): at the scorer's shapes
  (seq ≤ 1k, d_head 64) a head's whole attention fits VMEM easily
  (q/k/v/o ≈ 0.5 MB + one [S,S] f32 score tile ≈ 1 MB at s=512), so
  each head is a single fused QKᵀ → softmax → PV with no K-streaming
  loop — and because one-head programs are overhead-dominated (the
  round-4 attribution), `_head_block` folds as many heads per program
  as the ~16 MB/core VMEM budget allows (4 at the bench shapes; the
  budget math lives in its docstring). The flash recipe's K-streaming
  only pays once S² no longer fits, and the blockwise ring layer
  (ring.py) already bounds S per device before that point.
* Internally arrays are laid out [batch, heads, seq, d_head] so each
  block's minor-most two dims are the full (seq, d_head) tile —
  Pallas TPU requires the last two block dims be tile-aligned or
  whole; the (b, h) grid dims lead. The public interface stays the
  model's [batch, seq, heads, d_head]; XLA fuses the transposes into
  the surrounding reshapes.
* Matmuls run bf16 × bf16 → f32 (`preferred_element_type`) on the
  MXU; softmax stays f32 on the VPU; nothing round-trips to HBM
  between the three stages (the win over dispatching three XLA ops).
* Training needs gradients: `flash_attention` carries a custom VJP
  whose backward pass is a second Pallas kernel implementing the
  standard flash backward (recompute P from the saved row-logsumexp,
  then dV = PᵀdO, dS = P∘(dO Vᵀ − Δ), dQ = dS·K, dK = dSᵀ·Q) — same
  VMEM-residency argument, one grid program per (batch, head-block)
  with a tighter budget (more streams and live score tiles).
* Off-TPU the kernels run in interpreter mode, so the correctness
  suite (tests/test_ml_extension.py) exercises the exact kernel code
  on CPU against the einsum reference.
* Backward default: Δ = Σ(dO∘O) is PREcomputed outside the kernel
  (``_bwd_kernel_delta``, the flash-v2 arrangement) — promoted from
  the staged sweep (``scripts/sweep_flash_bwd.py --cpu``, interpret
  mode, the chip tunnel being down): delta-precompute ran the small-
  config train step at 50.6 ms vs 60.8 ms for the in-kernel-Δ
  baseline (−17%), and the win is structural (one fewer double-
  buffered [h_blk, S, D] input stream) rather than shape-dependent.
  The same sweep ranked ``bwd_hblk=8`` fastest outright, but that is
  an interpret-mode artifact — fewer program invocations — that
  contradicts the on-chip round-4 measurement (8 heads/program
  regresses under VMEM pressure; see ``_head_block``), so the block
  heuristic stays. ``TASKSRUNNER_FLASH_BWD_DELTA=fused`` restores the
  in-kernel Δ for A/B runs; both variants stay numerically pinned by
  ``test_flash_backward_variants_match_einsum``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dot(a, b, *, trans_b: bool = False):
    """bf16×bf16→f32 MXU contraction of 2-D operands."""
    dims = (((1,), (1 if trans_b else 0,)), ((), ()))
    return jax.lax.dot_general(
        a.astype(jnp.bfloat16), b.astype(jnp.bfloat16), dims,
        preferred_element_type=jnp.float32)


def _env_hblk(var: str, h: int) -> int | None:
    """Trace-time head-block override for on-chip sweeps
    (scripts/sweep_flash_bwd.py): TASKSRUNNER_FLASH_HBLK_FWD /
    _BWD / _RING = an integer dividing n_heads. Unset → the
    VMEM-budget heuristic below decides."""
    import os
    raw = os.environ.get(var)
    if not raw:
        return None
    blk = int(raw)
    if blk < 1 or h % blk:
        raise ValueError(f"{var}={raw} must divide n_heads={h}")
    return blk


def _head_block(h: int, s: int, d: int, *, n_qkv: int = 4,
                n_tiles: int = 2) -> int:
    """Heads folded into one grid program. One-head programs are tiny
    (67 MFLOP at the bench shapes) and the per-program pipeline
    overhead dominated the kernel — measured on the v5e, 4 heads per
    program runs the forward 1.7× faster than 1 (2.17 → 1.27 ms at
    b=32 h=16 s=512 d=64), while 8 regresses (VMEM pressure defeats
    the in/out copy pipelining). The loop is a static unroll; results
    are bit-identical across block sizes.

    The block size is VMEM-budgeted, not fixed: per program, Pallas
    double-buffers ``n_qkv``-ish [h_blk, s, d] bf16 streams and the
    unrolled body keeps ~``n_tiles`` [s, s] f32 score tiles live per
    head iteration — at larger seq the tiles quadruple, so a blind
    h_blk=4 would blow the ~16 MB/core budget exactly the way the
    measured 8-head variant did at s=512."""
    budget = 12 * 1024 * 1024  # leave headroom under ~16 MB/core
    for blk in (4, 2):
        if h % blk:
            continue
        streams = 2 * n_qkv * blk * s * d * 2          # double-buffered bf16
        tiles = n_tiles * s * s * 4                    # f32, per iteration
        if streams + tiles <= budget:
            return blk
    return 1


def _specs(b, s, h, d, h_blk: int = 1):
    """BlockSpecs over the internal [b, h, s, d] / [b, h, 1, s]
    layouts: one (batch, head-block) per grid program, minor dims
    whole."""
    qkv = pl.BlockSpec((1, h_blk, s, d), lambda i, j: (i, j, 0, 0),
                       memory_space=pltpu.VMEM)
    lse = pl.BlockSpec((1, h_blk, 1, s), lambda i, j: (i, j, 0, 0),
                       memory_space=pltpu.VMEM)
    return qkv, lse


# -- forward --------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, *, scale, h_blk):
    for i in range(h_blk):                     # static unroll
        q = q_ref[0, i]                        # [S, D]
        k = k_ref[0, i]
        v = v_ref[0, i]
        s = _dot(q, k, trans_b=True) * scale   # [S, S] f32
        m = jnp.max(s, axis=-1)                # [S]
        p = jnp.exp(s - m[:, None])            # f32, unnormalised
        den = jnp.sum(p, axis=-1)              # [S]
        ctx = _dot(p, v) / den[:, None]        # [S, D] f32 in-register
        o_ref[0, i] = ctx.astype(o_ref.dtype)  # HBM bytes in IO dtype
        l_ref[0, i, 0, :] = m + jnp.log(den)   # row logsumexp, for bwd


def _flash_fwd(q, k, v, scale):
    """q/k/v in internal [b, h, s, d] layout; the context comes back in
    the inputs' dtype (bf16 activations halve the HBM bytes — softmax
    statistics and accumulation stay f32 inside the kernel)."""
    b, h, s, d = q.shape
    h_blk = (_env_hblk("TASKSRUNNER_FLASH_HBLK_FWD", h)
             or _head_block(h, s, d, n_qkv=5, n_tiles=2))
    qkv_spec, lse_spec = _specs(b, s, h, d, h_blk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, h_blk=h_blk),
        grid=(b, h // h_blk),
        in_specs=[qkv_spec, qkv_spec, qkv_spec],
        out_specs=[qkv_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# -- backward -------------------------------------------------------------

def _bwd_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, l_ref,
                dq_ref, dk_ref, dv_ref, *, scale, h_blk):
    for i in range(h_blk):                      # static unroll
        q = q_ref[0, i]
        k = k_ref[0, i]
        v = v_ref[0, i]
        o = o_ref[0, i]
        do = do_ref[0, i]
        lse = l_ref[0, i, 0, :]                 # [S]
        s = _dot(q, k, trans_b=True) * scale    # [S, S]
        p = jnp.exp(s - lse[:, None])           # normalised probs, f32
        dv = _dot(p.T, do)                      # [S, D]
        dp = _dot(do, v, trans_b=True)          # [S, S]
        delta = jnp.sum(do.astype(jnp.float32)  # f32 on the VPU even
                        * o.astype(jnp.float32), axis=-1)  # with bf16 IO
        ds = p * (dp - delta[:, None]) * scale  # [S, S]
        dq_ref[0, i] = _dot(ds, k).astype(dq_ref.dtype)
        dk_ref[0, i] = _dot(ds.T, q).astype(dk_ref.dtype)
        dv_ref[0, i] = dv.astype(dv_ref.dtype)


def _bwd_kernel_delta(q_ref, k_ref, v_ref, do_ref, l_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, scale, h_blk):
    """Backward variant with Δ = Σ(dO ∘ O) PREcomputed outside the
    kernel (the standard flash-v2 arrangement): the ``o`` stream
    disappears from the program (one fewer [h_blk, S, D] double-
    buffered input), trading a cheap XLA-fused elementwise pass for
    VMEM headroom. Numerically identical to _bwd_kernel; which one
    wins on the clock is a sweep question (scripts/sweep_flash_bwd.py)."""
    for i in range(h_blk):                      # static unroll
        q = q_ref[0, i]
        k = k_ref[0, i]
        v = v_ref[0, i]
        do = do_ref[0, i]
        lse = l_ref[0, i, 0, :]                 # [S]
        delta = delta_ref[0, i, 0, :]           # [S], f32
        s = _dot(q, k, trans_b=True) * scale    # [S, S]
        p = jnp.exp(s - lse[:, None])           # normalised probs, f32
        dv = _dot(p.T, do)                      # [S, D]
        dp = _dot(do, v, trans_b=True)          # [S, S]
        ds = p * (dp - delta[:, None]) * scale  # [S, S]
        dq_ref[0, i] = _dot(ds, k).astype(dq_ref.dtype)
        dk_ref[0, i] = _dot(ds.T, q).astype(dk_ref.dtype)
        dv_ref[0, i] = dv.astype(dv_ref.dtype)


def _bwd_delta_precompute() -> bool:
    """Δ placement for the backward, resolved at trace time. Default
    is PREcompute (_bwd_kernel_delta) — promoted by the sweep result
    in the module docstring; TASKSRUNNER_FLASH_BWD_DELTA=fused
    restores the in-kernel Δ of the round-4 configuration."""
    import os
    return os.environ.get("TASKSRUNNER_FLASH_BWD_DELTA", "precompute") != "fused"


def _flash_bwd_call(q, k, v, out, lse, dout, scale):
    b, h, s, d = q.shape
    dout = dout.astype(q.dtype)
    if _bwd_delta_precompute():
        # Δ in one XLA-fused elementwise+reduce pass; the kernel then
        # streams 4 big inputs instead of 5
        delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)[:, :, None, :]            # [b, h, 1, s]
        h_blk = (_env_hblk("TASKSRUNNER_FLASH_HBLK_BWD", h)
                 or _head_block(h, s, d, n_qkv=7, n_tiles=3))
        qkv_spec, lse_spec = _specs(b, s, h, d, h_blk)
        return pl.pallas_call(
            functools.partial(_bwd_kernel_delta, scale=scale, h_blk=h_blk),
            grid=(b, h // h_blk),
            in_specs=[qkv_spec] * 4 + [lse_spec, lse_spec],
            out_specs=[qkv_spec] * 3,
            out_shape=[jax.ShapeDtypeStruct((b, h, s, d), q.dtype)] * 3,
            interpret=_interpret(),
        )(q, k, v, dout, lse, delta)
    # bwd streams more (q/k/v/o/do in, dq/dk/dv out) and keeps more
    # score-sized temporaries live (s, p, dp, ds)
    h_blk = (_env_hblk("TASKSRUNNER_FLASH_HBLK_BWD", h)
             or _head_block(h, s, d, n_qkv=8, n_tiles=3))
    qkv_spec, lse_spec = _specs(b, s, h, d, h_blk)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, h_blk=h_blk),
        grid=(b, h // h_blk),
        in_specs=[qkv_spec] * 5 + [lse_spec],
        out_specs=[qkv_spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((b, h, s, d), q.dtype)] * 3,
        interpret=_interpret(),
    )(q, k, v, out, dout, lse)


# -- public op ------------------------------------------------------------

def _to_internal(x):
    return jnp.transpose(x, (0, 2, 1, 3))      # [b,s,h,d] -> [b,h,s,d]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, scale=None):
    """Fused attention: q/k/v [batch, seq, heads, d_head] → context
    [batch, seq, heads, d_head] **in the inputs' dtype** (bf16
    activations halve HBM bytes; softmax statistics and MXU
    accumulation stay f32 inside the kernel). Differentiable; the VJP
    is the flash backward kernel, gradients in the inputs' dtype."""
    out, _ = _flash_fwd(_to_internal(q), _to_internal(k), _to_internal(v),
                        _resolve_scale(q, scale))
    return _to_internal(out)


def _resolve_scale(q, scale):
    return float(scale) if scale is not None else 1.0 / (q.shape[-1] ** 0.5)


def _fwd_rule(q, k, v, scale):
    qi, ki, vi = _to_internal(q), _to_internal(k), _to_internal(v)
    out, lse = _flash_fwd(qi, ki, vi, _resolve_scale(q, scale))
    return _to_internal(out), (qi, ki, vi, out, lse)


def _bwd_rule(scale, res, dout):
    qi, ki, vi, out, lse = res
    dq, dk, dv = _flash_bwd_call(qi, ki, vi, out, lse, _to_internal(dout),
                                 _resolve_scale(qi, scale))
    return _to_internal(dq), _to_internal(dk), _to_internal(dv)


flash_attention.defvjp(_fwd_rule, _bwd_rule)


# -- ring block update ----------------------------------------------------

def _ring_block_kernel(q_ref, k_ref, v_ref, m_ref, num_ref, den_ref,
                       m_out, num_out, den_out, *, scale, h_blk):
    """One visiting K/V block folded into the running flash state —
    the ring step's inner update (ring.py `_block_update`) as one
    fused kernel: logits, running max, correction, and both
    accumulators without leaving VMEM. Head-blocked like the main
    kernels: the per-device ring blocks are the SMALLEST programs in
    the module (Sq = seq/sp), so per-program overhead bites hardest
    here."""
    for i in range(h_blk):                      # static unroll
        q = q_ref[0, i]                         # [Sq, D]
        k = k_ref[0, i]                         # [Sk, D]
        v = v_ref[0, i]
        m = m_ref[0, i, 0, :]                   # [Sq]
        num = num_ref[0, i]                     # [Sq, D]
        den = den_ref[0, i, 0, :]
        s = _dot(q, k, trans_b=True) * scale    # [Sq, Sk]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        m_out[0, i, 0, :] = m_new
        num_out[0, i] = num * corr[:, None] + _dot(p, v)
        den_out[0, i, 0, :] = den * corr + jnp.sum(p, axis=-1)


def ring_block_update(q, k_blk, v_blk, m, num, den, *, scale):
    """Pallas twin of ring.py's `_block_update`.

    Layouts match the ring's per-device state: q/k/v [b, sq|sk, h, dh],
    m/den [b, h, sq], num [b, h, sq, dh]. Forward-only — the ring's
    VJP differentiates the einsum block update instead.
    """
    b, sq, h, d = q.shape
    sk = k_blk.shape[1]
    # budget with the larger of the two seq dims: the score tile is
    # [Sq, Sk] and the streams carry both block sizes
    h_blk = (_env_hblk("TASKSRUNNER_FLASH_HBLK_RING", h)
             or _head_block(h, max(sq, sk), d, n_qkv=7, n_tiles=2))
    qkv_spec, vec_spec = _specs(b, sq, h, d, h_blk)
    kv_spec = pl.BlockSpec((1, h_blk, sk, d), lambda i, j: (i, j, 0, 0),
                           memory_space=pltpu.VMEM)
    m_new, num_new, den_new = pl.pallas_call(
        functools.partial(_ring_block_kernel, scale=scale, h_blk=h_blk),
        grid=(b, h // h_blk),
        in_specs=[qkv_spec, kv_spec, kv_spec, vec_spec, qkv_spec, vec_spec],
        out_specs=[vec_spec, qkv_spec, vec_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, 1, sq), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, 1, sq), jnp.float32),
        ],
        interpret=_interpret(),
    )(_to_internal(q), _to_internal(k_blk), _to_internal(v_blk),
      m[:, :, None, :], num, den[:, :, None, :])
    return m_new[:, :, 0, :], num_new, den_new[:, :, 0, :]
