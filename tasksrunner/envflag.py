"""One parser for boolean environment knobs.

Every on/off env toggle (TASKSRUNNER_ACCESS_LOG, TASKSRUNNER_FLASH,
TASKSRUNNER_PERF_TESTS, ...) must accept the same spellings — a
per-call-site tuple would drift the moment one copy learns a new
spelling.
"""

from __future__ import annotations

import os

_FALSE = frozenset({"0", "false", "off", "no"})


def env_flag(name: str, default: bool = True) -> bool:
    """True unless the variable is set to an explicit disable value
    (case-insensitive: 0 / false / off / no). Unset → ``default``."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in _FALSE
