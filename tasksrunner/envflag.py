"""One parser — and one inventory — for environment knobs.

Every on/off env toggle (TASKSRUNNER_ACCESS_LOG, TASKSRUNNER_FLASH,
TASKSRUNNER_PERF_TESTS, ...) must accept the same spellings — a
per-call-site tuple would drift the moment one copy learns a new
spelling.

:data:`FLAGS` is the central inventory of every ``TASKSRUNNER_*``
variable the runtime reads: name, kind, default, one-line doc. The
``env-flag-discipline`` tasklint rule fails the build on any raw
``os.environ`` read of a declared boolean (must use :func:`env_flag`)
and on any undeclared ``TASKSRUNNER_*`` read; ``env_flag`` itself
refuses undeclared names at runtime, and ``tests/test_flag_inventory``
asserts the inventory and the docs agree.
"""

from __future__ import annotations

import dataclasses
import os

_FALSE = frozenset({"0", "false", "off", "no"})


@dataclasses.dataclass(frozen=True)
class Flag:
    """One declared environment variable."""

    name: str
    kind: str      # "bool" | "int" | "float" | "string" | "path" | "enum" | "json"
    default: str   # human-readable default ("on"/"off" for bools)
    doc: str
    #: the value is a credential: the tasklint secret-taint rule treats
    #: env reads of it as taint sources (never logged unredacted)
    secret: bool = False


def _f(name: str, kind: str, default: str, doc: str,
       *, secret: bool = False) -> tuple[str, Flag]:
    return name, Flag(name, kind, default, doc, secret)


#: every TASKSRUNNER_* variable any part of the repo reads. Keep the
#: table alphabetical; the docs table in module 31 must list every name
#: here (asserted by tests/test_flag_inventory.py).
FLAGS: dict[str, Flag] = dict([
    _f("TASKSRUNNER_ACCESS_LOG", "bool", "on",
       "per-request access-log lines from app servers and sidecars"),
    _f("TASKSRUNNER_ACTORS", "bool", "off",
       "virtual-actor runtime (placement, turns, reminders, failover)"),
    _f("TASKSRUNNER_ACTOR_LEASE_SECONDS", "float", "30",
       "placement lease duration; expiry lets survivors take ownership"),
    _f("TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS", "float", "2",
       "reminder/lease sweep interval on every actor-hosting replica"),
    _f("TASKSRUNNER_ACTOR_TURN_TIMEOUT_SECONDS", "float", "30",
       "per-turn actor handler deadline before the turn fails"),
    _f("TASKSRUNNER_ACT_F32", "bool", "off",
       "keep ML activations in float32 instead of the platform default"),
    _f("TASKSRUNNER_ADMISSION", "bool", "off",
       "per-replica admission control (shed with 429 when saturated)"),
    _f("TASKSRUNNER_ADMISSION_MAX_INFLIGHT", "int", "64",
       "in-flight app requests at which the saturation score reaches 1.0"),
    _f("TASKSRUNNER_ADMISSION_MAX_LAG_SECONDS", "float", "0.25",
       "event-loop lag at which the saturation score reaches 1.0"),
    _f("TASKSRUNNER_ADMISSION_MAX_QUEUE_DEPTH", "int", "512",
       "state/broker write-queue depth at which the score reaches 1.0"),
    _f("TASKSRUNNER_API_TOKEN", "string", "unset",
       "bearer token the sidecar and admin APIs require when set",
       secret=True),
    _f("TASKSRUNNER_APP_ID", "string", "unset",
       "app-id grants are evaluated against (injected by the orchestrator)"),
    _f("TASKSRUNNER_BENCH_TPU_FORCE", "bool", "off",
       "force the TPU benchmark sections to run even off-TPU"),
    _f("TASKSRUNNER_CHAOS", "bool", "off",
       "master gate for declarative fault injection (kind: Chaos)"),
    _f("TASKSRUNNER_FLASH", "bool", "on",
       "flash-attention path in the ML extension"),
    _f("TASKSRUNNER_FLASH_BWD_DELTA", "enum", "precompute",
       "flash backward delta strategy (precompute | fused)"),
    _f("TASKSRUNNER_FLASH_HBLK_BWD", "int", "auto",
       "head-block size override for the flash backward kernel"),
    _f("TASKSRUNNER_FLASH_HBLK_FWD", "int", "auto",
       "head-block size override for the flash forward kernel"),
    _f("TASKSRUNNER_FLASH_HBLK_RING", "int", "auto",
       "head-block size override for the ring-attention kernel"),
    _f("TASKSRUNNER_FLIGHTREC", "bool", "on",
       "black-box flight recorder (ring of recent request timelines, "
       "dumped on shed entry, slow exemplars, and unclean shutdown)"),
    _f("TASKSRUNNER_FLIGHTREC_DIR", "path", ".tasksrunner/flightrec",
       "directory flight-recorder dumps are written to"),
    _f("TASKSRUNNER_FLIGHTREC_RING", "int", "256",
       "request timelines the flight-recorder ring retains per process"),
    _f("TASKSRUNNER_GRANTS", "json", "unset",
       "JSON grants document applied to the app (orchestrator-injected)"),
    _f("TASKSRUNNER_HISTOGRAMS", "bool", "on",
       "latency-histogram recording kill switch"),
    _f("TASKSRUNNER_HTTP_PORT", "int", "3500",
       "sidecar port AppClient.from_env connects to"),
    _f("TASKSRUNNER_MESH", "bool", "on",
       "framed sidecar-to-sidecar transport lane"),
    _f("TASKSRUNNER_MESH_CA", "path", "unset",
       "CA bundle path; with CERT and KEY enables mesh mTLS"),
    _f("TASKSRUNNER_MESH_CERT", "path", "unset",
       "mesh mTLS certificate path"),
    _f("TASKSRUNNER_MESH_COALESCE", "bool", "on",
       "write-behind frame coalescing (off = per-frame write+drain)"),
    _f("TASKSRUNNER_MESH_COALESCE_SECONDS", "float", "0",
       "extra coalescing window per flush (0 = event-loop-natural batching)"),
    _f("TASKSRUNNER_MESH_CODEC", "enum", "binary",
       "mesh header codec ceiling (binary | json); json forces the v1 headers"),
    _f("TASKSRUNNER_MESH_CONNECT_TIMEOUT_SECONDS", "float", "2",
       "mesh dial deadline before the caller falls back to HTTP"),
    _f("TASKSRUNNER_MESH_KEY", "path", "unset",
       "mesh mTLS private-key path"),
    _f("TASKSRUNNER_MESH_PING_SECONDS", "float", "15",
       "pre-warm/keepalive tick: idle-ping cadence (<= 0 disables)"),
    _f("TASKSRUNNER_MESH_REQUEST_TIMEOUT_SECONDS", "float", "300",
       "per-request mesh ceiling; consecutive expiries condemn the connection"),
    _f("TASKSRUNNER_ML_BATCHING", "bool", "on",
       "continuous micro-batching in the ML serving plane (off = batch-of-one)"),
    _f("TASKSRUNNER_ML_BUCKETS", "string", "1,2,4,8,16,32",
       "padding-bucket ladder; each bucket jit-compiles exactly once at warmup"),
    _f("TASKSRUNNER_ML_MAX_BATCH", "int", "32",
       "micro-batch size that flushes assembly immediately (size flush)"),
    _f("TASKSRUNNER_ML_MAX_DELAY_MS", "float", "5",
       "micro-batch assembly latency budget before a partial batch flushes"),
    _f("TASKSRUNNER_ML_MAX_QUEUE", "int", "256",
       "queued inference requests beyond which submits shed with 429"),
    _f("TASKSRUNNER_ML_MAX_TOKENS", "int", "8192",
       "tokens in flight at which the ML admission signal reaches 1.0"),
    _f("TASKSRUNNER_PERF_TESTS", "bool", "off",
       "opt-in performance assertions in the test suite"),
    _f("TASKSRUNNER_REPLICA", "int", "0",
       "replica index injected by the orchestrator"),
    _f("TASKSRUNNER_REPL_ACK_TIMEOUT_SECONDS", "float", "10",
       "deadline for a write to reach its ack quorum before failing 503"),
    _f("TASKSRUNNER_REPL_LEASE_SECONDS", "float", "5",
       "shard-leadership lease duration; expiry lets a follower promote"),
    _f("TASKSRUNNER_REPL_LOG_RETAIN", "int", "4096",
       "replication records kept per member; gaps beyond resync via snapshot"),
    _f("TASKSRUNNER_REPL_MAX_LAG_RECORDS", "int", "256",
       "follower lag bound for stale-tolerant reads (followerReads)"),
    _f("TASKSRUNNER_RESHARD", "bool", "off",
       "orchestrator elastic-placement control loop (heat ranking + "
       "rebalance planning over sharded stores)"),
    _f("TASKSRUNNER_RESHARD_HEAT_THRESHOLD", "float", "50",
       "EWMA write rate (ops/s) above which a shard counts as hot"),
    _f("TASKSRUNNER_RESHARD_HYSTERESIS_SECONDS", "float", "10",
       "how long a shard must stay above the heat threshold before it "
       "ranks hot (spikes below this never trigger a rebalance)"),
    _f("TASKSRUNNER_RESHARD_PAUSE_BUDGET_SECONDS", "float", "2",
       "write-pause ceiling for the fenced routing flip; a measured "
       "pause beyond it logs a warning with the drain time"),
    _f("TASKSRUNNER_SLOW_THRESHOLD_SECONDS", "float", "0.25",
       "latency above which histogram observations capture trace exemplars"),
    _f("TASKSRUNNER_SOAK", "bool", "off",
       "opt-in long-running soak tests"),
    _f("TASKSRUNNER_TOKENS_FILE", "path", "unset",
       "per-app API-token table used by the orchestrator"),
    _f("TASKSRUNNER_TRACE_DB", "path", ".tasksrunner/traces.db",
       "span-recorder SQLite path (set empty to disable recording)"),
    _f("TASKSRUNNER_TRACE_RETENTION_SECONDS", "float", "2592000",
       "span retention sweep horizon in seconds (<= 0 keeps everything)"),
    _f("TASKSRUNNER_UVLOOP", "bool", "off",
       "install uvloop's event-loop policy when the package is available"),
    _f("TASKSRUNNER_WORKFLOWS", "bool", "off",
       "durable workflow engine (orchestrators, activities, sagas) on "
       "the actor runtime"),
    _f("TASKSRUNNER_WORKFLOW_ACTIVITY_TIMEOUT_SECONDS", "float", "30",
       "default per-attempt activity deadline when the activity "
       "declares none"),
    _f("TASKSRUNNER_WORKFLOW_HISTORY_RETAIN_SECONDS", "float", "3600",
       "how long a terminal instance keeps its full history before the "
       "GC reminder truncates it to a summary (<= 0 keeps everything)"),
    _f("TASKSRUNNER_WORKFLOW_REPLAY_BATCH", "int", "16",
       "max activity executions committed per workflow step turn; "
       "bounds both turn length and the work a crash can lose"),
])

#: names env_flag accepts — the env-flag-discipline rule sends every
#: raw os.environ read of these through here
BOOL_FLAGS = frozenset(n for n, f in FLAGS.items() if f.kind == "bool")


def env_flag(name: str, default: bool = True) -> bool:
    """True unless the variable is set to an explicit disable value
    (case-insensitive: 0 / false / off / no). Unset or empty →
    ``default``.

    ``TASKSRUNNER_*`` names must be declared in :data:`FLAGS` — an
    undeclared knob is invisible to operators, the docs, and the
    static analysis, so it is refused loudly here rather than parsed
    quietly.
    """
    if name.startswith("TASKSRUNNER_") and name not in FLAGS:
        raise LookupError(
            f"{name} is not declared in tasksrunner.envflag.FLAGS — "
            "add it to the inventory (name, kind, default, doc)")
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    return raw.strip().lower() not in _FALSE
