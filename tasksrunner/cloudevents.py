"""CloudEvents 1.0 envelope helpers.

The reference publishes through the sidecar which wraps payloads in
CloudEvents, and the processor unwraps them with ``UseCloudEvents()``
(TasksTracker.Processor.Backend.Svc/Program.cs:29). Same contract here:
publish wraps, subscriber-side middleware unwraps, and raw payloads
pass through untouched when the content-type isn't cloudevents+json.
"""

from __future__ import annotations

import json
import time
from tasksrunner.ids import hex16
from typing import Any

CONTENT_TYPE = "application/cloudevents+json"


def wrap(
    data: Any,
    *,
    source: str,
    topic: str,
    pubsub_name: str,
    event_id: str | None = None,
    data_content_type: str = "application/json",
) -> dict:
    return {
        "specversion": "1.0",
        "id": event_id or hex16(),
        "source": source,
        "type": "com.tasksrunner.event.sent",
        "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "datacontenttype": data_content_type,
        "topic": topic,
        "pubsubname": pubsub_name,
        "data": data,
    }


def is_cloudevent(doc: Any) -> bool:
    return isinstance(doc, dict) and "specversion" in doc and "data" in doc


def unwrap(body: bytes, content_type: str | None) -> Any:
    """Return the inner data if ``body`` is a CloudEvent, else the
    JSON-decoded body (or raw bytes if not JSON).

    When a content-type is present it is authoritative: a raw-published
    payload delivered as ``application/json`` is never unwrapped, even
    if it happens to look like an envelope (forwarding pre-wrapped
    events verbatim is the main use of rawPayload). Shape-sniffing only
    applies when no content-type was provided.
    """
    try:
        doc = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return body
    if content_type is not None:
        if content_type.startswith(CONTENT_TYPE):
            return doc.get("data") if isinstance(doc, dict) else doc
        return doc
    if is_cloudevent(doc):
        return doc.get("data")
    return doc
