"""tasksrunner — a Python-native distributed-application-runtime framework.

A ground-up rebuild of the capability set of the reference workshop
(chsakell/aca-dotnet-workshop, mounted at /root/reference): Dapr-style
"building blocks" — service invocation with app-id discovery, pluggable
state stores with key-prefixing and filter queries, CloudEvents pub/sub
with declarative subscriptions, input/output/cron bindings, secret
stores, YAML component configuration with scoping, sidecar-style process
decoupling, structured observability, KEDA-style backlog autoscaling, a
local multi-app orchestrator, and a declarative deploy/plan layer.

The reference defines WHAT (capability matrix, component names, API
shapes — see SURVEY.md §2); this package defines HOW, idiomatically in
async Python. Nothing is translated line-by-line from the reference's
C#.
"""

__version__ = "0.1.0"

from tasksrunner.component.spec import ComponentSpec
from tasksrunner.component.loader import load_components, load_component_file
from tasksrunner.component.registry import ComponentRegistry, driver
from tasksrunner.secrets import drivers as _secret_drivers  # noqa: F401  (registers drivers)
from tasksrunner import state as _state  # noqa: F401  (registers state drivers)
from tasksrunner import pubsub as _pubsub  # noqa: F401  (registers pubsub drivers)
from tasksrunner import bindings as _bindings  # noqa: F401  (registers binding drivers)

from tasksrunner.app import App, Request, Response
from tasksrunner.client import AppClient, InvocationResponse
from tasksrunner.runtime import Runtime, InProcAppChannel, HTTPAppChannel
from tasksrunner.sidecar import Sidecar
from tasksrunner.hosting import AppHost, InProcCluster
from tasksrunner.invoke.resolver import AppAddress, NameResolver
from tasksrunner.resiliency import (
    ResiliencyPolicies,
    ResiliencySpec,
    load_resiliency,
    parse_resiliency,
)
from tasksrunner.chaos import (
    ChaosPolicies,
    ChaosSpec,
    chaos_enabled,
    load_chaos,
    parse_chaos,
)

__all__ = [
    "ComponentSpec",
    "load_components",
    "load_component_file",
    "ComponentRegistry",
    "driver",
    "App",
    "Request",
    "Response",
    "AppClient",
    "InvocationResponse",
    "Runtime",
    "InProcAppChannel",
    "HTTPAppChannel",
    "Sidecar",
    "AppHost",
    "InProcCluster",
    "AppAddress",
    "NameResolver",
    "ResiliencyPolicies",
    "ResiliencySpec",
    "load_resiliency",
    "parse_resiliency",
    "ChaosPolicies",
    "ChaosSpec",
    "chaos_enabled",
    "load_chaos",
    "parse_chaos",
    "__version__",
]
