"""The fault-injection engine: deterministic, per-target injectors.

``ChaosPolicies`` is the runtime-facing view, mirroring
``ResiliencyPolicies``: merged in-scope specs resolved per target, with
one persistent ``_Injector`` per (rule, target) pair. Each injector
owns a PRNG seeded from ``(spec seed, rule name, target key)`` — string
seeding hashes deterministically (not via PYTHONHASHSEED), so a seeded
chaos run is bit-for-bit reproducible across processes and across
invocations: the Nth call to a given target sees the same verdict every
run.

Every injected fault increments ``chaos_injected_total{target,fault}``
in the process-global :data:`~tasksrunner.observability.metrics.metrics`
registry, which the sidecar's ``/v1.0/metadata`` already exports —
``tasksrunner chaos status`` reads it from there.
"""

from __future__ import annotations

import asyncio
import logging
import random

from tasksrunner.chaos.spec import (
    BlackholeFault,
    ChaosRule,
    ChaosSpec,
    CrashEveryNFault,
    ErrorFault,
    LatencyFault,
    resolve_error_class,
)
from tasksrunner.errors import ChaosInjectedError
from tasksrunner.observability.metrics import metrics

logger = logging.getLogger(__name__)


def chaos_enabled() -> bool:
    """The master gate: chaos wiring exists only under
    ``TASKSRUNNER_CHAOS=1`` (off by default — the opposite default from
    every other env flag, because fault injection in production is an
    explicit decision)."""
    from tasksrunner.envflag import env_flag

    return env_flag("TASKSRUNNER_CHAOS", default=False)


class _Injector:
    """One (rule, target) pair: seeded PRNG + deterministic call count."""

    def __init__(self, rule: ChaosRule, target: str, seed: int,
                 disabled: set[str]):
        self.rule = rule
        self.target = target
        # string seeding is stable across processes (sha512-based, not
        # object hash) — the reproducibility contract rests on this
        self.rng = random.Random(f"{seed}:{rule.name}:{target}")
        self.calls = 0
        self._disabled = disabled  # shared with the owning ChaosPolicies

    def _record(self) -> None:
        metrics.inc("chaos_injected_total",
                    target=self.target, fault=self.rule.name)

    async def inject(self) -> int | None:
        """Apply this rule once. Returns an HTTP status to synthesize
        (status-mode error faults) or None; raises for raising faults.

        The call counter and PRNG advance even while the rule is
        disabled-then-reenabled only for calls actually seen — verdicts
        are a pure function of (seed, rule, target, call index).
        """
        if self.rule.name in self._disabled:
            return None
        self.calls += 1
        fault = self.rule.fault
        if isinstance(fault, LatencyFault):
            delay = fault.duration
            if fault.jitter:
                delay += self.rng.uniform(0.0, fault.jitter)
            self._record()
            await asyncio.sleep(delay)
            return None
        if isinstance(fault, ErrorFault):
            if fault.probability < 1.0 and self.rng.random() >= fault.probability:
                return None
            self._record()
            if fault.status is not None:
                return fault.status
            cls = resolve_error_class(fault.error)
            raise cls(f"chaos: injected {fault.error} by rule "
                      f"{self.rule.name!r} on {self.target!r}")
        if isinstance(fault, BlackholeFault):
            self._record()
            await asyncio.sleep(fault.deadline)
            raise TimeoutError(
                f"chaos: blackhole rule {self.rule.name!r} held "
                f"{self.target!r} for {fault.deadline}s")
        if isinstance(fault, CrashEveryNFault):
            if self.calls % fault.n == 0:
                self._record()
                cls = resolve_error_class(fault.error)
                raise cls(f"chaos: injected {fault.error} by rule "
                          f"{self.rule.name!r} on {self.target!r} "
                          f"(call #{self.calls})")
            return None
        raise ChaosInjectedError(  # pragma: no cover - parser rejects
            f"unknown fault kind on rule {self.rule.name!r}")


class ChaosPolicy:
    """The resolved injector chain for one target."""

    def __init__(self, injectors: list[_Injector]):
        self.injectors = injectors

    async def before_call(self) -> int | None:
        """Run every injector; the first synthesized HTTP status wins
        (raising faults propagate immediately)."""
        status = None
        for inj in self.injectors:
            s = await inj.inject()
            if s is not None and status is None:
                status = s
        return status

    def raise_for_status(self, status: int) -> None:
        """Component seams have no HTTP reply to synthesize — a
        status-mode fault surfaces as ChaosInjectedError carrying it."""
        raise ChaosInjectedError(
            f"chaos: injected HTTP {status} on a component call",
            status=status)


class ChaosPolicies:
    """Merged in-scope Chaos specs with persistent per-target injectors
    (mirrors ``ResiliencyPolicies``' resolution and caching shape)."""

    def __init__(self, specs: list[ChaosSpec], *, app_id: str | None = None):
        self.specs = [s for s in specs if s.in_scope(app_id)]
        self._injectors: dict[tuple[str, str], _Injector] = {}
        self._cache: dict[tuple[str, str, str], ChaosPolicy | None] = {}
        #: rule names currently switched off (runtime toggle: tests
        #: flip faults mid-scenario; the admin surface lists them)
        self.disabled: set[str] = set()

    # -- runtime toggles -------------------------------------------------

    def disable(self, rule_name: str) -> None:
        self.disabled.add(rule_name)

    def enable(self, rule_name: str) -> None:
        self.disabled.discard(rule_name)

    # -- resolution ------------------------------------------------------

    def for_app(self, app_id: str) -> ChaosPolicy | None:
        """Faults applied to service invocation toward ``app_id``."""
        return self._resolve("apps", app_id, "outbound")

    def for_component(self, name: str, direction: str = "outbound") -> ChaosPolicy | None:
        """Faults applied to component operations on ``name``."""
        return self._resolve("components", name, direction)

    def for_actor(self, actor_type: str) -> ChaosPolicy | None:
        """Faults applied to actor turns of ``actor_type``. The actor
        runtime consults this inside the OWNER's turn execution, so a
        crashEveryN rule here deterministically fells whichever replica
        currently owns the actor — placement-following by construction,
        no replica targeting needed."""
        return self._resolve("actors", actor_type, "turn")

    def for_replication(self, store: str, shard: int,
                        member: str) -> ChaosPolicy | None:
        """Faults applied to the record stream from ``store``'s shard
        leader toward follower ``member``. Resolution is most-specific
        first — ``store/shard/member`` beats ``store/shard`` beats
        ``store`` — so a drill can sever exactly one lane."""
        for key in (f"{store}/{shard}/{member}", f"{store}/{shard}", store):
            policy = self._resolve("replication", key, "stream")
            if policy is not None:
                return policy
        return None

    def for_placement(self, store: str,
                      shard: int | None = None) -> ChaosPolicy | None:
        """Faults applied to a live migration's catch-up stream for
        ``store`` (elastic placement, PR 20). Resolution is
        most-specific first — ``store/shard`` beats ``store`` — so a
        drill can blackhole one shard's migration while another
        reshards normally. The store consults this ONLY on the pre-flip
        path (lag polls, bulk copies): an injected hang aborts the
        migration with routing untouched and can never extend the
        fenced write-pause."""
        keys = ((f"{store}/{shard}", store)
                if shard is not None else (store,))
        for key in keys:
            policy = self._resolve("placement", key, "migration")
            if policy is not None:
                return policy
        return None

    def for_workflow(self, workflow: str,
                     activity: str | None = None) -> ChaosPolicy | None:
        """Faults applied inside workflow activity attempts. Resolution
        is most-specific first — ``workflow/activity`` beats
        ``workflow`` — so a drill can poison exactly one saga step. The
        engine consults this on the instance's OWNING replica, inside
        the attempt, so a crashEveryN rule here fells whoever is
        executing that step right now (placement-following, like
        :meth:`for_actor`)."""
        keys = ((f"{workflow}/{activity}", workflow)
                if activity is not None else (workflow,))
        for key in keys:
            policy = self._resolve("workflows", key, "activity")
            if policy is not None:
                return policy
        return None

    def _resolve(self, kind: str, name: str, direction: str) -> ChaosPolicy | None:
        cache_key = (kind, name, direction)
        if cache_key in self._cache:
            return self._cache[cache_key]
        injectors: list[_Injector] = []
        for spec in self.specs:
            if kind == "apps":
                refs = spec.app_targets.get(name)
            elif kind == "actors":
                refs = spec.actor_targets.get(name)
            elif kind == "replication":
                refs = spec.replication_targets.get(name)
            elif kind == "workflows":
                refs = spec.workflow_targets.get(name)
            elif kind == "placement":
                refs = spec.placement_targets.get(name)
            else:
                refs = (spec.component_targets.get(name) or {}).get(direction)
            if not refs:
                continue
            target_key = f"{kind}/{name}/{direction}"
            for ref in refs:
                ikey = (ref, target_key)
                inj = self._injectors.get(ikey)
                if inj is None:
                    inj = self._injectors[ikey] = _Injector(
                        spec.rules[ref], target_key, spec.seed, self.disabled)
                injectors.append(inj)
            break  # first in-scope spec naming the target wins
        policy = ChaosPolicy(injectors) if injectors else None
        self._cache[cache_key] = policy
        return policy

    # -- introspection ---------------------------------------------------

    def describe(self) -> list[dict]:
        """Flat rule/target listing for the admin surface."""
        out = []
        for spec in self.specs:
            for rule in spec.rules.values():
                bound = [
                    f"apps/{app}" for app, refs in spec.app_targets.items()
                    if rule.name in refs
                ] + [
                    f"components/{comp}/{direction}"
                    for comp, dirs in spec.component_targets.items()
                    for direction, refs in dirs.items()
                    if rule.name in refs
                ] + [
                    f"actors/{atype}/turn"
                    for atype, refs in spec.actor_targets.items()
                    if rule.name in refs
                ] + [
                    f"replication/{lane}/stream"
                    for lane, refs in spec.replication_targets.items()
                    if rule.name in refs
                ] + [
                    f"workflows/{key}/activity"
                    for key, refs in spec.workflow_targets.items()
                    if rule.name in refs
                ] + [
                    f"placement/{key}/migration"
                    for key, refs in spec.placement_targets.items()
                    if rule.name in refs
                ]
                out.append({
                    "spec": spec.name,
                    "rule": rule.name,
                    "fault": type(rule.fault).__name__,
                    "params": rule.fault.__dict__,
                    "targets": bound,
                    "disabled": rule.name in self.disabled,
                })
        return out
