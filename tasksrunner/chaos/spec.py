"""Parse ``kind: Chaos`` YAML documents.

A Chaos document declares named **fault rules** and binds them to the
same target taxonomy the Resiliency loader uses (apps; components with
outbound/inbound directions — ``resiliency/spec.py``). The documents
live in the resources directory beside components and Resiliency docs;
the component loader skips them and ``load_chaos`` collects them. They
are inert unless the host runs with ``TASKSRUNNER_CHAOS=1``.

.. code-block:: yaml

    apiVersion: tasksrunner/v1alpha1
    kind: Chaos
    metadata:
      name: tasks-chaos
    scopes: [tasksmanager-backend-api]       # optional
    spec:
      seed: 42                               # PRNG seed (default 0)
      faults:
        slowStore:
          latency: {duration: 20ms, jitter: 10ms}
        flakyStore:
          error: {probability: 0.1, raise: OSError}
        deadPeer:
          blackhole: {deadline: 2s}
        poison:
          crashEveryN: {n: 5, raise: PubSubError}
      targets:
        apps:
          tasksmanager-backend-api: [deadPeer]
        components:
          statestore:
            outbound: [slowStore, flakyStore]
          taskspubsub:
            inbound: [poison]
        actors:
          Counter: [poison]
        replication:
          statestore/0/r1: [deadPeer]          # one leader→follower lane
          statestore: [slowStore]              # every lane of the store
        workflows:
          checkout/charge: [poison]            # one activity of one workflow
          checkout: [slowStore]                # every activity of the workflow
        placement:
          statestore/2: [deadPeer]             # migrations of one shard
          statestore: [slowStore]              # any migration of the store

Replication targets address the record stream between a shard's leader
and a follower (state/replication.py): the key is ``<store>``,
``<store>/<shard>``, or ``<store>/<shard>/<member>`` — most specific
wins at resolution time, so a drill can blackhole exactly one
leader→follower lane while the rest of the set replicates normally.

Workflow targets follow the same most-specific-first shape: the key is
``<workflow>`` or ``<workflow>/<activity>``, and the engine consults
it on the OWNING replica inside each activity attempt — so a
``crashEveryN`` rule on ``checkout/charge`` deterministically fells
whichever replica is executing that saga step, wherever placement
moved the instance (the workflow recovery drill's primitive).

Placement targets (``<store>`` or ``<store>/<shard>``, most specific
wins) bind to a live migration's catch-up stream
(state/sharding.py) — the lag polls and bulk key copies that run
BEFORE the fenced routing flip. A blackhole here must abort the
migration cleanly with routing untouched; it must never be able to
wedge the write-pause itself, which is why the gate is consulted only
on the pre-flip path.

Each named fault carries exactly one fault kind:

* ``latency`` — fixed delay plus uniform jitter before the call;
* ``error`` — with ``probability``, raise a named error class
  (a ``tasksrunner.errors`` class, or one of the transport shapes
  ``OSError``/``TimeoutError``/``ConnectionError`` that the resiliency
  retry loop treats as retriable), or synthesize an HTTP ``status``;
* ``blackhole`` — hang for ``deadline`` seconds, then time out;
* ``crashEveryN`` — deterministically fail every Nth call.

Dangling rule references and unknown error names fail at load time,
matching the Resiliency loader's posture: a typo'd chaos file must fail
the host's startup, not silently inject nothing.
"""

from __future__ import annotations

import pathlib
from dataclasses import dataclass, field
from typing import Any, Mapping

import yaml

from tasksrunner import errors as errors_mod
from tasksrunner.errors import ComponentError
from tasksrunner.resiliency.policy import parse_duration

_YAML_SUFFIXES = {".yaml", ".yml"}

#: error names an ``error``/``crashEveryN`` fault may raise: every
#: TasksRunnerError subclass, plus the transport shapes the builtin and
#: declarative retry loops treat as retriable.
_TRANSPORT_ERRORS = {
    "OSError": OSError,
    "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
}


def resolve_error_class(name: str, *, where: str = "chaos") -> type[BaseException]:
    """Map a fault's ``raise:`` name to an exception class, or fail."""
    if name in _TRANSPORT_ERRORS:
        return _TRANSPORT_ERRORS[name]
    cls = getattr(errors_mod, name, None)
    if isinstance(cls, type) and issubclass(cls, errors_mod.TasksRunnerError):
        return cls
    known = sorted(
        [n for n in dir(errors_mod)
         if isinstance(getattr(errors_mod, n), type)
         and issubclass(getattr(errors_mod, n), errors_mod.TasksRunnerError)]
        + list(_TRANSPORT_ERRORS))
    raise ComponentError(
        f"{where}: unknown fault error class {name!r} "
        f"(known: {', '.join(known)})")


@dataclass(frozen=True)
class LatencyFault:
    duration: float
    jitter: float = 0.0


@dataclass(frozen=True)
class ErrorFault:
    probability: float = 1.0
    #: name of the exception class to raise (validated at parse time)
    error: str | None = None
    #: alternatively, synthesize this HTTP status (invoke targets reply
    #: with it; component calls raise ChaosInjectedError carrying it)
    status: int | None = None


@dataclass(frozen=True)
class BlackholeFault:
    #: how long the call hangs before failing with TimeoutError
    deadline: float = 60.0


@dataclass(frozen=True)
class CrashEveryNFault:
    n: int
    error: str = "OSError"


Fault = LatencyFault | ErrorFault | BlackholeFault | CrashEveryNFault


@dataclass(frozen=True)
class ChaosRule:
    """One named fault rule (``spec.faults.<name>``)."""

    name: str
    fault: Fault


@dataclass
class ChaosSpec:
    """One parsed Chaos document."""

    name: str
    seed: int = 0
    scopes: list[str] = field(default_factory=list)
    rules: dict[str, ChaosRule] = field(default_factory=dict)
    #: app-id → rule names applied to outbound invokes toward that app
    app_targets: dict[str, tuple[str, ...]] = field(default_factory=dict)
    #: component → direction → rule names
    component_targets: dict[str, dict[str, tuple[str, ...]]] = field(
        default_factory=dict)
    #: actor type → rule names, injected inside the owning replica's
    #: turn execution — by construction the fault always hits the
    #: CURRENT owner, wherever placement moved it (the failover drill's
    #: crash-the-owner primitive)
    actor_targets: dict[str, tuple[str, ...]] = field(default_factory=dict)
    #: replication-lane key → rule names, injected on the leader's
    #: record shipment toward a follower. Keys are ``store``,
    #: ``store/shard`` or ``store/shard/member`` (most specific wins).
    replication_targets: dict[str, tuple[str, ...]] = field(
        default_factory=dict)
    #: workflow key → rule names, injected inside activity attempts on
    #: the instance's owning replica. Keys are ``workflow`` or
    #: ``workflow/activity`` (most specific wins).
    workflow_targets: dict[str, tuple[str, ...]] = field(
        default_factory=dict)
    #: placement key → rule names, injected on a live migration's
    #: catch-up stream before the fenced flip. Keys are ``store`` or
    #: ``store/shard`` (most specific wins).
    placement_targets: dict[str, tuple[str, ...]] = field(
        default_factory=dict)

    def in_scope(self, app_id: str | None) -> bool:
        if not self.scopes or app_id is None:
            return True
        return app_id in self.scopes


def is_chaos_doc(doc: Any) -> bool:
    return isinstance(doc, Mapping) and doc.get("kind") == "Chaos"


def _parse_fault(name: str, raw: Mapping[str, Any], *, where: str) -> Fault:
    if not isinstance(raw, Mapping) or len(raw) != 1:
        raise ComponentError(
            f"{where}: fault {name!r} must be a mapping with exactly one "
            "fault kind (latency / error / blackhole / crashEveryN)")
    kind, body = next(iter(raw.items()))
    if not isinstance(body, Mapping):
        raise ComponentError(f"{where}: fault {name!r}.{kind} must be a mapping")
    if kind == "latency":
        jitter = parse_duration(body.get("jitter", 0))
        duration = parse_duration(body.get("duration", 0))
        if duration < 0 or jitter < 0:
            raise ComponentError(f"{where}: fault {name!r}: negative latency")
        return LatencyFault(duration=duration, jitter=jitter)
    if kind == "error":
        prob = float(body.get("probability", 1.0))
        if not 0.0 <= prob <= 1.0:
            raise ComponentError(
                f"{where}: fault {name!r}: probability must be in [0, 1]")
        error = body.get("raise")
        status = body.get("status")
        if (error is None) == (status is None):
            raise ComponentError(
                f"{where}: fault {name!r}: give exactly one of "
                "'raise: <ErrorClass>' or 'status: <int>'")
        if error is not None:
            resolve_error_class(str(error), where=f"{where}: fault {name!r}")
            return ErrorFault(probability=prob, error=str(error))
        status = int(status)
        if not 100 <= status <= 599:
            raise ComponentError(
                f"{where}: fault {name!r}: status {status} is not an "
                "HTTP status")
        return ErrorFault(probability=prob, status=status)
    if kind == "blackhole":
        return BlackholeFault(deadline=parse_duration(body.get("deadline", "60s")))
    if kind == "crashEveryN":
        n = int(body.get("n", 0))
        if n < 1:
            raise ComponentError(
                f"{where}: fault {name!r}: crashEveryN needs n >= 1")
        error = str(body.get("raise", "OSError"))
        resolve_error_class(error, where=f"{where}: fault {name!r}")
        return CrashEveryNFault(n=n, error=error)
    raise ComponentError(
        f"{where}: fault {name!r}: unknown fault kind {kind!r} "
        "(expected latency / error / blackhole / crashEveryN)")


def _parse_rule_refs(raw: Any, *, where: str, target: str) -> tuple[str, ...]:
    """A target binds one rule name or a list of them."""
    if isinstance(raw, str):
        return (raw,)
    if isinstance(raw, list) and all(isinstance(r, str) for r in raw):
        return tuple(raw)
    raise ComponentError(
        f"{where}: target {target!r} must name a fault rule or a list "
        "of fault rules")


def parse_chaos(doc: Mapping[str, Any], *, source: str | None = None) -> ChaosSpec:
    where = source or "chaos"
    if not is_chaos_doc(doc):
        raise ComponentError(f"{where}: not a Chaos document")
    meta = doc.get("metadata") or {}
    name = str(meta.get("name") or "chaos")
    spec = doc.get("spec") or {}

    try:
        seed = int(spec.get("seed", 0))
    except (TypeError, ValueError):
        raise ComponentError(f"{where}: seed must be an integer") from None

    rules: dict[str, ChaosRule] = {}
    for rname, raw in (spec.get("faults") or {}).items():
        rules[str(rname)] = ChaosRule(
            name=str(rname), fault=_parse_fault(str(rname), raw, where=where))

    targets = spec.get("targets") or {}
    app_targets = {
        str(app): _parse_rule_refs(raw, where=where, target=str(app))
        for app, raw in (targets.get("apps") or {}).items()
    }
    component_targets: dict[str, dict[str, tuple[str, ...]]] = {}
    for comp, raw in (targets.get("components") or {}).items():
        if not isinstance(raw, Mapping):
            raise ComponentError(
                f"{where}: component target {comp!r} must be a mapping")
        directions: dict[str, tuple[str, ...]] = {}
        for direction in ("outbound", "inbound"):
            if direction in raw:
                directions[direction] = _parse_rule_refs(
                    raw[direction], where=where, target=str(comp))
        if not directions:
            raise ComponentError(
                f"{where}: component target {comp!r} needs an 'outbound' "
                "or 'inbound' direction")
        component_targets[str(comp)] = directions
    actor_targets = {
        str(atype): _parse_rule_refs(raw, where=where, target=str(atype))
        for atype, raw in (targets.get("actors") or {}).items()
    }
    replication_targets = {
        str(lane): _parse_rule_refs(raw, where=where, target=str(lane))
        for lane, raw in (targets.get("replication") or {}).items()
    }
    workflow_targets = {
        str(key): _parse_rule_refs(raw, where=where, target=str(key))
        for key, raw in (targets.get("workflows") or {}).items()
    }
    placement_targets = {
        str(key): _parse_rule_refs(raw, where=where, target=str(key))
        for key, raw in (targets.get("placement") or {}).items()
    }

    scopes = doc.get("scopes") or []
    if not isinstance(scopes, list) or not all(isinstance(s, str) for s in scopes):
        raise ComponentError(f"{where}: scopes must be a list of app-ids")

    # dangling rule references fail at load time, like the Resiliency
    # loader: a typo must fail startup, not silently inject nothing
    all_refs = (list(app_targets.items()) + list(actor_targets.items())
                + list(replication_targets.items())
                + list(workflow_targets.items())
                + list(placement_targets.items())) + [
        (comp, ref)
        for comp, dirs in component_targets.items()
        for ref in dirs.values()
    ]
    for target, refs in all_refs:
        for ref in refs:
            if ref not in rules:
                raise ComponentError(
                    f"{where}: target {target!r} references unknown fault "
                    f"rule {ref!r}")

    return ChaosSpec(
        name=name,
        seed=seed,
        scopes=list(scopes),
        rules=rules,
        app_targets=app_targets,
        component_targets=component_targets,
        actor_targets=actor_targets,
        replication_targets=replication_targets,
        workflow_targets=workflow_targets,
        placement_targets=placement_targets,
    )


def load_chaos(resources_path: str | pathlib.Path) -> list[ChaosSpec]:
    """Collect every ``kind: Chaos`` document under ``resources_path``."""
    root = pathlib.Path(resources_path)
    if not root.is_dir():
        return []
    specs: list[ChaosSpec] = []
    for path in sorted(root.iterdir()):
        if path.suffix.lower() not in _YAML_SUFFIXES or not path.is_file():
            continue
        try:
            docs = list(yaml.safe_load_all(path.read_text()))
        except (OSError, yaml.YAMLError) as exc:
            raise ComponentError(f"cannot read {path}: {exc}") from exc
        for doc in docs:
            if is_chaos_doc(doc):
                specs.append(parse_chaos(doc, source=str(path)))
    return specs
