"""Declarative, deterministic fault injection (``kind: Chaos``).

Off by default: the subsystem only activates when the host runs with
``TASKSRUNNER_CHAOS=1`` *and* a Chaos document targets the app — the
production hot path never sees a wrapper object. See
``docs/modules/16-chaos.md``.
"""

from tasksrunner.chaos.engine import ChaosPolicies, ChaosPolicy, chaos_enabled
from tasksrunner.chaos.spec import (
    BlackholeFault,
    ChaosRule,
    ChaosSpec,
    CrashEveryNFault,
    ErrorFault,
    LatencyFault,
    is_chaos_doc,
    load_chaos,
    parse_chaos,
)
from tasksrunner.chaos.wrappers import (
    ChaosInputBinding,
    ChaosOutputBinding,
    ChaosPubSubBroker,
    ChaosStateStore,
    wrap_component,
)

__all__ = [
    "BlackholeFault",
    "ChaosInputBinding",
    "ChaosOutputBinding",
    "ChaosPolicies",
    "ChaosPolicy",
    "ChaosPubSubBroker",
    "ChaosRule",
    "ChaosSpec",
    "ChaosStateStore",
    "CrashEveryNFault",
    "ErrorFault",
    "LatencyFault",
    "chaos_enabled",
    "is_chaos_doc",
    "load_chaos",
    "parse_chaos",
    "wrap_component",
]
