"""Chaos wrappers: interpose fault rules on every building-block seam.

Each wrapper subclasses the building-block ABC it shadows (the runtime
isinstance-checks ``InputBinding``/``OutputBinding`` and treats the
others by block), applies the resolved :class:`ChaosPolicy` before
delegating, and forwards everything else to the wrapped instance via
``__getattr__`` so driver extras — the sqlite broker's
``requeue_dead_letters``/``dead_letters``, the state store's cache
stats — keep working through the wrapper.

Direction semantics mirror the Resiliency target taxonomy:

* state stores and output bindings are pure *outbound* seams;
* pub/sub applies **outbound** rules to ``publish`` and **inbound**
  rules to each delivery (the handler wrapper raises, which the broker
  counts as a nack → redelivery → DLQ, so injected inbound faults
  exercise the real at-least-once machinery);
* input bindings apply **inbound** rules to each event delivery.
"""

from __future__ import annotations

from typing import Any

from tasksrunner.bindings.base import (
    BindingEvent,
    BindingResponse,
    EventSink,
    InputBinding,
    OutputBinding,
)
from tasksrunner.chaos.engine import ChaosPolicies, ChaosPolicy
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.pubsub.base import Handler, Message, PubSubBroker, Subscription
from tasksrunner.state.base import (
    QueryResponse,
    StateItem,
    StateStore,
    TransactionOp,
)


async def _before(policy: ChaosPolicy | None) -> None:
    """Run the injector chain for a component seam. Synthesized HTTP
    statuses have no reply to ride on here, so they become
    ChaosInjectedError carrying the status."""
    if policy is None:
        return
    status = await policy.before_call()
    if status is not None:
        policy.raise_for_status(status)


class ChaosStateStore(StateStore):
    """State store with outbound fault rules applied per operation."""

    def __init__(self, inner: StateStore, policy: ChaosPolicy):
        super().__init__(inner.name)
        self.inner = inner
        self.policy = policy
        self.supports_query = inner.supports_query

    async def get(self, key: str) -> StateItem | None:
        await _before(self.policy)
        return await self.inner.get(key)

    async def set(self, key: str, value: Any, *, etag: str | None = None) -> str:
        await _before(self.policy)
        return await self.inner.set(key, value, etag=etag)

    async def delete(self, key: str, *, etag: str | None = None) -> bool:
        await _before(self.policy)
        return await self.inner.delete(key, etag=etag)

    async def query(self, query: dict, *, key_prefix: str = "") -> QueryResponse:
        await _before(self.policy)
        return await self.inner.query(query, key_prefix=key_prefix)

    async def bulk_get(self, keys: list[str]) -> list[StateItem | None]:
        await _before(self.policy)
        return await self.inner.bulk_get(keys)

    async def transact(self, ops: list[TransactionOp]) -> None:
        await _before(self.policy)
        await self.inner.transact(ops)

    async def keys(self, *, prefix: str = "") -> list[str]:
        await _before(self.policy)
        return await self.inner.keys(prefix=prefix)

    def close(self) -> None:
        self.inner.close()

    async def aclose(self) -> None:
        await self.inner.aclose()

    def __getattr__(self, item: str) -> Any:
        return getattr(self.inner, item)


class ChaosPubSubBroker(PubSubBroker):
    """Broker with outbound rules on publish, inbound rules on delivery."""

    def __init__(self, inner: PubSubBroker,
                 outbound: ChaosPolicy | None, inbound: ChaosPolicy | None):
        super().__init__(inner.name)
        self.inner = inner
        self.outbound = outbound
        self.inbound = inbound

    async def publish(self, topic: str, data: Any, *,
                      metadata: dict[str, str] | None = None) -> str:
        await _before(self.outbound)
        return await self.inner.publish(topic, data, metadata=metadata)

    async def subscribe(self, topic: str, group: str, handler: Handler) -> Subscription:
        if self.inbound is None:
            return await self.inner.subscribe(topic, group, handler)
        inbound = self.inbound

        async def chaotic_handler(message: Message) -> bool:
            # a raised fault is a nack: the broker's redelivery /
            # dead-letter machinery sees exactly what a crashing
            # consumer would produce
            await _before(inbound)
            return await handler(message)

        return await self.inner.subscribe(topic, group, chaotic_handler)

    async def ensure_group(self, topic: str, group: str) -> None:
        await self.inner.ensure_group(topic, group)

    async def aclose(self) -> None:
        await self.inner.aclose()

    def __getattr__(self, item: str) -> Any:
        return getattr(self.inner, item)


class ChaosInputBinding(InputBinding):
    """Input binding with inbound rules applied to each delivery."""

    def __init__(self, inner: InputBinding, policy: ChaosPolicy):
        super().__init__(inner.name)
        self.inner = inner
        self.policy = policy
        self.route = inner.route

    @property
    def running(self) -> bool:
        return self.inner.running

    @running.setter
    def running(self, value: bool) -> None:
        # InputBinding.__init__ assigns running=False before self.inner
        # exists; the real flag lives on the wrapped instance
        if "inner" in self.__dict__:
            self.inner.running = value

    async def start(self, sink: EventSink) -> None:
        policy = self.policy

        async def chaotic_sink(event: BindingEvent) -> bool:
            await _before(policy)
            return await sink(event)

        await self.inner.start(chaotic_sink)

    async def stop(self) -> None:
        await self.inner.stop()

    def __getattr__(self, item: str) -> Any:
        return getattr(self.inner, item)


class ChaosOutputBinding(OutputBinding):
    """Output binding with outbound rules applied per invoke."""

    def __init__(self, inner: OutputBinding, policy: ChaosPolicy):
        super().__init__(inner.name)
        self.inner = inner
        self.policy = policy

    @property
    def operations(self) -> list[str]:
        return self.inner.operations

    async def invoke(self, operation: str, data: Any,
                     metadata: dict[str, str] | None = None) -> BindingResponse:
        await _before(self.policy)
        return await self.inner.invoke(operation, data, metadata)

    def __getattr__(self, item: str) -> Any:
        return getattr(self.inner, item)


def wrap_component(instance: Any, spec: ComponentSpec,
                   chaos: ChaosPolicies | None) -> Any:
    """Wrap a freshly-built component in its chaos interposer, if any
    rule targets it. With no matching rules (or no chaos at all) the
    instance is returned untouched — the disabled path allocates
    nothing."""
    if chaos is None:
        return instance
    block = spec.block
    if block == "state":
        # replication-lane faults bind to the member links themselves
        # (leader→follower record stream), independent of — and
        # composable with — the outbound per-operation rules below
        attach = getattr(instance, "attach_chaos", None)
        if attach is not None:
            attach(chaos)
        else:
            for child in getattr(instance, "_shards", []):
                child_attach = getattr(child, "attach_chaos", None)
                if child_attach is not None:
                    child_attach(chaos)
        policy = chaos.for_component(spec.name, "outbound")
        if policy is not None and isinstance(instance, StateStore):
            return ChaosStateStore(instance, policy)
        return instance
    if block == "pubsub":
        outbound = chaos.for_component(spec.name, "outbound")
        inbound = chaos.for_component(spec.name, "inbound")
        if (outbound or inbound) and isinstance(instance, PubSubBroker):
            return ChaosPubSubBroker(instance, outbound, inbound)
        return instance
    if block == "bindings":
        if isinstance(instance, InputBinding):
            policy = chaos.for_component(spec.name, "inbound")
            if policy is not None:
                return ChaosInputBinding(instance, policy)
        elif isinstance(instance, OutputBinding):
            policy = chaos.for_component(spec.name, "outbound")
            if policy is not None:
                return ChaosOutputBinding(instance, policy)
    return instance
