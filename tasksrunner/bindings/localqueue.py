"""SQLite-backed message queue: input binding + output binding + raw queue.

The local stand-in for the reference's Azure Storage Queue pair
(components/dapr-bindings-in-storagequeue.yaml: the sidecar polls
``external-tasks-queue`` and POSTs each message to the app route from
the component's ``route`` metadata; 2xx acks/deletes, non-2xx →
redelivery — docs/aca/06-aca-dapr-bindingsapi/index.md:47-60). External
producers drop messages in via the ``SqliteQueue`` API, an output
binding, or any sqlite client — the moral equivalent of the workshop's
"send a message with Azure Storage Explorer" step.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
import json
import logging
import pathlib
import sqlite3
import time
import uuid
from typing import Any

from tasksrunner.bindings.base import (
    BindingEvent,
    BindingResponse,
    EventSink,
    InputBinding,
    OutputBinding,
)
from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec

logger = logging.getLogger(__name__)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS queue (
    id            TEXT PRIMARY KEY,
    data          TEXT NOT NULL,
    enqueued      REAL NOT NULL,
    visible_at    REAL NOT NULL,
    attempts      INTEGER NOT NULL DEFAULT 0,
    done          INTEGER NOT NULL DEFAULT 0
);
CREATE INDEX IF NOT EXISTS idx_queue_visible ON queue (done, visible_at);
"""



def _locked(fn):
    """Serialise a db-touching method on the instance's _db_lock."""
    def wrapper(self, *args, **kwargs):
        with self._db_lock:
            return fn(self, *args, **kwargs)
    return wrapper


class SqliteQueue:
    """The queue itself — shared across processes via the db file."""

    def __init__(self, path: str | pathlib.Path, *, claim_lease: float = 30.0):
        self.path = str(path)
        if self.path != ":memory:":
            pathlib.Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        self.claim_lease = claim_lease
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        # WAL + NORMAL: fsync at checkpoint, not per-commit — the
        # standard durability/throughput point for local engines
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute("PRAGMA busy_timeout=5000")
        self._conn.executescript(_SCHEMA)
        self._conn.commit()
        # serialises cross-thread use of the shared connection (binding
        # executor thread vs. sync producers on other threads)
        self._db_lock = threading.Lock()

    @_locked
    def send(self, data: Any) -> str:
        msg_id = str(uuid.uuid4())
        now = time.time()
        self._conn.execute(
            "INSERT INTO queue(id, data, enqueued, visible_at) VALUES (?,?,?,?)",
            (msg_id, json.dumps(data), now, now),
        )
        self._conn.commit()
        return msg_id

    @_locked
    def claim(self) -> tuple[str, Any, int] | None:
        """Claim the next visible message: (id, data, attempt#)."""
        now = time.time()
        cur = self._conn.cursor()
        try:
            cur.execute("BEGIN IMMEDIATE")
            row = cur.execute(
                "SELECT id, data, attempts FROM queue "
                "WHERE done = 0 AND visible_at <= ? ORDER BY enqueued LIMIT 1",
                (now,),
            ).fetchone()
            if row is None:
                self._conn.commit()
                return None
            msg_id, data, attempts = row
            cur.execute(
                "UPDATE queue SET visible_at = ?, attempts = attempts + 1 WHERE id = ?",
                (now + self.claim_lease, msg_id),
            )
            self._conn.commit()
        except BaseException:
            self._conn.rollback()
            raise
        return msg_id, json.loads(data), attempts + 1

    @_locked
    def ack(self, msg_id: str) -> None:
        self._conn.execute("UPDATE queue SET done = 1 WHERE id = ?", (msg_id,))
        self._conn.commit()

    @_locked
    def nack(self, msg_id: str, *, delay: float = 0.2) -> None:
        self._conn.execute(
            "UPDATE queue SET visible_at = ? WHERE id = ?",
            (time.time() + delay, msg_id),
        )
        self._conn.commit()

    @_locked
    def dead_letter(self, msg_id: str) -> None:
        self._conn.execute("UPDATE queue SET done = 2 WHERE id = ?", (msg_id,))
        self._conn.commit()

    @_locked
    def backlog(self) -> int:
        (n,) = self._conn.execute(
            "SELECT COUNT(*) FROM queue WHERE done = 0"
        ).fetchone()
        return n

    @_locked
    def dead_letter_detail(self) -> list[dict]:
        """Parked messages with payloads, for operator inspection
        (≙ peeking a Storage-queue poison queue)."""
        rows = self._conn.execute(
            "SELECT id, data, attempts, enqueued FROM queue "
            "WHERE done = 2 ORDER BY enqueued").fetchall()
        return [
            {"id": msg_id, "attempts": attempts, "data": json.loads(data),
             "created": enqueued}
            for msg_id, data, attempts, enqueued in rows
        ]

    @_locked
    def purge_dead_letters(self, msg_ids: list[str] | None = None) -> int:
        """Explicitly discard dead letters."""
        sql = "DELETE FROM queue WHERE done = 2"
        params: list = []
        if msg_ids is not None:
            if not msg_ids:
                return 0
            sql += f" AND id IN ({', '.join('?' for _ in msg_ids)})"
            params.extend(msg_ids)
        cur = self._conn.execute(sql, params)
        self._conn.commit()
        return cur.rowcount

    @_locked
    def requeue_dead_letters(self, msg_ids: list[str] | None = None) -> int:
        """Return dead-letters to the queue with a fresh attempt budget."""
        now = time.time()
        sql = ("UPDATE queue SET done = 0, attempts = 0, visible_at = ? "
               "WHERE done = 2")
        params: list = [now]
        if msg_ids is not None:
            if not msg_ids:
                return 0
            sql += f" AND id IN ({', '.join('?' for _ in msg_ids)})"
            params.extend(msg_ids)
        cur = self._conn.execute(sql, params)
        self._conn.commit()
        return cur.rowcount

    def close(self) -> None:
        self._conn.close()


def open_queue_for_inspection(spec: ComponentSpec,
                              base_dir: pathlib.Path | str | None = None,
                              *, must_exist: bool = True) -> SqliteQueue:
    """Open a queue-binding component's shared queue file out-of-band
    (same position as pubsub.sqlite.open_for_inspection). Metadata
    defaults mirror the driver exactly."""
    from tasksrunner.errors import ComponentError

    if spec.type not in QUEUE_BINDING_TYPES:
        raise ComponentError(
            f"component {spec.name!r} is {spec.type}, not a queue binding "
            f"backed by a shared queue file ({', '.join(sorted(QUEUE_BINDING_TYPES))})")
    root = spec.metadata.get("queuePath", ".tasksrunner/queues")
    qname = spec.metadata.get("queueName", spec.name)
    if not isinstance(root, str) or not isinstance(qname, str):
        raise ComponentError(
            f"component {spec.name!r} has secret-typed queue path metadata")
    path = pathlib.Path(root) / f"{qname}.db"
    if not path.is_absolute():
        path = pathlib.Path(base_dir or pathlib.Path.cwd()) / path
    if must_exist and not path.is_file():
        raise ComponentError(
            f"queue file {path} does not exist — has anything been sent to "
            "this queue yet? (relative queuePath resolves against the "
            "run-config's directory; pass --base-dir)")
    return SqliteQueue(path)


class LocalQueueBinding(InputBinding, OutputBinding):
    """Input side polls and delivers; output side `create` enqueues."""

    def __init__(self, name: str, path: str, *, route: str | None = None,
                 poll_interval: float = 0.05, max_attempts: int = 3,
                 retry_delay: float = 0.2):
        InputBinding.__init__(self, name)
        self.queue = SqliteQueue(path)
        if route:
            self.route = route if route.startswith("/") else "/" + route
        self.poll_interval = poll_interval
        self.max_attempts = max_attempts
        self.retry_delay = retry_delay
        self._task: asyncio.Task | None = None
        # one dedicated thread: cross-process sqlite lock waits must not
        # stall the event loop, and it serialises connection use between
        # the poll loop and output-side sends
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"queue-{name}")

    async def _run(self, fn, *args, **kwargs):
        return await asyncio.get_running_loop().run_in_executor(
            self._executor, lambda: fn(*args, **kwargs))

    async def start(self, sink: EventSink) -> None:
        async def loop() -> None:
            while True:
                claimed = await self._run(self.queue.claim)
                if claimed is None:
                    await asyncio.sleep(self.poll_interval)
                    continue
                msg_id, data, attempt = claimed
                try:
                    ok = await sink(BindingEvent(
                        binding=self.name, data=data,
                        metadata={"messageId": msg_id, "attempt": str(attempt)},
                    ))
                except Exception:
                    logger.exception("queue %s delivery failed", self.name)
                    ok = False
                if ok:
                    await self._run(self.queue.ack, msg_id)
                elif attempt >= self.max_attempts:
                    logger.warning("dead-lettering queue message %s after %d attempts",
                                   msg_id, attempt)
                    await self._run(self.queue.dead_letter, msg_id)
                else:
                    await self._run(self.queue.nack, msg_id, delay=self.retry_delay)

        self._task = asyncio.create_task(loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # don't block the loop on a possibly busy-waiting db thread
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: self._executor.shutdown(wait=True))
        self.queue.close()

    async def invoke(self, operation: str, data: Any,
                     metadata: dict[str, str] | None = None) -> BindingResponse:
        if operation != "create":
            from tasksrunner.errors import BindingError
            raise BindingError(f"queue binding supports only create, not {operation!r}")
        msg_id = await self._run(self.queue.send, data)
        return BindingResponse(metadata={"messageId": msg_id})


#: component types served by the shared-queue-file binding — the
#: driver registration below and open_queue_for_inspection's guard
#: must always agree
QUEUE_BINDING_TYPES = ("bindings.localqueue", "bindings.azure.storagequeues")


@driver(*QUEUE_BINDING_TYPES)
def _localqueue_binding(spec: ComponentSpec, metadata: dict[str, str]) -> LocalQueueBinding:
    # `queueName` (reference metadata) maps to a db file under queuePath's
    # directory so the azure-typed component file works unchanged.
    root = metadata.get("queuePath", ".tasksrunner/queues")
    qname = metadata.get("queueName", spec.name)
    return LocalQueueBinding(
        spec.name,
        str(pathlib.Path(root) / f"{qname}.db"),
        route=metadata.get("route"),
        poll_interval=float(metadata.get("pollIntervalSeconds", 0.05)),
        max_attempts=int(metadata.get("maxRetries", 3)),
        retry_delay=float(metadata.get("retryDelaySeconds", 0.2)),
    )
