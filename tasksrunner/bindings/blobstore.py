"""Filesystem blob-store output binding.

Local stand-in for ``bindings.azure.blobstorage``
(components/dapr-bindings-out-blobstorage.yaml): the processor archives
each external task as ``{taskId}.json``
(ExternalTasksProcessorController.cs:38-43, metadata ``blobName``).
Operations: create, get, delete, list — the same set Dapr's blob
binding exposes.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any

from tasksrunner.bindings.base import BindingResponse, OutputBinding
from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import BindingError


class LocalBlobStoreBinding(OutputBinding):
    def __init__(self, name: str, root: str | pathlib.Path, *, container: str = "blobs"):
        super().__init__(name)
        self.root = pathlib.Path(root) / container
        self.root.mkdir(parents=True, exist_ok=True)

    @property
    def operations(self) -> list[str]:
        return ["create", "get", "delete", "list"]

    def _path(self, blob_name: str) -> pathlib.Path:
        p = (self.root / blob_name).resolve()
        if not p.is_relative_to(self.root.resolve()):
            raise BindingError(f"blob name {blob_name!r} escapes the container")
        return p

    async def invoke(self, operation: str, data: Any,
                     metadata: dict[str, str] | None = None) -> BindingResponse:
        metadata = metadata or {}
        if operation == "list":
            names = sorted(
                str(p.relative_to(self.root))
                for p in self.root.rglob("*") if p.is_file()
            )
            return BindingResponse(data=names)

        blob_name = metadata.get("blobName")
        if not blob_name:
            if operation == "create":
                import uuid
                blob_name = str(uuid.uuid4())
            else:
                raise BindingError(f"{operation} requires blobName metadata")
        path = self._path(blob_name)

        if operation == "create":
            path.parent.mkdir(parents=True, exist_ok=True)
            if isinstance(data, (bytes, bytearray)):
                path.write_bytes(data)
            elif isinstance(data, str):
                path.write_text(data)
            else:
                path.write_text(json.dumps(data, indent=2))
            return BindingResponse(metadata={"blobName": blob_name})
        if operation == "get":
            if not path.is_file():
                raise BindingError(f"blob {blob_name!r} does not exist")
            return BindingResponse(data=path.read_bytes(),
                                   metadata={"blobName": blob_name})
        if operation == "delete":
            existed = path.is_file()
            if existed:
                path.unlink()
            return BindingResponse(metadata={"deleted": "true" if existed else "false"})
        raise BindingError(f"blob binding has no operation {operation!r}")


@driver("bindings.localblob", "bindings.azure.blobstorage")
def _blob_binding(spec: ComponentSpec, metadata: dict[str, str]) -> LocalBlobStoreBinding:
    return LocalBlobStoreBinding(
        spec.name,
        metadata.get("blobPath", ".tasksrunner/blobs"),
        container=metadata.get("container", "blobs"),
    )
