"""Filesystem blob-store output binding.

Local stand-in for ``bindings.azure.blobstorage``
(components/dapr-bindings-out-blobstorage.yaml): the processor archives
each external task as ``{taskId}.json``
(ExternalTasksProcessorController.cs:38-43, metadata ``blobName``).
Operations: create, get, delete, list — the same set Dapr's blob
binding exposes.
"""

from __future__ import annotations

import asyncio
import json
import os
import pathlib
from typing import Any

from tasksrunner.bindings.base import BindingResponse, OutputBinding
from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import BindingError


# module-level, plain args, dispatched via run_in_executor — NOT
# per-call closures via asyncio.to_thread: to_thread copies the
# caller's contextvars Context into the work item, and an idle executor
# worker pins its last work item until the next one arrives, so every
# worker thread would retain a whole request's context (payload, span
# state); measured as real per-message retention under soak load
def _write_blob(path: str, payload: bytes) -> None:  # tasklint: off-loop
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(payload)


def _read_blob(path: str) -> bytes | None:  # tasklint: off-loop
    if not os.path.isfile(path):
        return None
    with open(path, "rb") as f:
        return f.read()


class LocalBlobStoreBinding(OutputBinding):
    def __init__(self, name: str, root: str | pathlib.Path, *, container: str = "blobs"):
        super().__init__(name)
        self.root = pathlib.Path(root) / container
        self.root.mkdir(parents=True, exist_ok=True)

    @property
    def operations(self) -> list[str]:
        return ["create", "get", "delete", "list"]

    def _path(self, blob_name: str) -> str:
        # containment check via os.path (realpath), NOT pathlib: blob
        # names are unique per task, and on CPython 3.12 (immortal
        # interned strings) pathlib's component interning (sys.intern
        # in _parse_path) retains every name for the life of the
        # process (see email.py — same leak, measured under soak;
        # other CPython versions free mortal interned strings, but the
        # hot path avoiding the parser is cheap on all of them)
        root = os.path.realpath(str(self.root))
        p = os.path.realpath(os.path.join(root, blob_name))
        if not (p == root or p.startswith(root + os.sep)):
            raise BindingError(f"blob name {blob_name!r} escapes the container")
        return p

    async def invoke(self, operation: str, data: Any,
                     metadata: dict[str, str] | None = None) -> BindingResponse:
        metadata = metadata or {}
        if operation == "list":
            # os.walk, same reason as _path: rglob + relative_to would
            # route every unique blob name through pathlib's parser
            root = str(self.root)
            names = []
            for dirpath, _dirs, files in os.walk(root):
                rel = os.path.relpath(dirpath, root)
                for fname in files:
                    names.append(fname if rel == "."
                                 else os.path.join(rel, fname))
            return BindingResponse(data=sorted(names))

        blob_name = metadata.get("blobName")
        if not blob_name:
            if operation == "create":
                import uuid
                blob_name = str(uuid.uuid4())
            else:
                raise BindingError(f"{operation} requires blobName metadata")
        path = self._path(blob_name)

        if operation == "create":
            # utf-8 explicitly (write_text used the locale encoding;
            # a deliberate, portable choice beats a host-dependent one)
            if isinstance(data, (bytes, bytearray)):
                payload = bytes(data)
            elif isinstance(data, str):
                payload = data.encode("utf-8")
            else:
                payload = json.dumps(data, indent=2).encode("utf-8")

            # disk I/O off the event loop: a slow volume must degrade
            # this one invoke, not every request in the process
            await asyncio.get_running_loop().run_in_executor(
                None, _write_blob, path, payload)
            return BindingResponse(metadata={"blobName": blob_name})
        if operation == "get":
            blob = await asyncio.get_running_loop().run_in_executor(
                None, _read_blob, path)
            if blob is None:
                raise BindingError(f"blob {blob_name!r} does not exist")
            return BindingResponse(data=blob,
                                   metadata={"blobName": blob_name})
        if operation == "delete":
            existed = os.path.isfile(path)
            if existed:
                os.unlink(path)
            return BindingResponse(metadata={"deleted": "true" if existed else "false"})
        raise BindingError(f"blob binding has no operation {operation!r}")


@driver("bindings.localblob", "bindings.azure.blobstorage")
def _blob_binding(spec: ComponentSpec, metadata: dict[str, str]) -> LocalBlobStoreBinding:
    return LocalBlobStoreBinding(
        spec.name,
        metadata.get("blobPath", ".tasksrunner/blobs"),
        container=metadata.get("container", "blobs"),
    )
