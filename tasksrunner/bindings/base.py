"""Bindings building-block interfaces.

The reference's binding taxonomy (SURVEY.md §2.4, §3.3-3.4):

* **input bindings** push external events *into* the app: the sidecar
  polls/schedules and POSTs to an app route — storage-queue messages
  route to ``/externaltasksprocessor/process``
  (components/dapr-bindings-in-storagequeue.yaml:17-18), cron fires
  POST ``/<component-name>``
  (components/dapr-scheduled-cron.yaml, ScheduledTasksManagerController.cs:20).
  Ack contract: 2xx from the handler consumes the event; non-2xx →
  redelivery (docs/aca/06-aca-dapr-bindingsapi/index.md:55-56).
* **output bindings** push app data *out*: ``invoke_binding(name,
  operation, data, metadata)`` — blob ``create``
  (ExternalTasksProcessorController.cs:38-43), sendgrid ``create``
  (docs module 6 TasksNotifierController.cs:56).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable


@dataclass
class BindingEvent:
    """What an input binding delivers to the app."""

    binding: str
    data: Any
    metadata: dict[str, str] = field(default_factory=dict)


#: App-side sink: returns True to ack (consume), False to nack (redeliver
#: where the source supports it).
EventSink = Callable[[BindingEvent], Awaitable[bool]]


@dataclass
class BindingResponse:
    """Result of an output-binding operation."""

    data: Any = None
    metadata: dict[str, str] = field(default_factory=dict)


class InputBinding(abc.ABC):
    #: The app route events are delivered to. Defaults to the component
    #: name (cron convention); queue-style bindings set it from their
    #: ``route`` metadata.
    route: str

    def __init__(self, name: str):
        self.name = name
        self.route = "/" + name
        #: set by the runtime that starts this binding; guards a shared
        #: instance against being started twice (which would orphan the
        #: first poll task)
        self.running = False

    @abc.abstractmethod
    async def start(self, sink: EventSink) -> None:
        """Begin delivering events to ``sink`` until ``stop``."""

    @abc.abstractmethod
    async def stop(self) -> None: ...


class OutputBinding(abc.ABC):
    def __init__(self, name: str):
        self.name = name

    @property
    def operations(self) -> list[str]:
        return ["create"]

    @abc.abstractmethod
    async def invoke(self, operation: str, data: Any,
                     metadata: dict[str, str] | None = None) -> BindingResponse: ...
