"""Cron input binding with a from-scratch 5-field schedule engine.

Replicates the reference's ``bindings.cron`` component
(components/dapr-scheduled-cron.yaml, schedule ``5 0 * * *``): on each
fire the sidecar POSTs an empty event to the app route named after the
component (ScheduledTasksManagerController route ``/ScheduledTasksManager``).

Field order: minute hour day-of-month month day-of-week. Supports
``*``, lists, ranges, steps (``*/15``, ``1-30/5``), month/day names,
and the standard dom/dow OR rule (if both are restricted, either match
fires). ``@every 5s``-style shorthand is also accepted for fast local
testing (Dapr's cron binding supports @every too).
"""

from __future__ import annotations

import asyncio
import datetime as dt
import logging

from tasksrunner.bindings.base import BindingEvent, EventSink, InputBinding
from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import BindingError

logger = logging.getLogger(__name__)

_MONTHS = {m: i + 1 for i, m in enumerate(
    "jan feb mar apr may jun jul aug sep oct nov dec".split())}
_DOWS = {d: i for i, d in enumerate("sun mon tue wed thu fri sat".split())}

_BOUNDS = {  # field -> (min, max)
    "minute": (0, 59),
    "hour": (0, 23),
    "dom": (1, 31),
    "month": (1, 12),
    "dow": (0, 6),
}


def _parse_field(expr: str, field: str) -> tuple[set[int], bool]:
    """Return (allowed values, was_wildcard)."""
    lo, hi = _BOUNDS[field]
    names = _MONTHS if field == "month" else _DOWS if field == "dow" else {}

    def atom(tok: str) -> int:
        tok = tok.strip().lower()
        if tok in names:
            return names[tok]
        try:
            v = int(tok)
        except ValueError:
            raise BindingError(f"bad cron {field} value {tok!r}") from None
        if field == "dow" and v == 7:  # both 0 and 7 mean Sunday
            v = 0
        if not (lo <= v <= hi):
            raise BindingError(f"cron {field} value {v} out of range {lo}-{hi}")
        return v

    allowed: set[int] = set()
    wildcard = expr.strip() == "*"
    for part in expr.split(","):
        part = part.strip()
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError:
                raise BindingError(f"bad cron step {step_s!r}") from None
            if step <= 0:
                raise BindingError(f"cron step must be positive, got {step}")
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part and not part.lstrip("-").isdigit():
            a, b = part.split("-", 1)
            start, end = atom(a), atom(b)
            if end < start:
                raise BindingError(f"inverted cron range {part!r} in {field}")
        else:
            start = end = atom(part)
            if "/" in expr and step > 1 and part != "*":
                end = hi  # "N/step" means start at N
        allowed.update(range(start, end + 1, step))
    return allowed, wildcard


class CronSchedule:
    """A parsed cron expression that can compute the next fire time."""

    def __init__(self, expr: str):
        self.expr = expr.strip()
        self.interval: float | None = None
        if self.expr.startswith("@every"):
            _, _, spec = self.expr.partition(" ")
            self.interval = _parse_duration(spec.strip())
            return
        fields = self.expr.split()
        if len(fields) == 6:
            # Dapr's cron binding accepts 6-field (with seconds). We
            # support minute granularity: only a seconds field of
            # exactly "0" is accepted and dropped; anything else
            # (including "*" = every second) would silently change the
            # schedule, so reject it (use "@every Ns" instead).
            if fields[0] != "0":
                raise BindingError(
                    f"sub-minute cron schedules are not supported "
                    f"(seconds field {fields[0]!r} in {self.expr!r}); "
                    "use '@every Ns' for sub-minute cadence"
                )
            fields = fields[1:]
        if len(fields) != 5:
            raise BindingError(
                f"cron expression needs 5 fields (minute hour dom month dow), got {self.expr!r}"
            )
        (self.minutes, _), (self.hours, _) = (
            _parse_field(fields[0], "minute"), _parse_field(fields[1], "hour"))
        self.doms, self.dom_wild = _parse_field(fields[2], "dom")
        self.months, _ = _parse_field(fields[3], "month")
        self.dows, self.dow_wild = _parse_field(fields[4], "dow")

    def matches(self, t: dt.datetime) -> bool:
        if self.interval is not None:
            raise BindingError("@every schedules have no calendar match")
        if t.minute not in self.minutes or t.hour not in self.hours:
            return False
        if t.month not in self.months:
            return False
        dom_ok = t.day in self.doms
        dow_ok = ((t.weekday() + 1) % 7) in self.dows  # python Mon=0 → cron Sun=0
        if not self.dom_wild and not self.dow_wild:
            return dom_ok or dow_ok  # standard cron OR rule
        return dom_ok and dow_ok

    def next_after(self, t: dt.datetime) -> dt.datetime:
        if self.interval is not None:
            return t + dt.timedelta(seconds=self.interval)
        candidate = t.replace(second=0, microsecond=0) + dt.timedelta(minutes=1)
        # bounded scan: four years covers any satisfiable 5-field expr
        limit = candidate + dt.timedelta(days=1462)
        while candidate <= limit:
            if self.matches(candidate):
                return candidate
            candidate += dt.timedelta(minutes=1)
        raise BindingError(f"cron expression {self.expr!r} never fires")


def _parse_duration(spec: str) -> float:
    units = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}
    for suffix in ("ms", "s", "m", "h"):
        if spec.endswith(suffix):
            try:
                return float(spec[: -len(suffix)]) * units[suffix]
            except ValueError:
                break
    raise BindingError(f"bad @every duration {spec!r} (want e.g. 500ms, 5s, 2m, 1h)")


class CronBinding(InputBinding):
    def __init__(self, name: str, schedule: str):
        super().__init__(name)
        self.schedule = CronSchedule(schedule)
        self._task: asyncio.Task | None = None

    async def start(self, sink: EventSink) -> None:
        async def loop() -> None:
            while True:
                now = dt.datetime.now()
                if self.schedule.interval is not None:
                    delay = self.schedule.interval
                else:
                    delay = (self.schedule.next_after(now) - now).total_seconds()
                await asyncio.sleep(max(delay, 0.0))
                try:
                    await sink(BindingEvent(binding=self.name, data=None,
                                            metadata={"schedule": self.schedule.expr}))
                except Exception:
                    logger.exception("cron %s delivery failed", self.name)

        self._task = asyncio.create_task(loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


@driver("bindings.cron")
def _cron_binding(spec: ComponentSpec, metadata: dict[str, str]) -> CronBinding:
    try:
        schedule = metadata["schedule"]
    except KeyError:
        raise BindingError(f"cron component {spec.name!r} needs schedule metadata") from None
    return CronBinding(spec.name, schedule)
