"""Email output binding — file-outbox engine (SendGrid stand-in).

Local stand-in for ``bindings.twilio.sendgrid``
(components/dapr-bindings-out-sendgrid.yaml): the processor sends task
notifications via ``invoke_binding("sendgrid", "create", body,
{emailTo, emailToName, subject})``
(docs/aca/06-aca-dapr-bindingsapi/TasksNotifierController.cs:38-57).
Here each send is appended as a JSON document to an outbox directory so
tests and humans can assert on "sent" mail — the same observability the
workshop gets from the SendGrid dashboard.
"""

from __future__ import annotations

import asyncio
import json
import os
import pathlib
import time
import uuid
from typing import Any

from tasksrunner.bindings.base import BindingResponse, OutputBinding
from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import BindingError


# module-level, plain args, dispatched via run_in_executor — NOT a
# per-send closure via asyncio.to_thread: to_thread copies the caller's
# contextvars Context into the work item, and an idle executor worker
# pins its last work item until the next one arrives, so every worker
# thread would retain a whole delivery's context (parsed payload, span
# state); measured as real per-message retention under soak load
def _write_mail(path: str, payload: str) -> None:  # tasklint: off-loop
    with open(path, "w", encoding="utf-8") as f:
        f.write(payload)


class EmailOutboxBinding(OutputBinding):
    def __init__(self, name: str, outbox: str | pathlib.Path, *,
                 default_from: str = "", api_key: str = ""):
        super().__init__(name)
        self.outbox = pathlib.Path(outbox)
        self.outbox.mkdir(parents=True, exist_ok=True)
        self.default_from = default_from
        self.api_key = api_key  # kept to exercise the secretRef path

    async def invoke(self, operation: str, data: Any,
                     metadata: dict[str, str] | None = None) -> BindingResponse:
        if operation != "create":
            raise BindingError(f"email binding supports only create, not {operation!r}")
        metadata = metadata or {}
        to = metadata.get("emailTo")
        if not to:
            raise BindingError("email create requires emailTo metadata")
        mail_id = str(uuid.uuid4())
        doc = {
            "id": mail_id,
            "from": metadata.get("emailFrom", self.default_from),
            "to": to,
            "toName": metadata.get("emailToName", ""),
            "subject": metadata.get("subject", ""),
            "body": data if isinstance(data, str) else json.dumps(data),
            "sentAt": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        # os.path, not pathlib, on the per-send path: pathlib interns
        # every path component (_parse_path uses sys.intern), and on
        # CPython 3.12 interned strings are immortal — unique UUID
        # filenames grew the intern table forever (~0.4 KB of retained
        # memory per sent mail, measured under soak load)
        # outbox write off the event loop: one slow disk must not
        # stall every in-flight delivery in the process
        await asyncio.get_running_loop().run_in_executor(
            None, _write_mail,
            os.path.join(str(self.outbox), f"{mail_id}.json"),
            json.dumps(doc, indent=2))
        return BindingResponse(metadata={"mailId": mail_id})

    def sent(self) -> list[dict]:
        """All mail in the outbox, oldest first (test/diagnostic API)."""
        docs = [json.loads(p.read_text()) for p in self.outbox.glob("*.json")]
        return sorted(docs, key=lambda d: d["sentAt"])


@driver("bindings.smtp", "bindings.twilio.sendgrid")
def _email_binding(spec: ComponentSpec, metadata: dict[str, str]) -> EmailOutboxBinding:
    return EmailOutboxBinding(
        spec.name,
        metadata.get("outboxPath", ".tasksrunner/outbox"),
        default_from=metadata.get("emailFrom", ""),
        api_key=metadata.get("apiKey", ""),
    )
