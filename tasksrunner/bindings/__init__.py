from tasksrunner.bindings.base import InputBinding, OutputBinding, BindingResponse
from tasksrunner.bindings.cron import CronBinding, CronSchedule
from tasksrunner.bindings.localqueue import LocalQueueBinding, SqliteQueue
from tasksrunner.bindings.blobstore import LocalBlobStoreBinding
from tasksrunner.bindings.email import EmailOutboxBinding

__all__ = [
    "InputBinding",
    "OutputBinding",
    "BindingResponse",
    "CronBinding",
    "CronSchedule",
    "LocalQueueBinding",
    "SqliteQueue",
    "LocalBlobStoreBinding",
    "EmailOutboxBinding",
]
