"""Virtual actors: single-owner placement, turns, durable reminders.

The missing Dapr building block (ROADMAP open item 2): an *actor* is a
named unit of state + behavior (``("Counter", "user-7")``) that the
runtime materializes on exactly one replica at a time. Apps register a
turn handler per actor type with ``@app.actor("Counter")``; clients
call ``client.invoke_actor(...)`` and never learn (or care) where the
actor lives. See docs/modules/18-actors.md for the model, guarantees,
and failure semantics; gated by ``TASKSRUNNER_ACTORS`` (off).
"""

from tasksrunner.actors.turn import ActorTurn
from tasksrunner.actors.runtime import ActorRuntime

__all__ = ["ActorRuntime", "ActorTurn"]
