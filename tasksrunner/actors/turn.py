"""The object an actor turn handler receives.

Kept in its own module so ``tasksrunner.app`` can build turns without
importing the actor runtime (which would cycle back through the
runtime core).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class ActorTurn:
    """One turn: the handler mutates ``state`` in place (or replaces
    it) and returns a JSON-serializable result for the caller. The
    owning replica commits ``state`` with an etag-guarded write AFTER
    the handler returns — the turn is acked only once that commit
    resolves, which is what makes an ack durable across a crash."""

    actor_type: str
    actor_id: str
    #: invoked method name; for reminder turns this is the reminder name
    method: str
    data: Any = None
    state: dict = field(default_factory=dict)
    #: "turn" for client invocations, "reminder" for scheduled firings
    kind: str = "turn"
    #: reminder name when kind == "reminder"
    reminder: str | None = None

    @property
    def is_reminder(self) -> bool:
        return self.kind == "reminder"
