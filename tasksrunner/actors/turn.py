"""The object an actor turn handler receives.

Kept in its own module so ``tasksrunner.app`` can build turns without
importing the actor runtime (which would cycle back through the
runtime core).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class ActorTurn:
    """One turn: the handler mutates ``state`` in place (or replaces
    it) and returns a JSON-serializable result for the caller. The
    owning replica commits ``state`` with an etag-guarded write AFTER
    the handler returns — the turn is acked only once that commit
    resolves, which is what makes an ack durable across a crash.

    Beyond ``state``, a handler may stage two further kinds of change
    that ride the SAME etag-guarded commit:

    * **effects** (:meth:`stage_effect`) — writes to other keys in the
      actor store, applied in one transaction with the record. A fenced
      zombie loses the whole transaction, so an effect is applied
      exactly once per acked turn — the primitive the workflow engine's
      exactly-once activity contract is built on.
    * **reminder changes** (:meth:`set_reminder` /
      :meth:`clear_reminder`) — folded into the record's reminder table
      before the commit, so a turn and the schedule it arms (or
      disarms) are atomic: no crash window between them.
    """

    actor_type: str
    actor_id: str
    #: invoked method name; for reminder turns this is the reminder name
    method: str
    data: Any = None
    state: dict = field(default_factory=dict)
    #: "turn" for client invocations, "reminder" for scheduled firings
    kind: str = "turn"
    #: reminder name when kind == "reminder"
    reminder: str | None = None
    #: staged state ops committed atomically with the record
    effects: list = field(default_factory=list)
    #: staged reminder registrations / removals (name → spec / names)
    reminder_sets: dict = field(default_factory=dict)
    reminder_clears: list = field(default_factory=list)

    @property
    def is_reminder(self) -> bool:
        return self.kind == "reminder"

    def stage_effect(self, key: str, value: Any = None, *,
                     operation: str = "upsert") -> None:
        """Stage a write to ``key`` in the actor store, committed in
        one transaction with this turn's record write (and therefore
        fenced together with it)."""
        if operation not in ("upsert", "delete"):
            raise ValueError(f"unknown effect operation {operation!r}")
        self.effects.append(
            {"operation": operation, "key": key, "value": value})

    def set_reminder(self, name: str, due_seconds: float, *,
                     period_seconds: float | None = None,
                     data: Any = None) -> None:
        """Register (or replace) a reminder atomically with this turn."""
        self.reminder_clears = [n for n in self.reminder_clears if n != name]
        self.reminder_sets[name] = {
            "dueSeconds": float(due_seconds),
            "periodSeconds": period_seconds,
            "data": data,
        }

    def clear_reminder(self, name: str) -> None:
        """Remove a reminder atomically with this turn."""
        self.reminder_sets.pop(name, None)
        if name not in self.reminder_clears:
            self.reminder_clears.append(name)
