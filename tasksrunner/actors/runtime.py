"""The per-replica virtual-actor runtime: placement, turns, reminders.

Three durable records per actor, all in the app's actor state store
(single-key etag-guarded writes, so they stay atomic on the sharded
state plane — a record never spans shards):

* ``actor-rec||{type}||{id}`` — ``{"epoch", "data", "reminders"}``.
  The actor's state AND its reminder table in one record: a turn's
  state writes and its reminder changes commit in one etag-guarded
  ``set``, atomically with the turn.
* ``actor-place||{type}||{id}`` — the placement entry: owner identity
  (replica token, pid, host, sidecar port, registration time), the
  fencing epoch, and the lease expiry. Exactly one owner per actor id;
  everyone else forwards.
* ``actor-index||{type}`` — the id directory the failover sweep scans.

**Fencing.** Every ownership acquisition bumps the epoch with an
etag-guarded write to the actor record, which invalidates the previous
owner's cached etag. A zombie — a replica that lost its lease mid-turn
or crashed without releasing it — therefore fails its next commit with
``EtagMismatch``, surfaced as :class:`ActorFencedError`; the turn was
never acked, so the caller retries against the new owner. Acks happen
strictly after the commit resolves: a 2xx-acked turn is durable, full
stop. Ownership races (two replicas acquiring concurrently) are
likewise resolved by the etag chain — at most one of any two
conflicting commits can land.

**Liveness.** An owner is considered dead when its lease expired OR
``NameResolver.local_pid_dead`` says so — the ``/proc`` starttime
check closes the pid-recycling window, so a recycled pid cannot
impersonate a dead owner, and a live-but-wedged owner is still fenced
out once its lease lapses. No ghost passes both tests.

**Reminders.** Durable, re-armed on ownership acquisition: the sweep
loop (``TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS``) renews leases for
owned actors, fires due reminders (the due-time update commits in the
same record write as the handler's state changes — exactly-once per
schedule at the state level), and adopts actors with reminders whose
owner died, which is what makes failover automatic rather than
operator-driven.
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import json
import logging
import os
import time
from typing import Any

from tasksrunner.errors import (
    ActorError,
    ActorFencedError,
    ActorNotRegistered,
    EtagMismatch,
    TasksRunnerError,
)
from tasksrunner.invoke.resolver import NameResolver
from tasksrunner.observability.metrics import metrics
from tasksrunner.observability.spans import active as spans_active, record_span
from tasksrunner.observability.tracing import (
    BAGGAGE_HEADER,
    TRACEPARENT_HEADER,
    TraceContext,
    current_or_new,
    serialize_baggage,
    trace_scope,
)
from tasksrunner.security import TOKEN_ENV, TOKEN_HEADER

logger = logging.getLogger(__name__)

#: in-process forwarding table (replica token → ActorRuntime): an
#: InProcCluster's replicas route turns to each other through here;
#: hosted replicas advertise a sidecar address in the placement record
#: instead. A crashed runtime removes itself — exactly like a dead
#: process stops answering its port.
_LOCAL_REPLICAS: dict[str, "ActorRuntime"] = {}

_REPLICA_SEQ = itertools.count()

DEFAULT_LEASE_SECONDS = 30.0
DEFAULT_POLL_SECONDS = 2.0
DEFAULT_TURN_TIMEOUT = 30.0


def _env_seconds(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r; using %s", name, raw, default)
        return default


def record_key(actor_type: str, actor_id: str) -> str:
    return f"actor-rec||{actor_type}||{actor_id}"


def place_key(actor_type: str, actor_id: str) -> str:
    return f"actor-place||{actor_type}||{actor_id}"


def index_key(actor_type: str) -> str:
    return f"actor-index||{actor_type}"


class _Activation:
    """One locally-owned actor: its turn lock and cached etags."""

    __slots__ = ("lock", "etag", "place_etag", "epoch", "data",
                 "reminders", "lease_expires")

    def __init__(self, *, etag: str, place_etag: str, epoch: int,
                 data: dict, reminders: dict, lease_expires: float):
        self.lock = asyncio.Lock()  # turns run one-at-a-time per actor
        self.etag = etag
        self.place_etag = place_etag
        self.epoch = epoch
        self.data = data
        self.reminders = reminders
        self.lease_expires = lease_expires


class ActorRuntime:
    """Everything actor-shaped on one replica. Built by
    ``Runtime.start()`` when ``TASKSRUNNER_ACTORS`` is on and the app
    registered at least one ``@app.actor`` handler; absent otherwise,
    so the gate-off path carries no per-request cost."""

    def __init__(self, runtime: Any, actor_types: list[str], *,
                 store_name: str | None = None,
                 crash_on_chaos: bool = False):
        self.runtime = runtime
        self.types = sorted(actor_types)
        self.store = store_name or self._pick_store()
        self.lease_seconds = _env_seconds(
            "TASKSRUNNER_ACTOR_LEASE_SECONDS", DEFAULT_LEASE_SECONDS)
        self.poll_seconds = _env_seconds(
            "TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS", DEFAULT_POLL_SECONDS)
        self.turn_timeout = _env_seconds(
            "TASKSRUNNER_ACTOR_TURN_TIMEOUT_SECONDS", DEFAULT_TURN_TIMEOUT)
        #: drill switch: a chaos fault injected into a turn also kills
        #: this runtime (stops renewals, leaves leases dangling) so a
        #: seeded crashEveryN rule exercises real crash-failover —
        #: see the chaos drill in tests/test_actors.py and module 16
        self.crash_on_chaos = crash_on_chaos
        self.crashed = False
        self.replica_id = (f"{runtime.app_id or 'app'}"
                           f"@{os.getpid()}.{next(_REPLICA_SEQ)}")
        self._registered_at = time.time()
        self._activations: dict[tuple[str, str], _Activation] = {}
        self._sweep_task: asyncio.Task | None = None
        self._session = None  # outbound forwards to peer sidecars
        self._rec_turn: dict[str, Any] = {}
        #: async callbacks ``(actor_type, actor_id, method, result)``
        #: invoked after a reminder-driven turn commits — how the
        #: workflow runtime learns an adopted instance made progress
        #: and needs pumping (a direct invoke already returns its
        #: result to the caller; reminder results die here otherwise)
        self.turn_observers: list = []

    # -- lifecycle -------------------------------------------------------

    async def start(self) -> None:
        _LOCAL_REPLICAS[self.replica_id] = self
        self._sweep_task = asyncio.create_task(self._sweep_loop())
        logger.info("actor runtime %s hosting %s (lease %.1fs, poll %.1fs)",
                    self.replica_id, self.types, self.lease_seconds,
                    self.poll_seconds)

    async def stop(self) -> None:
        """Graceful shutdown: release every lease (keeping the epoch,
        so the next owner still fences above us) — failover after a
        clean stop is immediate, not lease-expiry-bounded."""
        if self._sweep_task is not None:
            self._sweep_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._sweep_task
            self._sweep_task = None
        _LOCAL_REPLICAS.pop(self.replica_id, None)
        now = time.time()
        for (atype, aid), act in list(self._activations.items()):
            release = {"owner": self._identity(), "epoch": act.epoch,
                       "lease_expires": 0.0, "granted_at": now,
                       "released": True}
            try:
                await self.runtime.save_state_item(
                    self.store, place_key(atype, aid), release,
                    etag=act.place_etag)
            except TasksRunnerError:
                pass  # already re-placed — nothing to release
        self._activations.clear()
        if self._session is not None:
            await self._session.close()
            self._session = None

    def simulate_crash(self) -> None:
        """Test/drill hook: die the way SIGKILL dies — stop sweeping,
        stop answering, release NOTHING. Leases dangle until expiry;
        in-flight turns keep running and hit the fence at commit (the
        zombie scenario the epoch exists for)."""
        self.crashed = True
        _LOCAL_REPLICAS.pop(self.replica_id, None)
        if self._sweep_task is not None:
            self._sweep_task.cancel()
            self._sweep_task = None

    # -- store / identity ------------------------------------------------

    def _pick_store(self) -> str:
        """The Dapr convention: the state component marked with
        metadata ``actorStateStore: "true"`` holds actor state; fall
        back to a component named ``statestore``, then to the only
        state component if there is exactly one."""
        names = list(self.runtime.registry.names(block="state"))
        for name in names:
            raw = self.runtime.registry.spec(name).metadata.get("actorStateStore")
            if str(raw).lower() == "true":
                return name
        if "statestore" in names:
            return "statestore"
        if len(names) == 1:
            return names[0]
        raise ActorError(
            "no actor state store: mark one state component with "
            'metadata actorStateStore: "true" '
            f"(state components: {names or 'none'})")

    def _identity(self) -> dict:
        addr = getattr(self.runtime, "actor_address", None)
        return {
            "replica": self.replica_id,
            "app_id": self.runtime.app_id,
            "host": addr[0] if addr else "127.0.0.1",
            "sidecar_port": addr[1] if addr else None,
            "pid": os.getpid(),
            "registered_at": self._registered_at,
        }

    @staticmethod
    def owner_dead(place_doc: dict, now: float | None = None) -> bool:
        """The takeover predicate: lease expired, or the owner's pid is
        provably gone. ``local_pid_dead`` includes the /proc starttime
        pid-recycling check, so a recycled pid cannot keep a dead
        owner's lease alive — and a live owner inside its lease is
        never preempted, however wedged it looks."""
        now = time.time() if now is None else now
        if float(place_doc.get("lease_expires", 0.0)) <= now:
            return True
        owner = place_doc.get("owner") or {}
        return NameResolver.local_pid_dead(
            owner.get("host"), owner.get("pid"), owner.get("registered_at"))

    # -- public operations -----------------------------------------------

    async def invoke_turn(self, actor_type: str, actor_id: str, method: str,
                          data: Any = None, *, forwarded: bool = False) -> Any:
        """Run one turn; returns the handler's result AFTER the turn's
        state commit resolved (the ack-after-commit contract)."""
        act_or_route = await self._resolve_owner(actor_type, actor_id,
                                                 forwarded=forwarded)
        if not isinstance(act_or_route, _Activation):
            return await self._forward_turn(act_or_route, actor_type,
                                            actor_id, method, data)
        return await self._execute_turn(
            act_or_route, actor_type, actor_id, method=method, data=data,
            kind="turn", reminder_name=None)

    async def register_reminder(self, actor_type: str, actor_id: str,
                                name: str, *, due_seconds: float,
                                period_seconds: float | None = None,
                                data: Any = None,
                                forwarded: bool = False) -> None:
        """Persist a reminder beside the actor's state (same record,
        same etag-guarded commit). Re-registering replaces."""
        target = await self._resolve_owner(actor_type, actor_id,
                                           forwarded=forwarded)
        if not isinstance(target, _Activation):
            body = {"dueSeconds": due_seconds,
                    "periodSeconds": period_seconds, "data": data}
            await self._forward_reminder(target, actor_type, actor_id, name,
                                         "POST", body)
            return
        act = target
        async with act.lock:
            reminders = dict(act.reminders)
            reminders[name] = {"due": time.time() + max(0.0, due_seconds),
                               "period": period_seconds, "data": data}
            await self._commit(act, actor_type, actor_id,
                               new_data=act.data, new_reminders=reminders)

    async def unregister_reminder(self, actor_type: str, actor_id: str,
                                  name: str, *, forwarded: bool = False) -> None:
        target = await self._resolve_owner(actor_type, actor_id,
                                           forwarded=forwarded)
        if not isinstance(target, _Activation):
            await self._forward_reminder(target, actor_type, actor_id, name,
                                         "DELETE", None)
            return
        act = target
        async with act.lock:
            if name not in act.reminders:
                return
            reminders = dict(act.reminders)
            reminders.pop(name)
            await self._commit(act, actor_type, actor_id,
                               new_data=act.data, new_reminders=reminders)

    async def read_state(self, actor_type: str, actor_id: str) -> dict:
        """Diagnostic read of the durable record (any replica may
        serve it — it is a plain state read, not a turn)."""
        item = await self.runtime.get_state(
            self.store, record_key(actor_type, actor_id))
        if item is None:
            return {"epoch": 0, "data": {}, "reminders": {}}
        return item.value

    # -- ownership resolution --------------------------------------------

    async def _resolve_owner(self, actor_type: str, actor_id: str, *,
                             forwarded: bool):
        if self.crashed:
            raise ActorError(
                f"actor runtime {self.replica_id} is down (crashed)")
        if actor_type not in self.types:
            raise ActorNotRegistered(
                f"no actor type {actor_type!r} on app "
                f"{self.runtime.app_id!r} (hosted: {self.types})")
        act = self._activations.get((actor_type, actor_id))
        if act is not None:
            if act.lease_expires > time.time():
                return act
            # our lease lapsed (a stalled sweep, a paused process):
            # drop the activation and re-walk placement — if nobody
            # took over we re-acquire (bumping OUR own epoch, which is
            # harmless); if somebody did, we forward
            self._deactivate(actor_type, actor_id)
        return await self._activate(actor_type, actor_id, forwarded=forwarded)

    def _locality_rank(self, actor_type: str, actor_id: str) -> float:
        """Affinity of THIS replica for the actor's backing shard
        (elastic placement, PR 20): 1.0 when the local member leads the
        shard holding the actor's record (or the store has no placement
        map at all), 0.0 when another host owns it. Used only to bias
        placement races — never to refuse an activation."""
        try:
            store, prefixer = self.runtime._state_store(self.store)
        except Exception:
            return 1.0
        rank_of = getattr(store, "locality_rank", None)
        if rank_of is None:
            return 1.0
        return float(rank_of(prefixer.apply(record_key(actor_type, actor_id))))

    async def _activate(self, actor_type: str, actor_id: str, *,
                        forwarded: bool):
        """Walk the placement table: forward to a live owner, or take
        (or retake) ownership — bumping the fencing epoch — when the
        record is free, released, or its owner is dead.

        Placement races are locality-biased: a replica that does NOT
        host the actor's backing shard yields a beat before claiming,
        so the shard-local replica usually wins the CAS and actor turns
        commit without a cross-host state hop."""
        deferred = False
        for _ in range(4):
            now = time.time()
            place = await self.runtime.get_state(
                self.store, place_key(actor_type, actor_id))
            takeover = False
            if place is not None:
                doc = place.value
                owner = doc.get("owner") or {}
                if owner.get("replica") != self.replica_id:
                    if not self.owner_dead(doc, now):
                        if forwarded:
                            # hop guard: a forwarded call never forwards
                            # again — placement moved mid-flight, the
                            # origin retries against the fresh table
                            raise ActorError(
                                f"actor {actor_type}/{actor_id} moved "
                                "while forwarding; retry")
                        return doc
                    takeover = not doc.get("released", False)
                epoch = int(doc.get("epoch", 0)) + 1
                place_etag = place.etag
            else:
                epoch = 1
                place_etag = None
            if not deferred and not forwarded and (
                    place is None or takeover):
                deferred = True  # one yield per activation, not per loop
                rank = self._locality_rank(actor_type, actor_id)
                if rank < 1.0:
                    # lose the race on purpose: if the shard-local
                    # replica claims during this nap our CAS below
                    # fails and the next pass forwards to it
                    await asyncio.sleep(0.05 * (1.0 - rank))
                    continue
            lease_expires = now + self.lease_seconds
            new_place = {"owner": self._identity(), "epoch": epoch,
                         "lease_expires": lease_expires, "granted_at": now}
            try:
                new_place_etag = await self.runtime.save_state_item(
                    self.store, place_key(actor_type, actor_id), new_place,
                    etag=place_etag)
            except EtagMismatch:
                continue  # lost the race — re-read and re-decide
            if place_etag is None:
                # first-activation create is unguarded (no etag to CAS
                # on), so two replicas can both "win" the write. Read
                # back: the store's last write is the truth.
                check = await self.runtime.get_state(
                    self.store, place_key(actor_type, actor_id))
                if check is None or (check.value.get("owner") or {}).get(
                        "replica") != self.replica_id:
                    continue
                new_place_etag = check.etag
            act = await self._fence_record(actor_type, actor_id, epoch,
                                           new_place_etag, lease_expires)
            if act is None:
                continue
            await self._index_add(actor_type, actor_id)
            self._activations[(actor_type, actor_id)] = act
            if takeover:
                metrics.inc("actor_failover_total", type=actor_type)
                logger.warning("actor %s/%s failed over to %s (epoch %d)",
                               actor_type, actor_id, self.replica_id, epoch)
            return act
        raise ActorError(
            f"could not place actor {actor_type}/{actor_id}: placement "
            "contention; retry")

    async def _fence_record(self, actor_type: str, actor_id: str, epoch: int,
                            place_etag: str, lease_expires: float):
        """Write the new epoch into the actor record BEFORE serving any
        turn. This is the fence: it rotates the record's etag, so every
        etag the previous owner cached is now stale and its in-flight
        commit lands in :class:`ActorFencedError` instead of state."""
        rec = await self.runtime.get_state(
            self.store, record_key(actor_type, actor_id))
        for _ in range(4):
            if rec is None:
                value = {"epoch": epoch, "data": {}, "reminders": {}}
                etag = None
            else:
                value = dict(rec.value)
                value["epoch"] = epoch
                etag = rec.etag
            try:
                new_etag = await self.runtime.save_state_item(
                    self.store, record_key(actor_type, actor_id), value,
                    etag=etag)
            except EtagMismatch:
                # a zombie's last commit slipped in between our read
                # and our bump — legitimate (it still held the etag
                # chain); absorb its write and fence on top of it
                rec = await self.runtime.get_state(
                    self.store, record_key(actor_type, actor_id))
                continue
            return _Activation(
                etag=new_etag, place_etag=place_etag, epoch=epoch,
                data=value.get("data") or {},
                reminders=value.get("reminders") or {},
                lease_expires=lease_expires)
        return None

    async def _index_add(self, actor_type: str, actor_id: str) -> None:
        key = index_key(actor_type)
        for _ in range(8):
            item = await self.runtime.get_state(self.store, key)
            ids = list((item.value.get("ids") or [])) if item is not None else []
            if actor_id in ids:
                return
            doc = {"ids": sorted({*ids, actor_id})}
            try:
                await self.runtime.save_state_item(
                    self.store, key, doc,
                    etag=item.etag if item is not None else None)
            except EtagMismatch:
                continue
            if item is not None:
                return
            # unguarded create: verify a concurrent creator didn't
            # overwrite us, else loop and merge into their record
            check = await self.runtime.get_state(self.store, key)
            if check is not None and actor_id in (check.value.get("ids") or []):
                return
        logger.warning("actor index %s: gave up adding %s under contention",
                       actor_type, actor_id)

    async def _index_ids(self, actor_type: str) -> list[str]:
        item = await self.runtime.get_state(self.store, index_key(actor_type))
        if item is None:
            return []
        return list(item.value.get("ids") or [])

    def _deactivate(self, actor_type: str, actor_id: str) -> None:
        self._activations.pop((actor_type, actor_id), None)

    # -- turn execution --------------------------------------------------

    def _chaos_policy(self, actor_type: str):
        chaos = getattr(self.runtime, "chaos", None)
        if chaos is None:
            return None
        return chaos.for_actor(actor_type)

    async def _execute_turn(self, act: _Activation, actor_type: str,
                            actor_id: str, *, method: str, data: Any,
                            kind: str, reminder_name: str | None) -> Any:
        rec_latency = self._rec_turn.get(actor_type)
        if rec_latency is None:
            rec_latency = self._rec_turn[actor_type] = metrics.recorder(
                "actor_turn_latency_seconds", type=actor_type)
        # the turn gets its own span as a child of the caller's context
        # (the sidecar ingress span, a forward hop, or a reminder root);
        # with recording off this whole lane costs one bool test
        turn_ctx = current_or_new().child() if spans_active() else None
        async with act.lock:
            started = time.perf_counter()
            wall_started = time.time()
            policy = self._chaos_policy(actor_type)
            if policy is not None:
                # the fault fires HERE, on the owning replica, inside
                # the turn — which is what lets a crashEveryN rule
                # target "whoever currently owns this actor type"
                try:
                    status = await policy.before_call()
                except BaseException:
                    if self.crash_on_chaos:
                        self.simulate_crash()
                    metrics.inc("actor_turns_total", type=actor_type,
                                status="chaos")
                    raise
                if status is not None:
                    policy.raise_for_status(status)
            payload = json.dumps({
                "data": data, "state": act.data, "kind": kind,
                "reminder": reminder_name,
            }).encode()
            headers = {"content-type": "application/json"}
            scope = contextlib.nullcontext()
            if turn_ctx is not None:
                # the app channel adopts this header in _handle_actor,
                # so the handler's ACTOR span nests under the turn span
                headers[TRACEPARENT_HEADER] = turn_ctx.header
                bag = serialize_baggage(turn_ctx.baggage)
                if bag:
                    headers[BAGGAGE_HEADER] = bag
                scope = trace_scope(turn_ctx)
            turn_status = 500
            try:
                with scope:
                    try:
                        status, _, body = await asyncio.wait_for(
                            self.runtime.app_channel.request(
                                "PUT",
                                f"/tasksrunner/actors/{actor_type}/{actor_id}/{method}",
                                headers=headers,
                                body=payload),
                            timeout=self.turn_timeout)
                    except asyncio.TimeoutError:
                        metrics.inc("actor_turns_total", type=actor_type,
                                    status="timeout")
                        raise ActorError(
                            f"actor {actor_type}/{actor_id}.{method} exceeded the "
                            f"{self.turn_timeout}s turn timeout "
                            "(TASKSRUNNER_ACTOR_TURN_TIMEOUT_SECONDS)") from None
                    if status >= 300:
                        metrics.inc("actor_turns_total", type=actor_type,
                                    status="error")
                        detail = body[:200].decode("utf-8", "replace")
                        turn_status = status
                        raise ActorError(
                            f"actor {actor_type}/{actor_id}.{method} failed "
                            f"({status}): {detail}")
                    doc = json.loads(body) if body else {}
                    new_state = doc.get("state")
                    if not isinstance(new_state, dict):
                        new_state = {}
                    reminders = dict(act.reminders)
                    if kind == "reminder" and reminder_name is not None:
                        rem = reminders.get(reminder_name)
                        if rem is not None:
                            if rem.get("period"):
                                rem = dict(rem)
                                rem["due"] = time.time() + float(rem["period"])
                                reminders[reminder_name] = rem
                            else:
                                reminders.pop(reminder_name)
                    # staged reminder changes land AFTER the fired-reminder
                    # re-arm/pop above, so a handler re-setting (or clearing)
                    # the very reminder that fired wins over the default
                    now = time.time()
                    for rname, spec in (doc.get("reminders_set") or {}).items():
                        reminders[rname] = {
                            "due": now + max(0.0, float(spec.get("dueSeconds", 0.0))),
                            "period": spec.get("periodSeconds"),
                            "data": spec.get("data"),
                        }
                    for rname in doc.get("reminders_clear") or []:
                        reminders.pop(rname, None)
                    await self._commit(act, actor_type, actor_id,
                                       new_data=new_state, new_reminders=reminders,
                                       effects=doc.get("effects") or None)
                    turn_status = 200
            finally:
                if turn_ctx is not None:
                    record_span(
                        kind="server",
                        name=f"actor-turn {actor_type}/{method}",
                        status=turn_status, start=wall_started,
                        duration=time.perf_counter() - started,
                        attrs={"actor": f"{actor_type}/{actor_id}",
                               "turn_kind": kind},
                        span_id=turn_ctx.span_id,
                        parent_id=turn_ctx.parent_id)
            rec_latency(time.perf_counter() - started)
            metrics.inc("actor_turns_total", type=actor_type, status="ok")
            if kind == "reminder":
                metrics.inc("actor_reminder_fired_total", type=actor_type)
            return doc.get("result")

    async def _commit(self, act: _Activation,  # tasklint: fenced-lane
                      actor_type: str, actor_id: str, *, new_data: dict,
                      new_reminders: dict,
                      effects: list | None = None) -> None:
        """The only writer of the actor record — etag-guarded, called
        with the turn lock held. Success is the precondition for the
        ack; EtagMismatch means we were fenced.

        With ``effects`` the record write and every staged effect go
        through ONE store transaction guarded by the record's etag: a
        fenced zombie loses the whole transaction, so effects inherit
        the record's exactly-once-per-acked-turn guarantee."""
        record = {"epoch": act.epoch, "data": new_data,
                  "reminders": new_reminders}
        rkey = record_key(actor_type, actor_id)
        try:
            if not effects:
                act.etag = await self.runtime.save_state_item(
                    self.store, rkey, record, etag=act.etag)
            else:
                ops = [{"operation": "upsert",
                        "request": {"key": rkey, "value": record,
                                    "etag": act.etag}}]
                for eff in effects:
                    req: dict[str, Any] = {"key": str(eff["key"])}
                    if eff.get("operation", "upsert") == "upsert":
                        req["value"] = eff.get("value")
                    ops.append({"operation": eff.get("operation", "upsert"),
                                "request": req})
                await self.runtime.transact_state(self.store, ops)
        except EtagMismatch as exc:
            self._deactivate(actor_type, actor_id)
            metrics.inc("actor_fenced_total", type=actor_type)
            metrics.inc("actor_turns_total", type=actor_type, status="fenced")
            raise ActorFencedError(
                f"actor {actor_type}/{actor_id}: commit fenced — a newer "
                f"owner bumped past epoch {act.epoch}; this turn was NOT "
                "applied (retry against the new owner)") from exc
        if effects:
            # transact returns no etag; read back and adopt it — but
            # only while the record still carries OUR epoch. Epochs are
            # unique per ownership generation, so an epoch mismatch
            # means a new owner fenced in between and the etag we'd
            # adopt is theirs, not ours.
            check = await self.runtime.get_state(self.store, rkey)
            # monotone fence: epochs only grow (every takeover bumps
            # through the etag CAS in _fence_record), so a record that
            # no longer carries OUR epoch can only carry a HIGHER one —
            # ``>`` is the exact fencedness test, and unlike ``!=`` it
            # cannot misread a lower epoch (impossible on a consistent
            # read) as a fence
            if check is None or int(check.value.get("epoch", -1)) > act.epoch:
                self._deactivate(actor_type, actor_id)
                raise ActorFencedError(
                    f"actor {actor_type}/{actor_id}: fenced right after an "
                    f"effectful commit (epoch {act.epoch} superseded); the "
                    "turn WAS applied but this owner is done")
            act.etag = check.etag
        act.data = new_data
        act.reminders = new_reminders

    # -- forwarding ------------------------------------------------------

    async def _forward_turn(self, owner: dict, actor_type: str,
                            actor_id: str, method: str, data: Any) -> Any:
        peer = _LOCAL_REPLICAS.get((owner.get("owner") or {}).get("replica"))
        odoc = owner.get("owner") or {}
        # the forward hop is a client span; the owner's turn span (and
        # its ACTOR handler span) nest under it — in-proc via the
        # ambient scope, cross-process via the traceparent header on
        # the x-tasksrunner-actor-forward request
        fwd_ctx = current_or_new().child() if spans_active() else None
        scope = (trace_scope(fwd_ctx) if fwd_ctx is not None
                 else contextlib.nullcontext())
        started = time.time()
        fwd_status = 500
        try:
            with scope:
                if peer is not None:
                    result = await peer.invoke_turn(
                        actor_type, actor_id, method, data, forwarded=True)
                    fwd_status = 200
                    return result
                if odoc.get("sidecar_port"):
                    path = (f"/v1.0/actors/{actor_type}/{actor_id}"
                            f"/method/{method}")
                    status, body = await self._http_forward(
                        odoc, "PUT", path, None if data is None else data,
                        trace_ctx=fwd_ctx)
                    fwd_status = status
                    if status == 409:
                        raise ActorFencedError(
                            f"actor {actor_type}/{actor_id}: owner fenced the "
                            "forwarded turn; retry")
                    if status >= 300:
                        raise ActorError(
                            f"forwarded turn to {odoc.get('replica')} failed "
                            f"({status}): {body[:200].decode('utf-8', 'replace')}")
                    doc = json.loads(body) if body else {}
                    return doc.get("result")
                raise ActorError(
                    f"actor {actor_type}/{actor_id} is owned by "
                    f"{odoc.get('replica')!r} which is unreachable from here; "
                    "retry (ownership moves when its lease expires)")
        finally:
            if fwd_ctx is not None:
                record_span(
                    kind="client",
                    name=f"actor-forward {actor_type}/{method}",
                    status=fwd_status, start=started,
                    duration=time.time() - started,
                    attrs={"target": odoc.get("replica"),
                           "actor": f"{actor_type}/{actor_id}"},
                    span_id=fwd_ctx.span_id, parent_id=fwd_ctx.parent_id)

    async def _forward_reminder(self, owner: dict, actor_type: str,
                                actor_id: str, name: str, http_method: str,
                                body: Any) -> None:
        odoc = owner.get("owner") or {}
        peer = _LOCAL_REPLICAS.get(odoc.get("replica"))
        if peer is not None:
            if http_method == "POST":
                await peer.register_reminder(
                    actor_type, actor_id, name,
                    due_seconds=body["dueSeconds"],
                    period_seconds=body.get("periodSeconds"),
                    data=body.get("data"), forwarded=True)
            else:
                await peer.unregister_reminder(actor_type, actor_id, name,
                                               forwarded=True)
            return
        if odoc.get("sidecar_port"):
            path = f"/v1.0/actors/{actor_type}/{actor_id}/reminders/{name}"
            status, resp = await self._http_forward(odoc, http_method, path, body)
            if status >= 300:
                raise ActorError(
                    f"forwarded reminder op to {odoc.get('replica')} failed "
                    f"({status}): {resp[:200].decode('utf-8', 'replace')}")
            return
        raise ActorError(
            f"actor {actor_type}/{actor_id} is owned by "
            f"{odoc.get('replica')!r} which is unreachable from here; retry")

    async def _http_forward(self, owner: dict, http_method: str, path: str,
                            body: Any, *,
                            trace_ctx: TraceContext | None = None,
                            ) -> tuple[int, bytes]:
        if self._session is None:
            import aiohttp
            self._session = aiohttp.ClientSession()
        headers = {"content-type": "application/json",
                   "x-tasksrunner-actor-forward": "1"}
        if trace_ctx is not None:
            headers[TRACEPARENT_HEADER] = trace_ctx.header
            bag = serialize_baggage(trace_ctx.baggage)
            if bag:
                headers[BAGGAGE_HEADER] = bag
        token = os.environ.get(TOKEN_ENV)
        if token:
            headers[TOKEN_HEADER] = token
        url = (f"http://{owner.get('host')}:{owner.get('sidecar_port')}{path}")
        try:
            async with self._session.request(
                    http_method, url, headers=headers,
                    data=None if body is None else json.dumps(body)) as resp:
                return resp.status, await resp.read()
        except OSError as exc:
            raise ActorError(
                f"owner sidecar unreachable at {url}: {exc} "
                "(retry; ownership moves when its lease expires)") from exc

    # -- sweep: lease renewal, reminders, failover -----------------------

    async def _sweep_loop(self) -> None:
        while not self.crashed:
            await asyncio.sleep(self.poll_seconds)
            try:
                await self.sweep()
            except asyncio.CancelledError:
                raise
            except Exception:  # tasklint: disable=error-taxonomy (sweep)
                logger.exception("actor sweep failed on %s", self.replica_id)

    async def sweep(self) -> dict:
        """One control-loop pass. Exposed for tests and the drill, so
        they can step the loop deterministically instead of sleeping."""
        stats = {"renewed": 0, "fired": 0, "adopted": 0}
        now = time.time()
        # 1. renew leases on everything we own; losing the CAS means a
        # new owner fenced us while we slept — drop the activation
        for (atype, aid), act in list(self._activations.items()):
            if self.crashed:
                return stats
            renewal = {"owner": self._identity(), "epoch": act.epoch,
                       "lease_expires": now + self.lease_seconds,
                       "granted_at": now}
            try:
                act.place_etag = await self.runtime.save_state_item(
                    self.store, place_key(atype, aid), renewal,
                    etag=act.place_etag)
                act.lease_expires = now + self.lease_seconds
                stats["renewed"] += 1
            except EtagMismatch:
                self._deactivate(atype, aid)
        # 2. fire due reminders on owned actors
        for (atype, aid), act in list(self._activations.items()):
            if self.crashed:
                return stats
            stats["fired"] += await self._fire_due(atype, aid, act)
        # 3. adopt actors with reminders whose owner is dead — the
        # automatic-failover half of the durability story (actors
        # without reminders re-place lazily, on their next invoke)
        for atype in self.types:
            for aid in await self._index_ids(atype):
                if self.crashed:
                    return stats
                if (atype, aid) in self._activations:
                    continue
                place = await self.runtime.get_state(
                    self.store, place_key(atype, aid))
                if place is None or not self.owner_dead(place.value):
                    continue
                rec = await self.runtime.get_state(
                    self.store, record_key(atype, aid))
                if rec is None or not rec.value.get("reminders"):
                    continue
                if (atype, aid) in self._activations:
                    # a concurrent invoke activated it while the state
                    # reads above suspended — adopting now would
                    # double-activate on top of the live turn
                    continue
                try:
                    adopted = await self._activate(atype, aid, forwarded=False)
                except TasksRunnerError as exc:
                    logger.warning("adopting %s/%s failed: %s", atype, aid, exc)
                    continue
                if isinstance(adopted, _Activation):
                    stats["adopted"] += 1
                    stats["fired"] += await self._fire_due(atype, aid, adopted)
        counts: dict[str, int] = {}
        for (atype, _aid) in self._activations:
            counts[atype] = counts.get(atype, 0) + 1
        for atype in self.types:
            metrics.set_gauge("actor_owned", counts.get(atype, 0), type=atype)
        return stats

    async def _fire_due(self, actor_type: str, actor_id: str,
                        act: _Activation) -> int:
        fired = 0
        now = time.time()
        for name, rem in sorted(act.reminders.items()):
            if float(rem.get("due", 0.0)) > now:
                continue
            try:
                # a reminder turn has no caller — it roots a fresh
                # trace (workflow drive turns re-attach to the durable
                # instance trace inside the engine)
                scope = (trace_scope(TraceContext.new()) if spans_active()
                         else contextlib.nullcontext())
                with scope:
                    result = await self._execute_turn(
                        act, actor_type, actor_id, method=name,
                        data=rem.get("data"), kind="reminder",
                        reminder_name=name)
                fired += 1
                for observer in self.turn_observers:
                    try:
                        await observer(actor_type, actor_id, name, result)
                    except Exception:  # tasklint: disable=error-taxonomy (observer)
                        logger.exception(
                            "turn observer failed after reminder %s on "
                            "%s/%s", name, actor_type, actor_id)
            except ActorFencedError:
                return fired  # lost the actor mid-sweep; the new owner fires
            except TasksRunnerError as exc:
                # a failing handler must not wedge the sweep; the due
                # time is unchanged, so it retries next pass
                logger.warning("reminder %s on %s/%s failed: %s",
                               name, actor_type, actor_id, exc)
        return fired

    # -- introspection ---------------------------------------------------

    def summary(self) -> dict:
        """Cheap local view for ``/v1.0/metadata`` and ``ps``."""
        owned: dict[str, int] = {}
        for (atype, _aid) in self._activations:
            owned[atype] = owned.get(atype, 0) + 1
        return {"types": self.types, "replica": self.replica_id,
                "owned": owned, "crashed": self.crashed,
                "lease_seconds": self.lease_seconds}

    async def placement_table(self) -> list[dict]:
        """The global placement table, rendered from the shared store
        (any replica computes the same view). One row per actor id."""
        rows: list[dict] = []
        now = time.time()
        for atype in self.types:
            for aid in await self._index_ids(atype):
                place = await self.runtime.get_state(
                    self.store, place_key(atype, aid))
                if place is None:
                    continue
                doc = place.value
                owner = doc.get("owner") or {}
                rows.append({
                    "type": atype,
                    "id": aid,
                    "owner": owner.get("replica"),
                    "owner_app": owner.get("app_id"),
                    "host": owner.get("host"),
                    "sidecar_port": owner.get("sidecar_port"),
                    "pid": owner.get("pid"),
                    "epoch": doc.get("epoch"),
                    "lease_age": round(
                        max(0.0, now - float(doc.get("granted_at", now))), 3),
                    "lease_expires_in": round(
                        float(doc.get("lease_expires", 0.0)) - now, 3),
                    "alive": not self.owner_dead(doc, now),
                    "owned_here": (atype, aid) in self._activations,
                })
        return rows
