"""Per-replica admission control: shed load before the replica collapses.

An overloaded replica that keeps accepting work converts overload into
timeouts, connection resets, and (worst) acknowledged-then-lost writes
when the process finally dies. This controller samples the saturation
signals module 08 already publishes — event-loop lag, the state/broker
write-queue depths, and the app's in-flight request count — folds them
into one score, and flips the replica into *shedding* when the score
crosses 1.0. While shedding, non-exempt HTTP requests are answered
``429`` with a ``Retry-After`` derived from the score instead of being
queued; health, metrics, and admin/metadata endpoints stay open so
probes and the autoscaler never go blind exactly when they matter.

Two design points keep this safe:

* **Hysteresis.** Shedding starts at score >= 1.0 but only stops below
  ``exit_ratio`` (default 0.75). Without the band the controller flaps
  at the threshold — admit a burst, saturate, shed, drain, admit —
  turning one overload into a square wave of them.
* **Zero cost when off.** The ``TASKSRUNNER_ADMISSION`` gate decides at
  construction time: :meth:`AdmissionController.from_env` returns
  ``None`` and the request paths guard on ``admission is not None``,
  so the disabled path costs one identity check (the chaos-gate bar of
  <1%, proven by ``bench.py --overload-bench``).

The score is the max of the per-signal ratios (a replica is as
saturated as its worst resource): ``lag / max_lag``, worst write-queue
``depth / max_depth``, ``inflight / max_inflight``, plus any ratios
subsystems contributed through :func:`register_signal` (the ML
batcher's tokens-in-flight signal rides this). Thresholds come
from ``TASKSRUNNER_ADMISSION_MAX_*``; setting one to 0 disables that
signal. Shedding state and the raw score are published as
``admission_state`` / ``admission_saturation`` gauges and every shed
request increments ``admission_shed_total`` — the drill in
``tests/test_overload_drill.py`` asserts the whole trajectory off the
``/metrics`` exposition.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import math
import os
from typing import Callable

from tasksrunner.envflag import env_flag
from tasksrunner.observability import flightrec
from tasksrunner.observability.metrics import MetricsRegistry, metrics as default_metrics

logger = logging.getLogger(__name__)

#: loop-lag gauge sampled from the registry (set by EventLoopLagProbe)
LAG_GAUGE = "event_loop_lag_seconds"
#: write-queue depth gauges; the worst series across all label sets
#: (per store / per shard / per broker) counts
QUEUE_GAUGES = ("state_write_queue_depth", "broker_publish_queue_depth")

DEFAULT_INTERVAL = 0.25
#: shedding stops only when the score drops below this fraction of the
#: entry threshold — the hysteresis band that prevents flapping
DEFAULT_EXIT_RATIO = 0.75

DEFAULT_MAX_LAG_SECONDS = 0.25
DEFAULT_MAX_QUEUE_DEPTH = 512
DEFAULT_MAX_INFLIGHT = 64

#: Retry-After is ceil(score) seconds — deeper saturation pushes
#: clients further away — clamped to this ceiling so a pathological
#: score can't park clients for minutes
MAX_RETRY_AFTER_SECONDS = 30


#: extra saturation signals registered by subsystems the controller
#: can't know about up front (e.g. the ML batcher's tokens-in-flight
#: ratio). Each is a zero-arg callable returning a ratio on the same
#: scale as the built-in signals: >= 1.0 means saturated. Process-wide
#: by design — AppHost shares one controller between the app server
#: and the sidecar, and a subsystem registering here must not need a
#: handle on either.
_EXTRA_SIGNALS: dict[str, Callable[[], float]] = {}


def register_signal(name: str, fn: Callable[[], float]) -> None:
    """Fold ``fn()`` into every subsequent :meth:`AdmissionController.sample`
    as one more saturation ratio (the score is the max across signals).
    Re-registering a name replaces the previous callable."""
    _EXTRA_SIGNALS[name] = fn


def unregister_signal(name: str) -> None:
    _EXTRA_SIGNALS.pop(name, None)


def _env_number(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r; using %s", name, raw, default)
        return default


class AdmissionController:
    """Saturation sampler + hysteresis gate for one replica.

    The hot path reads :attr:`shedding` (a plain bool attribute — no
    lock, no call) and, when shedding, :meth:`retry_after_seconds`.
    The sampling loop runs as an asyncio task owned by the sidecar,
    mirroring :class:`~tasksrunner.observability.probes.EventLoopLagProbe`.
    """

    def __init__(
        self,
        *,
        max_lag_seconds: float = DEFAULT_MAX_LAG_SECONDS,
        max_queue_depth: float = DEFAULT_MAX_QUEUE_DEPTH,
        max_inflight: float = DEFAULT_MAX_INFLIGHT,
        inflight: Callable[[], int] | None = None,
        interval: float = DEFAULT_INTERVAL,
        exit_ratio: float = DEFAULT_EXIT_RATIO,
        registry: MetricsRegistry | None = None,
    ) -> None:
        self.max_lag_seconds = max_lag_seconds
        self.max_queue_depth = max_queue_depth
        self.max_inflight = max_inflight
        self.inflight = inflight
        self.interval = interval
        self.exit_ratio = exit_ratio
        self.registry = registry if registry is not None else default_metrics
        self.shedding = False
        self.score = 0.0
        self._task: asyncio.Task | None = None
        self._publish()

    @classmethod
    def from_env(
        cls,
        *,
        inflight: Callable[[], int] | None = None,
        registry: MetricsRegistry | None = None,
    ) -> AdmissionController | None:
        """The gate: ``None`` unless ``TASKSRUNNER_ADMISSION`` is on."""
        if not env_flag("TASKSRUNNER_ADMISSION", default=False):
            return None
        return cls(
            max_lag_seconds=_env_number(
                "TASKSRUNNER_ADMISSION_MAX_LAG_SECONDS", DEFAULT_MAX_LAG_SECONDS),
            max_queue_depth=_env_number(
                "TASKSRUNNER_ADMISSION_MAX_QUEUE_DEPTH", DEFAULT_MAX_QUEUE_DEPTH),
            max_inflight=_env_number(
                "TASKSRUNNER_ADMISSION_MAX_INFLIGHT", DEFAULT_MAX_INFLIGHT),
            inflight=inflight,
            registry=registry,
        )

    # -- scoring ---------------------------------------------------------

    def sample(self) -> float:
        """Recompute the score and apply the hysteresis transition.

        Called from the sampling task; also callable directly by tests
        (and anything else that just changed a signal and can't wait an
        interval).
        """
        score = 0.0
        if self.max_lag_seconds > 0:
            lag = self.registry.get(LAG_GAUGE)
            score = max(score, lag / self.max_lag_seconds)
        if self.max_queue_depth > 0:
            for name in QUEUE_GAUGES:
                for depth in self.registry.gauge_values(name):
                    score = max(score, depth / self.max_queue_depth)
        if self.max_inflight > 0 and self.inflight is not None:
            score = max(score, self.inflight() / self.max_inflight)
        for name, fn in list(_EXTRA_SIGNALS.items()):
            try:
                score = max(score, float(fn()))
            except Exception:  # pragma: no cover - buggy signal providers
                logger.exception("admission: extra signal %s failed", name)
        self.score = score
        if not self.shedding and score >= 1.0:
            self.shedding = True
            logger.warning(
                "admission: shedding (saturation %.2f >= 1.0; "
                "Retry-After %ds)", score, self.retry_after_seconds())
            # shed entry is a black-box moment: dump the flight
            # recorder's ring so the lead-up to the trip survives
            flightrec.dump("admission-shed", {"score": score})
        elif self.shedding and score < self.exit_ratio:
            self.shedding = False
            logger.info(
                "admission: admitting again (saturation %.2f < %.2f)",
                score, self.exit_ratio)
        self._publish()
        return score

    def retry_after_seconds(self) -> int:
        """Back clients off proportionally to how saturated we are."""
        return max(1, min(MAX_RETRY_AFTER_SECONDS, math.ceil(self.score)))

    def _publish(self) -> None:
        self.registry.set_gauge("admission_state", 1.0 if self.shedding else 0.0)
        self.registry.set_gauge("admission_saturation", self.score)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                self.sample()
            except Exception:  # pragma: no cover - registry bugs only
                logger.exception("admission: sampler failed; retrying")
