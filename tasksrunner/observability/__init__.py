from tasksrunner.observability.tracing import TraceContext, current_trace, trace_scope
from tasksrunner.observability.logging import configure_logging, service_logger
from tasksrunner.observability.metrics import (
    Histogram,
    MetricsRegistry,
    estimate_percentile,
    merge_histogram_snapshots,
    metrics,
    render_prometheus,
)
from tasksrunner.observability.probes import EventLoopLagProbe

__all__ = [
    "TraceContext",
    "current_trace",
    "trace_scope",
    "configure_logging",
    "service_logger",
    "Histogram",
    "MetricsRegistry",
    "metrics",
    "estimate_percentile",
    "merge_histogram_snapshots",
    "render_prometheus",
    "EventLoopLagProbe",
]
