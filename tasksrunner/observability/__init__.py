from tasksrunner.observability.tracing import TraceContext, current_trace, trace_scope
from tasksrunner.observability.logging import configure_logging, service_logger
from tasksrunner.observability.metrics import MetricsRegistry, metrics

__all__ = [
    "TraceContext",
    "current_trace",
    "trace_scope",
    "configure_logging",
    "service_logger",
    "MetricsRegistry",
    "metrics",
]
