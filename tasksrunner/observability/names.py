"""The single registry of every metric name the runtime emits.

Time series fork silently: a typo'd name or an instrument-kind switch
("publish" as a counter here, a gauge there) produces two series that
dashboards and the autoscaler then disagree about. Every
``metrics.inc`` / ``set_gauge`` / ``observe`` call site must use a name
declared here — ``scripts/check_metrics.py`` (``make lint-metrics``)
greps the instrumentation sites and fails the build on an undeclared
name, and ``MetricsRegistry`` refuses at registration time to reuse
one name across two instrument kinds.

Latency histograms follow the Prometheus convention of naming the unit
(``*_seconds``); the exposition route derives ``_bucket``/``_sum``/
``_count`` series from them.
"""

from __future__ import annotations

#: monotonically increasing event counts
COUNTERS: dict[str, str] = {
    "state_save": "state items written via the runtime",
    "state_get": "state point reads via the runtime",
    "state_delete": "state deletes via the runtime",
    "state_bulk_get": "keys fetched via bulk state reads",
    "state_query": "state query executions",
    "state_transact": "state transactions",
    "publish": "messages published",
    "pubsub_delivery": "pub/sub deliveries to app routes, by status",
    "binding_invoke": "output-binding invocations",
    "binding_delivery": "input-binding deliveries to app routes, by status",
    "invoke": "service invocations issued, by target app",
    "invoke_transport": "invocation attempts per transport lane (mesh/http)",
    "mesh_frames_total": "mesh frames moved, by direction (in/out)",
    "admission_shed_total": "requests shed with 429 by admission control",
    "chaos_injected_total": "faults injected by the chaos engine",
    "resiliency_retry_total": "resiliency-policy retry attempts",
    "resiliency_retry_exhausted_total": "retry budgets exhausted",
    "actor_turns_total": "actor turns executed, by type and status",
    "actor_reminder_fired_total": "durable reminders fired, by actor type",
    "actor_fenced_total": "zombie-owner commits rejected by epoch fencing",
    "actor_failover_total": "ownership acquisitions from a dead or expired owner",
    "repl_records_total": "replication records shipped to followers, per member",
    "ml_batches_total": "micro-batches executed by the inference plane, per bucket",
    "ml_shed_total": "inference submits shed because the batch queue was full",
    "repl_fenced_total": "shard-leader sessions fenced by an epoch bump",
    "repl_failover_total": "shard leadership takeovers (epoch > 1 acquisitions)",
    "placement_flips_total": "routing-epoch flips committed by fenced handoffs, per store",
    "placement_keys_moved_total": "keys streamed between shards by live migration/split, per store",
    "placement_stale_routes_total": "state requests 409-redirected for a stale routing epoch, per store",
    "workflow_started_total": "workflow instances started, by workflow",
    "workflow_completed_total": "workflow instances reaching a terminal status, by workflow and status",
    "workflow_activity_total": "workflow activity executions, by activity and status",
    "workflow_compensation_total": "saga compensations fired, by workflow",
    "workflow_replays_total": "orchestrator replays executed, by workflow",
}

#: point-in-time levels (the saturation probes live here)
GAUGES: dict[str, str] = {
    "uptime_seconds": "seconds since this registry was created",
    "admission_state": "admission controller state (0 admitting / 1 shedding)",
    "admission_saturation": "saturation score (>= 1.0 trips shedding)",
    "autoscale_desired_replicas": "replica count the autoscaler last computed",
    "resiliency_breaker_state": "circuit breaker state (0 closed/2 open)",
    "event_loop_lag_seconds": "asyncio timer drift sampled per process",
    "mesh_pool_connections": "live pooled mesh connections, per process",
    "state_write_queue_depth": "pending writes in the state group-commit queue",
    "broker_publish_queue_depth": "pending publishes in the broker write queue",
    "broker_dlq_depth": "dead-lettered messages per topic/group",
    "span_buffer_depth": "spans buffered in the recorder awaiting flush",
    "actor_owned": "actor activations this replica currently owns, per type",
    "repl_epoch": "current shard leadership epoch, per store and shard",
    "repl_follower_lag_records": "records a follower trails the leader by",
    "placement_epoch": "current routing-table epoch, per store",
    "shard_heat": "EWMA write rate (ops/s), per store and shard",
    "placement_pause_seconds": "write-pause length of the last fenced flip, per store",
    "ml_queue_depth": "inference requests waiting for micro-batch assembly",
    "ml_tokens_in_flight": "tokens queued or executing in the inference plane",
}

#: latency distributions (seconds); exposed as _bucket/_sum/_count
HISTOGRAMS: dict[str, str] = {
    "sidecar_request_latency_seconds": "sidecar HTTP API handling, per route",
    "invoke_latency_seconds": "service invocation client, per target app",
    "mesh_dial_latency_seconds": "mesh connection dial + codec negotiation",
    "mesh_frame_bytes": "mesh frame size on the wire, by direction (in/out)",
    "state_op_latency_seconds": "runtime state operations, per store and op",
    "state_queue_wait_seconds": "group-commit queue wait (enqueue to batch start)",
    "state_commit_seconds": "group-commit batch execution (begin to resolve)",
    "publish_latency_seconds": "pub/sub publish, per pubsub and topic",
    "delivery_latency_seconds": "pub/sub delivery to the app, per route",
    "binding_latency_seconds": "output-binding invocation, per binding and op",
    "binding_delivery_latency_seconds": "input-binding delivery, per binding",
    "actor_turn_latency_seconds": "actor turn execution, per actor type",
    "ml_batch_size": "assembled micro-batch size (before bucket padding)",
    "ml_queue_wait_seconds": "inference queue wait (submit to batch start), per bucket",
    "ml_infer_latency_seconds": "micro-batch device execution, per padding bucket",
    "workflow_activity_latency_seconds": "workflow activity execution, per activity",
    "workflow_history_events": "history length at workflow commit, per workflow",
}

ALL: dict[str, str] = {**COUNTERS, **GAUGES, **HISTOGRAMS}

#: allowed ``kind=`` values on ``record_span`` — the OpenTelemetry
#: span-kind vocabulary plus "internal" for in-process stages
SPAN_KINDS: frozenset[str] = frozenset(
    {"server", "client", "producer", "consumer", "internal"})

#: canonical span-name prefixes. Span names are "<prefix> <target>"
#: (the dynamic suffix names the app/topic/actor/store); the lint in
#: ``analysis/rules/metricnames.py`` checks the literal first token of
#: every ``record_span(name=...)`` site against this table so trace
#: trees don't fork on a typo'd lane name. HTTP server spans whose
#: whole name is dynamic ("GET /api/tasks") are exempt — no literal to
#: check.
SPAN_NAMES: dict[str, str] = {
    "invoke": "service invocation client span, per target app + path",
    "publish": "pub/sub producer span, per pubsub/topic",
    "ACTOR": "app-channel actor turn handler (app-side server span)",
    "actor-turn": "owner-side actor turn execution (server span)",
    "actor-forward": "caller-to-owner forward hop (client span)",
    "workflow-turn": "workflow scheduling turn on the instance trace",
    "workflow-activity": "workflow activity attempt, per activity",
    "workflow-compensation": "saga compensation execution",
    "workflow-timer": "durable workflow timer wait",
    "state-write": "group-commit state write (queue-wait/service split)",
    "repl-ship": "leader-to-follower record batch ship (producer span)",
    "repl-apply": "follower apply of a shipped record batch (consumer span)",
    "repl-ack": "ack-quorum completion for a committed record batch",
    "ml-batch": "micro-batch device execution, per padding bucket",
    "ml-request": "one queued inference request (queue-wait/occupancy split)",
}
