"""W3C trace-context propagation across invoke/pubsub/binding hops.

The reference gets distributed tracing from the App Insights SDK plus
sidecar telemetry (SURVEY.md §5.1): one logical operation (create task
→ state write → publish → processor handle) renders as a single
transaction across three services. Here the same capability is carried
by ``traceparent`` headers: generated at the first ingress, propagated
through every sidecar hop and into pub/sub message metadata, and
attached to structured logs so logs from all services correlate.
"""

from __future__ import annotations

import contextvars
from dataclasses import dataclass, field
from urllib.parse import quote, unquote

from tasksrunner.ids import hex8, hex16

TRACEPARENT_HEADER = "traceparent"
BAGGAGE_HEADER = "baggage"

#: W3C baggage caps — the header must not grow hop over hop, so both
#: the item count and the serialized size are bounded; excess entries
#: are dropped oldest-insertion-first at serialization time
MAX_BAGGAGE_ITEMS = 16
MAX_BAGGAGE_BYTES = 1024


@dataclass(slots=True, eq=False)
class TraceContext:
    """Treat as immutable: contexts are shared across tasks (the
    ambient contextvar, span buffers, message metadata), so never
    assign to a field — construct a new context (see ``set_baggage``).
    Not ``frozen=True``: one of these is built on EVERY traced hop and
    the frozen init's object.__setattr__ round-trips double its cost.
    """

    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars
    flags: str = "01"
    #: the span this one descends from (the wire parent after
    #: ensure_trace, or the local parent after child()) — what lets the
    #: span viewer reassemble the tree
    parent_id: str | None = None
    #: cross-cutting key/values that ride the trace (serialized as the
    #: W3C ``baggage`` header on outbound hops, capped — see
    #: serialize_baggage)
    baggage: dict = field(default_factory=dict)

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(trace_id=hex16(), span_id=hex8())

    @classmethod
    def parse(cls, header: str | None,
              baggage: dict | None = None) -> "TraceContext | None":
        if not header:
            return None
        parts = header.split("-")
        if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        return cls(trace_id=parts[1], span_id=parts[2], flags=parts[3],
                   baggage=baggage or {})

    def child(self) -> "TraceContext":
        # hot path (2-3 children per handled request): explicit
        # construction is ~3x cheaper than dataclasses.replace. The
        # field list is pinned by test_child_preserves_all_fields —
        # adding a TraceContext field fails that test until it is
        # propagated here.
        return TraceContext(trace_id=self.trace_id, span_id=hex8(),
                            flags=self.flags, parent_id=self.span_id,
                            baggage=self.baggage)

    @property
    def header(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"


_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "tasksrunner_trace", default=None
)


def current_trace() -> TraceContext | None:
    return _current.get()


def parse_baggage(header: str | None) -> dict:
    """Decode a W3C ``baggage`` header (``k=v,k2=v2``) into a dict.

    Malformed items are skipped, never raised — a peer's bad header
    must not fail the request it rode in on. Item count is capped on
    the way in so a hostile header cannot grow the context."""
    if not header:
        return {}
    out: dict = {}
    for item in header.split(","):
        key, sep, value = item.partition("=")
        key = key.strip()
        if not sep or not key:
            continue
        out[key] = unquote(value.strip())
        if len(out) >= MAX_BAGGAGE_ITEMS:
            break
    return out


def serialize_baggage(baggage: dict) -> str | None:
    """Encode baggage for the wire, dropping entries past the size cap."""
    if not baggage:
        return None
    parts: list[str] = []
    size = 0
    for key, value in baggage.items():
        item = f"{key}={quote(str(value), safe='')}"
        if size + len(item) + 1 > MAX_BAGGAGE_BYTES:
            break
        parts.append(item)
        size += len(item) + 1
    return ",".join(parts) or None


def ensure_trace(incoming_header: str | None = None,
                 baggage_header: str | None = None) -> TraceContext:
    """Adopt the incoming context (new child span) or start a new trace."""
    bag = parse_baggage(baggage_header) if baggage_header else {}
    if incoming_header:
        # inline parse + child in ONE construction — this runs on every
        # traced hop, and a frozen-dataclass init is the dominant cost
        parts = incoming_header.split("-")
        if len(parts) == 4 and len(parts[1]) == 32 and len(parts[2]) == 16:
            ctx = TraceContext(trace_id=parts[1], span_id=hex8(),
                               flags=parts[3], parent_id=parts[2],
                               baggage=bag)
            _current.set(ctx)
            return ctx
    if bag:
        ctx = TraceContext(trace_id=hex16(), span_id=hex8(), baggage=bag)
    else:
        ctx = TraceContext.new()
    _current.set(ctx)
    return ctx


def set_baggage(key: str, value: str) -> TraceContext:
    """Attach one baggage entry to the active context (installing a
    root context first when none is active)."""
    ctx = current_or_new()
    bag = dict(ctx.baggage)
    bag[key] = value
    ctx = TraceContext(trace_id=ctx.trace_id, span_id=ctx.span_id,
                       flags=ctx.flags, parent_id=ctx.parent_id, baggage=bag)
    _current.set(ctx)
    return ctx


class trace_scope:
    """Install ``ctx`` as the ambient trace for the with-block.

    A ``__slots__`` class, not a ``@contextmanager`` — this wraps every
    traced hop and the generator-based protocol costs ~4x as much as
    the set/reset it would be guarding."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: TraceContext):
        self._ctx = ctx

    def __enter__(self) -> TraceContext:
        self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> None:
        _current.reset(self._token)


def current_or_new() -> TraceContext:
    """The active context, creating (and installing) a root if absent."""
    ctx = current_trace()
    if ctx is None:
        ctx = TraceContext.new()
        _current.set(ctx)
    return ctx


def outgoing_headers() -> dict[str, str]:
    """Headers to attach to an outbound hop (child span of current)."""
    ctx = current_or_new()
    headers = {TRACEPARENT_HEADER: ctx.child().header}
    bag = serialize_baggage(ctx.baggage)
    if bag:
        headers[BAGGAGE_HEADER] = bag
    return headers
