"""W3C trace-context propagation across invoke/pubsub/binding hops.

The reference gets distributed tracing from the App Insights SDK plus
sidecar telemetry (SURVEY.md §5.1): one logical operation (create task
→ state write → publish → processor handle) renders as a single
transaction across three services. Here the same capability is carried
by ``traceparent`` headers: generated at the first ingress, propagated
through every sidecar hop and into pub/sub message metadata, and
attached to structured logs so logs from all services correlate.
"""

from __future__ import annotations

import contextvars
from contextlib import contextmanager
from dataclasses import dataclass, field

from tasksrunner.ids import hex8, hex16

TRACEPARENT_HEADER = "traceparent"


@dataclass(frozen=True)
class TraceContext:
    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars
    flags: str = "01"
    #: the span this one descends from (the wire parent after
    #: ensure_trace, or the local parent after child()) — what lets the
    #: span viewer reassemble the tree
    parent_id: str | None = None
    #: spans recorded locally under this trace (exported via /v1.0/metadata)
    baggage: dict = field(default_factory=dict)

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(trace_id=hex16(), span_id=hex8())

    @classmethod
    def parse(cls, header: str | None) -> "TraceContext | None":
        if not header:
            return None
        parts = header.split("-")
        if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        return cls(trace_id=parts[1], span_id=parts[2], flags=parts[3])

    def child(self) -> "TraceContext":
        # hot path (2-3 children per handled request): explicit
        # construction is ~3x cheaper than dataclasses.replace. The
        # field list is pinned by test_child_preserves_all_fields —
        # adding a TraceContext field fails that test until it is
        # propagated here.
        return TraceContext(trace_id=self.trace_id, span_id=hex8(),
                            flags=self.flags, parent_id=self.span_id,
                            baggage=self.baggage)

    @property
    def header(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"


_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "tasksrunner_trace", default=None
)


def current_trace() -> TraceContext | None:
    return _current.get()


def ensure_trace(incoming_header: str | None = None) -> TraceContext:
    """Adopt the incoming context (new child span) or start a new trace."""
    ctx = TraceContext.parse(incoming_header)
    ctx = ctx.child() if ctx else TraceContext.new()
    _current.set(ctx)
    return ctx


@contextmanager
def trace_scope(ctx: TraceContext):
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def current_or_new() -> TraceContext:
    """The active context, creating (and installing) a root if absent."""
    ctx = current_trace()
    if ctx is None:
        ctx = TraceContext.new()
        _current.set(ctx)
    return ctx


def outgoing_headers() -> dict[str, str]:
    """Headers to attach to an outbound hop (child span of current)."""
    return {TRACEPARENT_HEADER: current_or_new().child().header}
