"""In-process metrics: counters, gauges, and latency histograms.

The reference's metrics (CPU/memory/replica counts, request rates,
percentile latencies) come from the platform + App Insights (SURVEY.md
§5.5); the framework-level equivalents here are maintained per sidecar
process and exported three ways:

* raw counters/gauges + histogram bucket arrays via ``/v1.0/metadata``
  (what the orchestrator admin and ``tasksrunner metrics`` merge
  across replicas — bucket arrays with identical bounds add
  element-wise, so cross-replica percentiles are exact up to bucket
  resolution),
* Prometheus text exposition via the sidecar's ``GET /metrics`` route
  (:func:`render_prometheus`),
* trace exemplars: an observation slower than
  ``TASKSRUNNER_SLOW_THRESHOLD_SECONDS`` captures the current trace id
  so ``tasksrunner metrics --slow`` can hand the tail straight to
  ``tasksrunner traces show``.

Histograms use fixed log-spaced bounds (100µs · 2^i). The hot path
never touches the bucket arrays: an observation is one lock-free
append onto the series' packed pending buffer (plus a float compare
for the exemplar threshold), and buffers fold into buckets in batches
— sort the pending values, then one ``bisect`` per *bound* instead of
one per value. That is the same shape as the rest of the runtime's
hot paths (group-commit writes, the span buffer): enqueue cheap,
aggregate in bulk. Per-request call sites go one step further and
cache a :meth:`MetricsRegistry.recorder` closure so they skip the
name/label resolution entirely. ``TASKSRUNNER_HISTOGRAMS=0`` turns
every entry point into an early return; ``bench.py --hist-bench``
measures the on/off delta.

Every metric name must be declared in :mod:`tasksrunner.observability.names`
(enforced by ``scripts/check_metrics.py``), and one name may only ever
be used as one instrument kind — the registry raises on a kind
collision instead of letting two series shadow each other.
"""

from __future__ import annotations

import os
import threading
import time
from array import array
from bisect import bisect_right
from typing import Any, Iterable

from tasksrunner.envflag import env_flag
from tasksrunner.observability.tracing import current_trace

ENV_HISTOGRAMS = "TASKSRUNNER_HISTOGRAMS"
ENV_SLOW_THRESHOLD = "TASKSRUNNER_SLOW_THRESHOLD_SECONDS"

#: 100µs .. ~105s in factor-of-2 steps; the +Inf overflow bucket is implicit
#: (len(bounds)+1 counts slots). Identical everywhere, so snapshots merge.
DEFAULT_BOUNDS: tuple[float, ...] = tuple(1e-4 * 2.0**i for i in range(21))

#: newest-N exemplar ring per label set
MAX_EXEMPLARS = 8

DEFAULT_SLOW_THRESHOLD = 0.25

#: optional observer for slow-threshold crossings, set by the flight
#: recorder (``observability/flightrec.py``) so a slow exemplar also
#: snapshots the black-box ring. A module global (not an import) keeps
#: metrics free of a flightrec dependency and costs one ``is None``
#: test on the already-rare slow branch.
on_slow_exemplar = None


def set_on_slow_exemplar(hook) -> None:
    """Install (or clear) the slow-exemplar observer.

    Callers must use this instead of assigning the global through an
    imported module object: the observability package ``__init__``
    rebinds the name ``metrics`` to the registry singleton, so both
    ``from tasksrunner.observability import metrics`` AND
    ``import tasksrunner.observability.metrics as m`` hand back the
    *instance* (PEP 328 submodule-attribute precedence) — an
    assignment there lands on the registry, and exemplar capture,
    which reads this module's global, never sees it."""
    global on_slow_exemplar
    on_slow_exemplar = hook

#: fold a series' pending buffer into its bucket array once it holds
#: this many raw values (snapshots fold whatever is left). Sized to
#: keep the resident cost of an un-scraped series small — ~512 floats
#: is ~12 KiB worst case — while the sort+bisect fold cost stays
#: amortised well under the <3% histogram-overhead budget.
FOLD_AT = 512


def _slow_threshold() -> float:
    raw = os.environ.get(ENV_SLOW_THRESHOLD)
    if not raw:
        return DEFAULT_SLOW_THRESHOLD
    try:
        return float(raw)
    except ValueError:
        return DEFAULT_SLOW_THRESHOLD


class _HistogramSeries:
    """Bucket counts + pending buffer + exemplars for one label set."""

    __slots__ = ("counts", "sum", "count", "pending", "exemplars")

    def __init__(self, n_buckets: int) -> None:
        self.counts = [0] * n_buckets
        self.sum = 0.0
        self.count = 0
        # raw observations awaiting a fold; appended lock-free (append
        # is atomic under the GIL), drained head-first by _fold. Never
        # rebound — recorder closures hold a direct reference. A packed
        # double array, not a list: an idle (un-scraped) series then
        # retains 8 bytes per pending value instead of a boxed float +
        # pointer, which keeps per-process residency trivial even with
        # many live series.
        self.pending: array = array("d")
        # (trace_id, value, unix_time) newest last, capped at MAX_EXEMPLARS
        self.exemplars: list[tuple[str, float, float]] = []


class Histogram:
    """Fixed-bound latency histogram with per-label-set bucket arrays.

    One instance per metric name; label sets materialise series lazily.
    The bounds are shared process-wide (``DEFAULT_BOUNDS``) so snapshots
    from different replicas merge by element-wise addition.

    Observations append to a per-series pending buffer; :meth:`_fold`
    turns a buffer into bucket increments in one pass — sort the
    values (C-speed), then bisect once per *bound* and add the
    position deltas. Folds run when a buffer reaches ``FOLD_AT`` and
    at snapshot time, so scrapes always see up-to-date buckets.
    """

    __slots__ = ("name", "bounds", "_series", "_lock")

    def __init__(self, name: str, bounds: tuple[float, ...] = DEFAULT_BOUNDS) -> None:
        self.name = name
        self.bounds = bounds
        self._series: dict[tuple[tuple[str, str], ...], _HistogramSeries] = {}
        self._lock = threading.Lock()

    def _fold(self, series: _HistogramSeries) -> None:
        """Drain ``series.pending`` into the bucket array.

        Appenders never take the lock, so only the head of the pending
        list is drained: the copy + del-slice pair below each run as a
        single C call under the GIL, and appends racing with the fold
        land at the tail, surviving for the next fold. Folders
        serialise on the histogram lock.
        """
        with self._lock:
            raw = series.pending[:]
            if not raw:
                return
            del series.pending[:len(raw)]
            vals = sorted(raw)
            n = len(vals)
            counts = series.counts
            prev = 0
            for i, bound in enumerate(self.bounds):
                pos = bisect_right(vals, bound)
                if pos != prev:
                    counts[i] += pos - prev
                    prev = pos
                if pos == n:
                    break
            if prev != n:
                counts[len(self.bounds)] += n - prev
            series.sum += sum(vals)
            series.count += n

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            items = list(self._series.items())
        for _, s in items:
            if s.pending:
                self._fold(s)
        with self._lock:
            series = [
                {
                    "labels": dict(key),
                    "counts": list(s.counts),
                    "sum": s.sum,
                    "count": s.count,
                    "exemplars": [list(e) for e in s.exemplars],
                }
                for key, s in sorted(items)
                # a recorder() materialises its series eagerly; hide it
                # until something is actually observed
                if s.count
            ]
        return {"bounds": list(self.bounds), "series": series}


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple[tuple[str, str], ...]], float] = {}
        self._gauges: dict[tuple[str, tuple[tuple[str, str], ...]], float] = {}
        self._histograms: dict[str, Histogram] = {}
        # name -> instrument kind; snapshot() injects uptime, so its kind
        # is claimed up front.
        self._kinds: dict[str, str] = {"uptime_seconds": "gauge"}
        self.started_at = time.time()
        self.histograms_enabled = env_flag(ENV_HISTOGRAMS, default=True)
        self.slow_threshold = _slow_threshold()

    def _claim_kind(self, name: str, kind: str) -> None:
        # caller holds self._lock
        prev = self._kinds.get(name)
        if prev is None:
            self._kinds[name] = kind
        elif prev != kind:
            raise ValueError(
                f"metric {name!r} already registered as {prev}, cannot reuse as {kind}"
            )

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._claim_kind(name, "counter")
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._claim_kind(name, "gauge")
            self._gauges[key] = value

    def _materialize_histogram(self, name: str) -> Histogram:
        with self._lock:
            self._claim_kind(name, "histogram")
            hist = self._histograms.get(name)
            if hist is None:
                hist = self._histograms[name] = Histogram(name)
        return hist

    def _series_for(
        self, name: str, labels: dict[str, str]
    ) -> tuple[Histogram, _HistogramSeries]:
        # label keys skip sorted() for the 0/1-label case: call sites
        # pass kwargs in a fixed order, and snapshot/merge/render
        # re-sort anyway
        hist = self._histograms.get(name)
        if hist is None:
            hist = self._materialize_histogram(name)
        key = (
            tuple(sorted(labels.items()))
            if len(labels) > 1
            else tuple(labels.items())
        )
        series = hist._series.get(key)
        if series is None:
            with hist._lock:
                series = hist._series.get(key)
                if series is None:
                    series = hist._series[key] = _HistogramSeries(
                        len(hist.bounds) + 1)
        return hist, series

    def _record_slow(
        self, hist: Histogram, series: _HistogramSeries, value: float
    ) -> None:
        # exemplar capture — rare by construction (value crossed the
        # slow threshold), so the trace lookup, clock read, and lock
        # all live here instead of on the fast path
        ctx = current_trace()
        if ctx is None:
            return
        self._record_exemplar(hist, series, ctx.trace_id, value)

    def _record_exemplar(
        self, hist: Histogram, series: _HistogramSeries,
        trace_id: str, value: float,
    ) -> None:
        exemplar = (trace_id, value, time.time())
        with hist._lock:
            if len(series.exemplars) >= MAX_EXEMPLARS:
                del series.exemplars[0]
            series.exemplars.append(exemplar)
        if on_slow_exemplar is not None:
            try:
                on_slow_exemplar(hist.name, trace_id, value)
            except Exception:  # noqa: BLE001 - telemetry must not fail the op
                pass

    def observe(self, name: str, value: float, **labels: str) -> None:
        if not self.histograms_enabled:
            return
        hist, series = self._series_for(name, labels)
        if value >= self.slow_threshold:
            self._record_slow(hist, series, value)
        series.pending.append(value)
        if len(series.pending) >= FOLD_AT:
            hist._fold(series)

    def observe_many(self, name: str, values: list[float], *,
                     traces: list | None = None, **labels: str) -> None:
        """Bulk observe: one series resolution + one C-speed extend for
        a whole batch. Used by the group-commit writer for per-row
        queue-wait — a 64-row batch would otherwise pay per-call
        overhead 64 times on the writer thread (which still contends
        for the GIL). Batch work runs off the request's trace, so the
        ambient-context exemplar path can't apply; callers that carried
        each value's trace id by hand (the batched lanes do) pass them
        via ``traces`` (aligned with ``values``, ``None`` entries
        skipped) and slow observations still get exemplars."""
        if not self.histograms_enabled or not values:
            return
        hist, series = self._series_for(name, labels)
        if traces is not None:
            threshold = self.slow_threshold
            for value, trace_id in zip(values, traces):
                if value >= threshold and trace_id:
                    self._record_exemplar(hist, series, trace_id, value)
        series.pending.extend(values)
        if len(series.pending) >= FOLD_AT:
            hist._fold(series)

    def recorder(self, name: str, **labels: str):
        """Return a ``record(value)`` closure bound to one series.

        The per-request call sites (state ops, publish, delivery,
        invoke, sidecar requests) cache one of these instead of calling
        :meth:`observe`: the closure skips the kwargs/key/dict work so
        an observation is a float compare plus a lock-free append.
        Toggling ``histograms_enabled`` is honoured live — the closure
        re-reads it on every call (``bench.py --hist-bench`` flips it
        between rounds).
        """
        hist, series = self._series_for(name, labels)
        pending = series.pending
        append = pending.append
        fold = hist._fold
        record_slow = self._record_slow
        registry = self

        def record(value: float) -> None:
            if not registry.histograms_enabled:
                return
            if value >= registry.slow_threshold:
                record_slow(hist, series, value)
            append(value)
            if len(pending) >= FOLD_AT:
                fold(series)

        return record

    def get(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            kind = self._kinds.get(name)
            if kind == "gauge":
                return self._gauges.get(key, 0.0)
            return self._counters.get(key, 0.0)

    def gauge_values(self, name: str) -> list[float]:
        """Every live value of ``name`` across its label sets.

        The admission controller samples queue-depth gauges this way:
        it cares about the worst series (one saturated shard is enough
        to shed), not any single label combination.
        """
        with self._lock:
            return [v for (n, _), v in self._gauges.items() if n == name]

    @staticmethod
    def _key(name: str, labels: Iterable[tuple[str, str]]) -> str:
        labels = tuple(labels)
        if not labels:
            return name
        tag = ",".join(f"{k}={v}" for k, v in labels)
        return f"{name}{{{tag}}}"

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            out = {self._key(n, ls): v for (n, ls), v in self._counters.items()}
            out.update({self._key(n, ls): v for (n, ls), v in self._gauges.items()})
            out["uptime_seconds"] = time.time() - self.started_at
            return out

    def snapshot_histograms(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            hists = list(self._histograms.items())
        return {name: h.snapshot() for name, h in sorted(hists)}

    def snapshot_kinds(self) -> dict[str, str]:
        with self._lock:
            return dict(self._kinds)


def merge_histogram_snapshots(
    snaps: Iterable[dict[str, dict[str, Any]]],
) -> dict[str, dict[str, Any]]:
    """Merge per-replica ``snapshot_histograms()`` payloads.

    Series with the same name + label set add element-wise; bounds must
    match (they always do — every process uses DEFAULT_BOUNDS), else the
    offending series is skipped rather than merged wrongly.
    """
    merged: dict[str, dict[str, Any]] = {}
    for snap in snaps:
        for name, hist in snap.items():
            target = merged.setdefault(name, {"bounds": list(hist["bounds"]), "series": {}})
            if target["bounds"] != list(hist["bounds"]):
                continue
            for series in hist["series"]:
                key = tuple(sorted(series["labels"].items()))
                slot = target["series"].get(key)
                if slot is None:
                    target["series"][key] = {
                        "labels": dict(series["labels"]),
                        "counts": list(series["counts"]),
                        "sum": float(series["sum"]),
                        "count": int(series["count"]),
                        "exemplars": [list(e) for e in series.get("exemplars", ())],
                    }
                else:
                    slot["counts"] = [a + b for a, b in zip(slot["counts"], series["counts"])]
                    slot["sum"] += float(series["sum"])
                    slot["count"] += int(series["count"])
                    slot["exemplars"].extend(list(e) for e in series.get("exemplars", ()))
    return {
        name: {
            "bounds": hist["bounds"],
            "series": [hist["series"][k] for k in sorted(hist["series"])],
        }
        for name, hist in merged.items()
    }


def merge_flat_snapshots(
    snaps: Iterable[dict[str, float]],
    kinds: dict[str, str] | None = None,
) -> dict[str, float]:
    """Merge per-replica ``snapshot()`` payloads (flat ``name{labels}``
    keys): counters sum across replicas, gauges take the max (summing
    uptimes or queue depths would invent a replica that doesn't exist).
    Unknown kinds are treated as counters."""
    kinds = kinds or {}
    out: dict[str, float] = {}
    for snap in snaps:
        for key, value in snap.items():
            base = key.split("{", 1)[0]
            if kinds.get(base) == "gauge":
                out[key] = max(out.get(key, float("-inf")), float(value))
            else:
                out[key] = out.get(key, 0.0) + float(value)
    return out


def summarize_histograms(
    merged: dict[str, dict[str, Any]],
    quantiles: tuple[float, ...] = (0.5, 0.95, 0.99),
) -> list[dict[str, Any]]:
    """Flatten merged histograms into per-series percentile rows, the
    shape the admin API and ``tasksrunner metrics --percentiles``
    print."""
    rows: list[dict[str, Any]] = []
    for name, hist in sorted(merged.items()):
        bounds = hist["bounds"]
        for series in hist["series"]:
            row: dict[str, Any] = {
                "name": name,
                "labels": dict(series["labels"]),
                "count": series["count"],
                "sum": series["sum"],
            }
            for q in quantiles:
                row[f"p{int(q * 100)}"] = estimate_percentile(
                    bounds, series["counts"], q)
            rows.append(row)
    return rows


def estimate_percentile(bounds: list[float], counts: list[int], q: float) -> float:
    """Estimate the q-quantile (0..1) from cumulative bucket counts.

    Linear interpolation within the containing bucket; observations in
    the +Inf overflow bucket clamp to the top finite bound.
    """
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        prev_cum = cum
        cum += c
        if cum >= rank:
            if i >= len(bounds):
                return bounds[-1]
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - prev_cum) / c
            return lo + (hi - lo) * frac
    return bounds[-1]


def _prom_label_escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_labels(labels: dict[str, str], extra: tuple[str, str] | None = None) -> str:
    parts = [f'{k}="{_prom_label_escape(str(v))}"' for k, v in sorted(labels.items())]
    if extra is not None:
        parts.append(f'{extra[0]}="{_prom_label_escape(extra[1])}"')
    if not parts:
        return ""
    return "{" + ",".join(parts) + "}"


def _format_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render_prometheus(registry: MetricsRegistry, help_texts: dict[str, str] | None = None) -> str:
    """Render the registry as Prometheus text exposition (version 0.0.4)."""
    if help_texts is None:
        from tasksrunner.observability import names as _names

        help_texts = _names.ALL
    kinds = registry.snapshot_kinds()
    lines: list[str] = []

    with registry._lock:
        counters = sorted(registry._counters.items())
        gauges = sorted(registry._gauges.items())
        uptime = time.time() - registry.started_at
    gauges.append((("uptime_seconds", ()), uptime))
    gauges.sort()

    def scalar_block(items: list, prom_type: str) -> None:
        last_name = None
        for (name, label_items), value in items:
            if name != last_name:
                help_text = help_texts.get(name, name)
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} {prom_type}")
                last_name = name
            lines.append(f"{name}{_prom_labels(dict(label_items))} {_format_value(value)}")

    scalar_block(counters, "counter")
    scalar_block(gauges, "gauge")

    for name, hist in sorted(registry.snapshot_histograms().items()):
        help_text = help_texts.get(name, name)
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} histogram")
        bounds = hist["bounds"]
        for series in hist["series"]:
            labels = series["labels"]
            cum = 0
            for i, bound in enumerate(bounds):
                cum += series["counts"][i]
                lines.append(
                    f"{name}_bucket{_prom_labels(labels, ('le', repr(float(bound))))} {cum}"
                )
            cum += series["counts"][len(bounds)]
            lines.append(f"{name}_bucket{_prom_labels(labels, ('le', '+Inf'))} {cum}")
            lines.append(f"{name}_sum{_prom_labels(labels)} {repr(series['sum'])}")
            lines.append(f"{name}_count{_prom_labels(labels)} {series['count']}")
    lines.append("")
    return "\n".join(lines)


#: process-global default registry
metrics = MetricsRegistry()
