"""In-process metrics counters/gauges, exported via the sidecar's
``/v1.0/metadata`` route.

The reference's metrics (CPU/memory/replica counts, request rates) come
from the platform + App Insights (SURVEY.md §5.5); the framework-level
equivalents here are request/publish/delivery counters every sidecar
maintains, which the orchestrator and autoscaler read.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self.started_at = time.time()

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._counters[self._key(name, labels)] += value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = value

    def get(self, name: str, **labels: str) -> float:
        key = self._key(name, labels)
        with self._lock:
            if key in self._gauges:
                return self._gauges[key]
            return self._counters.get(key, 0.0)

    @staticmethod
    def _key(name: str, labels: dict[str, str]) -> str:
        if not labels:
            return name
        tag = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        return f"{name}{{{tag}}}"

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            out["uptime_seconds"] = time.time() - self.started_at
            return out


#: process-global default registry
metrics = MetricsRegistry()
