"""Span collection + query — the App Insights analog.

The reference gets cross-service transaction search and the
Application Map from App Insights (SURVEY.md §5.1,
docs/aca/08-aca-monitoring/index.md:365-410). The framework-native
equivalent: every process records spans (one per handled request,
invocation, publish, delivery) into a shared sqlite file; the
``tasksrunner traces`` CLI renders transactions and the service map.

Recording is buffered and flushed off the event loop; a lost tail on
crash is acceptable (telemetry, not state). Enabled whenever a span
database path is configured (``TASKSRUNNER_TRACE_DB`` or AppHost
default); disabled recording is a no-op costing one ``if``.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import pathlib
import sqlite3
import threading
import time
from dataclasses import dataclass, field

from tasksrunner.ids import hex8
from tasksrunner.observability.metrics import metrics
from tasksrunner.observability.tracing import current_trace

_SCHEMA = """
CREATE TABLE IF NOT EXISTS spans (
    trace_id TEXT NOT NULL,
    span_id  TEXT NOT NULL,
    parent_id TEXT,
    role     TEXT NOT NULL,
    kind     TEXT NOT NULL,    -- server | client | producer | consumer
    name     TEXT NOT NULL,
    status   INTEGER,
    start    REAL NOT NULL,
    duration REAL NOT NULL,
    attrs    TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_spans_trace ON spans (trace_id, start);
CREATE INDEX IF NOT EXISTS idx_spans_start ON spans (start);
"""

ENV_VAR = "TASKSRUNNER_TRACE_DB"
RETENTION_ENV_VAR = "TASKSRUNNER_TRACE_RETENTION_SECONDS"
#: default span retention ≙ the reference's Log Analytics 30-day
#: retention (container-apps-environment.bicep:29-37)
DEFAULT_RETENTION_SECONDS = 30 * 24 * 3600.0


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str | None
    role: str
    kind: str
    name: str
    status: int | None
    start: float
    duration: float
    attrs: dict = field(default_factory=dict)


class SpanRecorder:
    """Buffered writer of spans into the shared trace db."""

    def __init__(self, role: str, path: str | pathlib.Path, *,
                 flush_interval: float = 0.5, max_buffer: int = 256,
                 retention_seconds: float | None = None):
        self.role = role
        self.path = str(path)
        pathlib.Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        #: raw column tuples, attrs still a dict (serialized at flush,
        #: off the event loop); appenders never take a lock — append is
        #: one C call under the GIL, and flush drains the head with the
        #: copy + del-slice pair (the histogram _fold discipline), so
        #: appends racing a flush land at the tail and survive
        self._buffer: list[tuple] = []
        self._io_lock = threading.Lock()
        self._conn: sqlite3.Connection | None = None
        self.flush_interval = flush_interval
        self.max_buffer = max_buffer
        if retention_seconds is None:
            raw = os.environ.get(RETENTION_ENV_VAR)
            try:
                retention_seconds = (float(raw) if raw
                                     else DEFAULT_RETENTION_SECONDS)
            except ValueError:
                # a telemetry knob must never crash app startup
                logging.getLogger(__name__).warning(
                    "ignoring bad %s=%r (want seconds as a number)",
                    RETENTION_ENV_VAR, raw)
                retention_seconds = DEFAULT_RETENTION_SECONDS
        #: spans older than this are pruned (≙ Log Analytics 30-day
        #: retention); <= 0 keeps everything
        self.retention_seconds = retention_seconds
        self._last_prune = 0.0
        self._timer: threading.Timer | None = None
        self._closed = False
        atexit.register(self.flush)
        self._schedule()

    def _schedule(self) -> None:
        # _closed guard: a _tick() already past close()'s cancel would
        # otherwise resurrect the flush timer on a closed recorder
        if self._closed:
            return
        self._timer = threading.Timer(self.flush_interval, self._tick)
        self._timer.daemon = True
        self._timer.start()

    def _tick(self) -> None:
        if self._closed:
            return
        try:
            self.flush()
        finally:
            self._schedule()

    def record(self, *, kind: str, name: str, status: int | None,
               start: float, duration: float, attrs: dict | None = None,
               span_id: str | None = None,
               parent_id: str | None = None,
               trace_id: str | None = None) -> None:
        """Append a span (no I/O here — the background timer flushes).

        Defaults: server/consumer spans ARE the current context's span
        (parented to the wire parent); callers recording an outbound
        child (client/producer) pass explicit ids. An explicit
        ``trace_id`` bypasses the ambient context entirely — the lanes
        that run off the request task (writer thread commits, the
        replication ship loop, micro-batch execution) carry the
        committing request's ids by hand.
        """
        self._append(trace_id, span_id, parent_id, kind, name, status,
                     start, duration, attrs)

    def _append(self, trace_id, span_id, parent_id, kind, name, status,
                start, duration, attrs) -> None:
        # hot path: one tuple + one lock-free append; no inline flush —
        # this runs on the event loop and must never pay sqlite I/O
        # (the timer thread drains the buffer). The depth gauge is
        # refreshed every 64th span, not every span — a set_gauge is
        # ~4x the cost of the append it would be measuring.
        if trace_id is None:
            ctx = current_trace()
            if ctx is None:
                return
            trace_id = ctx.trace_id
            if span_id is None:
                span_id = ctx.span_id
                if parent_id is None:
                    parent_id = ctx.parent_id
        buf = self._buffer
        buf.append((trace_id, span_id or hex8(), parent_id, self.role,
                    kind, name, status, start, duration, attrs))
        if not len(buf) & 63:
            metrics.set_gauge("span_buffer_depth", len(buf))

    def flush(self) -> None:
        buf = self._buffer
        raw = buf[:]
        if not raw:
            return
        del buf[:len(raw)]
        metrics.set_gauge("span_buffer_depth", len(buf))
        # I/O off the appenders' path; _io_lock serialises the writers
        # (timer thread + close)
        with self._io_lock:
            if self._conn is None:
                self._conn = sqlite3.connect(self.path, check_same_thread=False)
                self._conn.execute("PRAGMA journal_mode=WAL")
                self._conn.execute("PRAGMA synchronous=NORMAL")
                self._conn.execute("PRAGMA busy_timeout=5000")
                self._conn.executescript(_SCHEMA)
            self._conn.executemany(
                "INSERT INTO spans VALUES (?,?,?,?,?,?,?,?,?,?)",
                [row[:9] + ((json.dumps(row[9], default=str)
                             if row[9] else "{}"),) for row in raw],
            )
            now = time.time()
            if self.retention_seconds > 0 and now - self._last_prune > 60:
                # retention sweep at most once a minute, piggybacked on
                # a flush so idle processes pay nothing
                self._conn.execute(
                    "DELETE FROM spans WHERE start < ?",
                    (now - self.retention_seconds,))
                self._last_prune = now
            self._conn.commit()

    def close(self) -> None:
        self._closed = True
        if self._timer is not None:
            self._timer.cancel()
        self.flush()
        # Timer.cancel() does not interrupt a tick already running, so a
        # flush on the timer thread may still hold _io_lock and be using
        # _conn; tear it down under the same lock or that flush dies with
        # "Cannot operate on a closed database".
        with self._io_lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None


#: process-global recorder; None = tracing disabled
_recorder: SpanRecorder | None = None


def configure_spans(role: str, path: str | pathlib.Path | None = None) -> SpanRecorder | None:
    """Enable span recording for this process. ``path`` falls back to
    $TASKSRUNNER_TRACE_DB; with neither, recording stays off."""
    global _recorder
    path = path or os.environ.get(ENV_VAR)
    if not path:
        return None
    if _recorder is not None:
        _recorder.close()
    _recorder = SpanRecorder(role, path)
    return _recorder


def recorder() -> SpanRecorder | None:
    return _recorder


def active() -> bool:
    """True when this process records spans — THE one-``if`` gate the
    hot paths test before doing any per-span bookkeeping."""
    return _recorder is not None


def record_span(*, kind: str, name: str, status: int | None,
                start: float, duration: float,
                attrs: dict | None = None,
                span_id: str | None = None,
                parent_id: str | None = None,
                trace_id: str | None = None) -> None:
    rec = _recorder
    if rec is not None:
        # positional into _append: this is THE per-span call site and a
        # second 9-kwarg parse would double its interpreter cost
        rec._append(trace_id, span_id, parent_id, kind, name, status,
                    start, duration, attrs)


# -- query side ----------------------------------------------------------

def _connect_ro(path: str) -> sqlite3.Connection:
    """Genuinely read-only (mode=ro): every reader of the span store —
    list/show/map/query — gets the cannot-mutate-telemetry guarantee,
    not just the one that documents it."""
    conn = sqlite3.connect(f"file:{path}?mode=ro", uri=True,
                           check_same_thread=False)
    conn.row_factory = sqlite3.Row
    return conn


def list_traces(path: str, *, limit: int = 20) -> list[dict]:
    conn = _connect_ro(path)
    try:
        rows = conn.execute(
            "SELECT trace_id, MIN(start) AS started, COUNT(*) AS spans, "
            "SUM(duration) AS total_time, "
            "MAX(start + duration) - MIN(start) AS wall, "
            "GROUP_CONCAT(DISTINCT role) AS roles "
            "FROM spans GROUP BY trace_id ORDER BY started DESC LIMIT ?",
            (limit,),
        ).fetchall()
        out = []
        for r in rows:
            root = conn.execute(
                "SELECT name, role, status FROM spans WHERE trace_id = ? "
                "ORDER BY start LIMIT 1", (r["trace_id"],)).fetchone()
            out.append({
                "trace_id": r["trace_id"], "started": r["started"],
                "spans": r["spans"], "wall": r["wall"],
                "roles": sorted((r["roles"] or "").split(",")),
                "root": f"{root['role']}: {root['name']}" if root else "?",
                "status": root["status"] if root else None,
            })
        return out
    finally:
        conn.close()


def trace_spans(path: str, trace_id: str) -> list[dict]:
    conn = _connect_ro(path)
    try:
        rows = conn.execute(
            "SELECT * FROM spans WHERE trace_id LIKE ? ORDER BY start",
            (trace_id + "%",),
        ).fetchall()
        return [dict(r) for r in rows]
    finally:
        conn.close()


def assemble_trace(sources: list, trace_id: str) -> list[dict]:
    """Merge one trace's spans from several sources — local span-db
    paths and/or already-fetched span-row lists (what the orchestrator
    pulls from each replica's sidecar). Deduplicates on span_id (a span
    flushed on two hosts counts once), returns rows ordered by start —
    the multi-host analog of the shared-file assumption the query
    helpers above make."""
    merged: dict[str, dict] = {}
    for source in sources:
        if isinstance(source, (str, pathlib.Path)):
            try:
                rows = trace_spans(str(source), trace_id)
            except sqlite3.Error:
                continue  # a replica with no span db yet is not an error
        else:
            rows = [r for r in source
                    if str(r.get("trace_id", "")).startswith(trace_id)]
        for row in rows:
            merged.setdefault(row["span_id"], dict(row))
    return sorted(merged.values(), key=lambda r: r["start"])


def critical_path(spans: list[dict]) -> list[dict]:
    """Extract the blame chain: from the root span, repeatedly descend
    into the child whose end time is latest — the longest pole holding
    the parent open. Each hop reports ``self_time`` (its duration minus
    the chosen child's overlap) plus the queue-wait/service split when
    the span recorded one (group-commit writes, ML batch requests), so
    the chain's self-times reconstruct the root's wall time."""
    if not spans:
        return []
    by_id = {s["span_id"]: s for s in spans}
    children: dict[str, list[dict]] = {}
    for s in spans:
        parent = s.get("parent_id")
        if parent and parent in by_id and parent != s["span_id"]:
            children.setdefault(parent, []).append(s)
    roots = [s for s in spans
             if not s.get("parent_id") or s["parent_id"] not in by_id]
    node = min(roots or spans, key=lambda s: s["start"])
    chain: list[dict] = []
    seen: set[str] = set()
    while node is not None and node["span_id"] not in seen:
        seen.add(node["span_id"])
        kids = children.get(node["span_id"], [])
        nxt = max(kids, key=lambda s: s["start"] + s["duration"],
                  default=None)
        attrs = node.get("attrs") or {}
        if isinstance(attrs, str):
            try:
                attrs = json.loads(attrs)
            except ValueError:
                attrs = {}
        hop = {
            "span_id": node["span_id"], "name": node["name"],
            "role": node["role"], "kind": node["kind"],
            "start": node["start"], "duration": node["duration"],
            # overlap, not the child's full duration: an async child
            # outliving its parent must not produce negative self-time
            "self_time": node["duration"] - (
                max(0.0, min(node["start"] + node["duration"],
                             nxt["start"] + nxt["duration"]) - nxt["start"])
                if nxt is not None else 0.0),
        }
        if "queue_wait" in attrs:
            hop["queue_wait"] = attrs["queue_wait"]
            hop["service"] = attrs.get(
                "service", node["duration"] - attrs["queue_wait"])
        chain.append(hop)
        node = nxt
    return chain


def service_map(path: str) -> list[dict]:
    """App-Map edges: caller role → target, with call counts.

    Client spans carry their target in attrs; this aggregates them.
    """
    conn = _connect_ro(path)
    try:
        # one edge per (caller, kind, TARGET): span names embed the
        # method path, so grouping by name would print the same App-Map
        # edge once per distinct operation; extracting the target in
        # SQL also keeps the grouping deterministic when attrs vary
        # within one name
        rows = conn.execute(
            "SELECT role, kind, "
            "COALESCE(json_extract(attrs, '$.target'), name) AS target, "
            "COUNT(*) AS n, AVG(duration) AS avg_duration "
            "FROM spans WHERE kind IN ('client', 'producer') "
            "GROUP BY role, kind, target ORDER BY n DESC",
        ).fetchall()
        return [
            {"from": r["role"], "to": r["target"], "kind": r["kind"],
             "calls": r["n"], "avg_ms": round(r["avg_duration"] * 1000, 2)}
            for r in rows
        ]
    finally:
        conn.close()
