"""Structured logging with service-role tagging and trace correlation.

Replicates the reference's observability posture (SURVEY.md §5.1, §5.5):
every service logs through ``ILogger<T>`` with a Cloud.RoleName set by
AppInsightsTelemetryInitializer.cs so the three services are
distinguishable in one stream. Here: a logfmt-ish line format carrying
``role=<app-id>`` and ``trace=<trace-id>`` on every record, so the
orchestrator's multiplexed output is greppable per service and per
transaction.
"""

from __future__ import annotations

import logging
import sys

from tasksrunner.observability.tracing import current_trace


class _RoleTraceFilter(logging.Filter):
    def __init__(self, role: str):
        super().__init__()
        self.role = role

    def filter(self, record: logging.LogRecord) -> bool:
        record.role = self.role
        ctx = current_trace()
        record.trace = ctx.trace_id[:16] if ctx else "-"
        return True


FORMAT = "%(asctime)s %(levelname)-7s role=%(role)s trace=%(trace)s %(name)s :: %(message)s"


def configure_logging(role: str, *, level: int = logging.INFO,
                      stream=None) -> logging.Logger:
    """Configure the root logger for one service process."""
    root = logging.getLogger()
    root.setLevel(level)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(logging.Formatter(FORMAT))
    handler.addFilter(_RoleTraceFilter(role))
    root.addHandler(handler)
    return root


def service_logger(name: str) -> logging.Logger:
    return logging.getLogger(name)
