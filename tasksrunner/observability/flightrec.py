"""Black-box flight recorder: the last N request timelines, always on.

Traces answer "what happened to this request"; the flight recorder
answers "what was this process doing right before it went wrong" when
nobody was watching — the aviation black box, not the radar track. A
bounded per-process ring holds span skeletons (name, trace id, status,
duration) for recent requests plus periodic saturation-gauge samples,
at O(1) cost per request (one deque append; the gauge sample is
rate-limited to once a second). The ring is dumped to disk as JSON on
the three events worth a post-mortem:

* admission-shed entry (the controller tripped — what led up to it),
* a slow-threshold exemplar (via :data:`metrics.on_slow_exemplar`),
* unclean shutdown (atexit without :func:`mark_clean`; a ``kill -9``
  loses the ring, which is the accepted black-box trade — the crash
  you *can* hook is the one you dump).

Dumps land in ``TASKSRUNNER_FLIGHTREC_DIR`` and are rendered by
``tasksrunner flightrec``. ``TASKSRUNNER_FLIGHTREC=0`` disables the
whole plane; the per-request cost of the disabled path is one ``if``.
"""

from __future__ import annotations

import asyncio
import atexit
import collections
import json
import logging
import os
import pathlib
import time

from tasksrunner.envflag import env_flag
from tasksrunner.observability.metrics import metrics, set_on_slow_exemplar

logger = logging.getLogger(__name__)

ENV_ENABLED = "TASKSRUNNER_FLIGHTREC"
ENV_RING = "TASKSRUNNER_FLIGHTREC_RING"
ENV_DIR = "TASKSRUNNER_FLIGHTREC_DIR"

DEFAULT_RING = 256
DEFAULT_DIR = ".tasksrunner/flightrec"

#: the saturation signals sampled into the ring — the same probes the
#: admission controller scores, so a dump shows the shed decision's
#: inputs alongside the requests that preceded it
SAMPLED_GAUGES = (
    "admission_saturation",
    "event_loop_lag_seconds",
    "state_write_queue_depth",
    "broker_publish_queue_depth",
    "ml_queue_depth",
)

#: gauge-sample cadence inside the ring (seconds)
_SAMPLE_EVERY = 1.0
#: per-reason dump rate limit — a shed storm or a burst of slow
#: requests must not turn the recorder into a disk-filling loop
_MIN_DUMP_INTERVAL = 5.0


class FlightRecorder:
    """Bounded ring of request skeletons + gauge samples for one process."""

    def __init__(self, role: str, *, ring_size: int | None = None,
                 out_dir: str | pathlib.Path | None = None):
        self.role = role
        if ring_size is None:
            raw = os.environ.get(ENV_RING)
            try:
                ring_size = int(raw) if raw else DEFAULT_RING
            except ValueError:
                logger.warning("ignoring bad %s=%r (want an integer)",
                               ENV_RING, raw)
                ring_size = DEFAULT_RING
        self.out_dir = str(out_dir or os.environ.get(ENV_DIR) or DEFAULT_DIR)
        #: deque appends are atomic under the GIL — note() takes no lock
        self._ring: collections.deque = collections.deque(maxlen=ring_size)
        self._last_sample = 0.0
        self._last_dump: dict[str, float] = {}
        self._clean = False

    # -- recording (hot path) ---------------------------------------------

    def note(self, *, name: str, trace_id: str | None,
             status: int | None, duration: float) -> None:
        """Append one request skeleton — O(1), no I/O, no lock."""
        now = time.time()
        entry = {"ts": now, "name": name, "trace": trace_id,
                 "status": status, "dur": duration}
        if now - self._last_sample >= _SAMPLE_EVERY:
            self._last_sample = now
            entry["gauges"] = self._sample_gauges()
        self._ring.append(entry)

    @staticmethod
    def _sample_gauges() -> dict[str, float]:
        out = {}
        for name in SAMPLED_GAUGES:
            values = metrics.gauge_values(name)
            if values:
                # worst series: one saturated shard is the story
                out[name] = max(values)
        return out

    # -- dumping ----------------------------------------------------------

    def dump(self, reason: str, detail: dict | None = None) -> str | None:
        """Snapshot the ring and write it to disk; returns the dump
        path, or None when the per-reason rate limit suppressed it.

        The ring snapshot is taken synchronously (in-memory, O(ring));
        the disk write is dispatched to an executor when a running
        event loop is present (the admission sampler's case) and done
        inline otherwise (atexit, sync hooks) — on-loop callers get
        the path back before the write lands."""
        now = time.time()
        if now - self._last_dump.get(reason, 0.0) < _MIN_DUMP_INTERVAL:
            return None
        self._last_dump[reason] = now
        payload = {
            "role": self.role, "pid": os.getpid(), "reason": reason,
            "detail": detail or {}, "ts": now,
            "gauges": self._sample_gauges(),
            "entries": list(self._ring),
        }
        path = pathlib.Path(self.out_dir) / (
            f"{self.role}-{os.getpid()}-{int(now)}-{reason}.json")
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return self._write_dump(path, payload, reason)
        loop.run_in_executor(None, self._write_dump, path, payload, reason)
        return str(path)

    # executor-dispatched when a loop is running; the inline (no-loop)
    # caller has no loop to block
    def _write_dump(self, path, payload, reason):  # tasklint: off-loop
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(json.dumps(payload, default=str))
        except OSError as exc:
            # a full disk must not take the process down with it
            logger.warning("flight-recorder dump to %s failed: %s", path, exc)
            return None
        logger.warning("flight recorder dumped %d entries to %s (%s)",
                       len(payload["entries"]), path, reason)
        return str(path)

    def mark_clean(self) -> None:
        """A deliberate shutdown — suppress the atexit black-box dump."""
        self._clean = True

    def _atexit(self) -> None:
        if not self._clean and self._ring:
            self.dump("unclean-shutdown")


#: process-global recorder; None = flight recording disabled
_flightrec: FlightRecorder | None = None


def configure_flightrec(role: str, *, ring_size: int | None = None,
                        out_dir: str | pathlib.Path | None = None,
                        ) -> FlightRecorder | None:
    """Enable the flight recorder for this process (the host path calls
    this at sidecar start). Always on unless TASKSRUNNER_FLIGHTREC=0."""
    global _flightrec
    if not env_flag(ENV_ENABLED, default=True):
        return None
    if _flightrec is None:
        _flightrec = FlightRecorder(role, ring_size=ring_size,
                                    out_dir=out_dir)
        atexit.register(_flightrec._atexit)
        # a slow exemplar is also a black-box moment: snapshot the ring
        set_on_slow_exemplar(_on_slow)
    return _flightrec


def flight_recorder() -> FlightRecorder | None:
    return _flightrec


def note_request(*, name: str, trace_id: str | None,
                 status: int | None, duration: float) -> None:
    """The one-``if`` hot-path entry point the sidecar calls per request."""
    if _flightrec is not None:
        _flightrec.note(name=name, trace_id=trace_id, status=status,
                        duration=duration)


def mark_clean() -> None:
    if _flightrec is not None:
        _flightrec.mark_clean()


def dump(reason: str, detail: dict | None = None) -> str | None:
    if _flightrec is not None:
        return _flightrec.dump(reason, detail)
    return None


def _on_slow(metric: str, trace_id: str, value: float) -> None:
    if _flightrec is not None:
        _flightrec.dump("slow-exemplar",
                        {"metric": metric, "trace_id": trace_id,
                         "value": value})


# -- reading (the `tasksrunner flightrec` CLI) ----------------------------

def list_dumps(out_dir: str | pathlib.Path | None = None) -> list[dict]:
    """Summaries of every dump file, newest first."""
    root = pathlib.Path(out_dir or os.environ.get(ENV_DIR) or DEFAULT_DIR)
    rows = []
    if not root.is_dir():
        return rows
    for path in sorted(root.glob("*.json"), reverse=True):
        try:
            payload = json.loads(path.read_text())
        except (OSError, ValueError):
            continue
        rows.append({
            "path": str(path), "role": payload.get("role"),
            "pid": payload.get("pid"), "reason": payload.get("reason"),
            "ts": payload.get("ts"),
            "entries": len(payload.get("entries") or ()),
        })
    return rows


def read_dump(path: str | pathlib.Path) -> dict:
    return json.loads(pathlib.Path(path).read_text())
