"""Runtime saturation probes.

The queue-depth gauges (state write queue, broker publish queue, DLQ,
span buffer) are set inline where the queues live; this module holds
the one probe that needs its own task: event-loop lag. A coroutine
sleeps for a fixed interval and reports how late the loop woke it —
the canonical timer-drift measure of how saturated the loop is with
callbacks. Autoscale on this before anything else: a loop that is 100ms
late is 100ms of latency added to *every* request the replica serves.
"""

from __future__ import annotations

import asyncio
import contextlib

from tasksrunner.observability.metrics import MetricsRegistry, metrics as default_metrics

DEFAULT_INTERVAL = 0.5


class EventLoopLagProbe:
    """Periodic timer-drift sampler feeding ``event_loop_lag_seconds``."""

    def __init__(
        self,
        interval: float = DEFAULT_INTERVAL,
        registry: MetricsRegistry | None = None,
    ) -> None:
        self.interval = interval
        self.registry = registry if registry is not None else default_metrics
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.interval)
            lag = loop.time() - t0 - self.interval
            self.registry.set_gauge("event_loop_lag_seconds", max(0.0, lag))
