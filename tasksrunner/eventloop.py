"""Optional uvloop event-loop policy for server entry points.

uvloop is NOT a dependency of this package: the flag degrades to a
logged no-op when the wheel is absent, so the same config can roll
across a fleet where only some images bundle it. Every long-running
entry point (`tasksrunner host/serve/sidecar/run` via
cli._run_until_interrupt, and the bench's worker processes) calls
:func:`maybe_enable_uvloop` before creating its event loop; the bench
reports availability honestly instead of silently measuring asyncio.
"""

from __future__ import annotations

import asyncio
import logging

from tasksrunner.envflag import env_flag

logger = logging.getLogger(__name__)


def maybe_enable_uvloop() -> bool:
    """Install uvloop's event-loop policy when ``TASKSRUNNER_UVLOOP=1``
    and the package is importable. Returns True iff installed. Must be
    called before the event loop is created (``asyncio.run``)."""
    if not env_flag("TASKSRUNNER_UVLOOP", default=False):
        return False
    try:
        import uvloop
    except ImportError:
        logger.warning(
            "TASKSRUNNER_UVLOOP is set but uvloop is not installed; "
            "continuing on the default asyncio event loop")
        return False
    asyncio.set_event_loop_policy(uvloop.EventLoopPolicy())
    logger.info("uvloop event-loop policy installed")
    return True


def uvloop_available() -> bool:
    """True when the uvloop package can be imported (bench reporting)."""
    try:
        import uvloop  # noqa: F401
    except ImportError:
        return False
    return True
