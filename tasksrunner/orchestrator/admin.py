"""Orchestrator control-plane API + the file that advertises it.

The reference operates running apps through `az containerapp` verbs —
`update --set-env-vars` / `--min-replicas` (docs/aca/02-aca-comm/
index.md:238-300, docs/aca/09-aca-autoscale-keda/index.md:100-145),
`revision restart` / `revision list` (used across modules 2 and 8),
`logs show`, and `replica list`. This module is that surface for the
local orchestrator: a localhost-only HTTP API the `tasksrunner`
CLI (`restart` / `update` / `scale` / `logs` / `revisions` / `ps`)
drives.

Discovery: the server writes ``orchestrator.json`` next to the
name-registry file (pid + admin URL); the CLI reads it. If
``TASKSRUNNER_API_TOKEN`` is set for the orchestrator, every admin
request must carry it in the same header the sidecars require —
one token protects the whole control plane.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import pathlib
import typing

from tasksrunner.security import TOKEN_ENV, TOKEN_HEADER

if typing.TYPE_CHECKING:  # import cycle: run.py starts the AdminServer
    from tasksrunner.orchestrator.run import Orchestrator

logger = logging.getLogger(__name__)

INFO_FILENAME = "orchestrator.json"


def info_path(registry_file: str | pathlib.Path) -> pathlib.Path:
    return pathlib.Path(registry_file).parent / INFO_FILENAME


class AdminServer:
    def __init__(self, orch: "Orchestrator", *, port: int = 0,
                 host: str = "127.0.0.1"):
        self.orch = orch
        self.host = host
        self.port = port
        self._runner = None
        self._site = None
        self._info_file: pathlib.Path | None = None

    # -- lifecycle -------------------------------------------------------

    async def start(self) -> None:
        from aiohttp import web

        @web.middleware
        async def auth_middleware(request, handler):
            token = os.environ.get(TOKEN_ENV)
            if token and request.headers.get(TOKEN_HEADER) != token:
                return web.json_response(
                    {"error": "missing or bad api token"}, status=401)
            return await handler(request)

        app = web.Application(middlewares=[auth_middleware])
        app.router.add_get("/admin/apps", self._apps)
        app.router.add_get("/admin/apps/{app_id}/logs", self._logs)
        app.router.add_get("/admin/apps/{app_id}/revisions", self._revisions)
        app.router.add_post("/admin/apps/{app_id}/restart", self._restart)
        app.router.add_post("/admin/apps/{app_id}/env", self._env)
        app.router.add_post("/admin/apps/{app_id}/scale", self._scale)
        app.router.add_get("/admin/apps/{app_id}/metrics", self._metrics)
        app.router.add_get("/admin/actors", self._actors)
        app.router.add_get("/admin/placement", self._placement)
        app.router.add_get("/admin/traces/{trace_id}", self._traces)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self.host, self.port)
        await self._site.start()
        actual_port = self._site._server.sockets[0].getsockname()[1]
        self.port = actual_port

        self._info_file = info_path(self.orch.config.registry_path)
        info = json.dumps({
            "admin_url": f"http://{self.host}:{actual_port}",
            "pid": os.getpid(),
        })

        def write_info() -> None:  # tasklint: off-loop
            import tempfile

            self._info_file.parent.mkdir(parents=True, exist_ok=True)
            # write-then-rename: a reader (CLI, standby orchestrator)
            # racing this write must see the old document or the new
            # one, never a torn half — same discipline as the name
            # registry's _mutate
            fd, tmp = tempfile.mkstemp(
                dir=self._info_file.parent, prefix=".orchestrator-")
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(info)
                os.replace(tmp, self._info_file)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

        # startup disk write off-loop: the supervisor loop is already
        # scheduling replica starts at this point
        await asyncio.to_thread(write_info)
        logger.info("orchestrator admin API on http://%s:%d", self.host, actual_port)

    async def stop(self) -> None:
        if self._info_file is not None:
            try:
                self._info_file.unlink()
            except OSError:
                pass
            self._info_file = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def abandon(self) -> None:
        """Release the listener but leave ``orchestrator.json`` behind
        — the on-disk state a kill -9'd orchestrator leaves (it never
        gets to unlink). The takeover orchestrator overwrites it."""
        self._info_file = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- handlers --------------------------------------------------------

    def _resolve_app(self, request):
        from aiohttp import web

        app_id = request.match_info["app_id"]
        if app_id not in self.orch.replicas:
            known = ", ".join(sorted(self.orch.replicas)) or "(none)"
            raise web.HTTPNotFound(
                text=json.dumps({"error": f"unknown app {app_id!r}; "
                                          f"running: {known}"}),
                content_type="application/json")
        return app_id

    async def _apps(self, request):
        from aiohttp import web

        return web.json_response(self.orch.status())

    async def _logs(self, request):
        from aiohttp import web

        app_id = self._resolve_app(request)
        try:
            tail = int(request.query.get("tail", "100"))
            replica = (int(request.query["replica"])
                       if "replica" in request.query else None)
        except ValueError:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "tail and replica must be integers"}),
                content_type="application/json")
        return web.json_response(
            {"lines": self.orch.app_logs(app_id, tail=tail, replica=replica)})

    async def _revisions(self, request):
        from aiohttp import web

        app_id = self._resolve_app(request)
        return web.json_response(
            {"revisions": self.orch.revisions.get(app_id, [])})

    async def _restart(self, request):
        from aiohttp import web

        app_id = self._resolve_app(request)
        entry = await self.orch.restart_app(app_id)
        return web.json_response({"restarted": app_id, "revision": entry})

    async def _env(self, request):
        from aiohttp import web

        app_id = self._resolve_app(request)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "body must be JSON"}),
                content_type="application/json")
        set_env = body.get("set") or {}
        remove = body.get("remove") or []
        if not isinstance(set_env, dict) or not isinstance(remove, list):
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "expected {set: {..}, remove: [..]}"}),
                content_type="application/json")
        entry = await self.orch.update_env(
            app_id, set_env=set_env, remove=[str(k) for k in remove])
        return web.json_response({"updated": app_id, "revision": entry})

    async def _metrics(self, request):
        """Cross-replica metrics: fan out to every replica sidecar's
        ``/v1.0/metadata``, sum counters / max gauges, and merge
        histogram bucket arrays so the percentiles are computed over
        the app, not one replica."""
        import aiohttp
        from aiohttp import web

        from tasksrunner.observability.metrics import (
            merge_flat_snapshots,
            merge_histogram_snapshots,
            summarize_histograms,
        )

        app_id = self._resolve_app(request)
        token = os.environ.get(TOKEN_ENV)
        headers = {TOKEN_HEADER: token} if token else {}
        payloads = []
        async with aiohttp.ClientSession() as session:
            for replica in self.orch.replicas.get(app_id, []):
                if not replica.ports:
                    continue
                url = f"http://127.0.0.1:{replica.ports[1]}/v1.0/metadata"
                try:
                    async with session.get(
                            url, headers=headers,
                            timeout=aiohttp.ClientTimeout(total=5)) as resp:
                        if resp.status == 200:
                            payloads.append(await resp.json())
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    continue  # a dead replica must not fail the view
        kinds: dict[str, str] = {}
        for p in payloads:
            kinds.update(p.get("metric_kinds") or {})
        merged_hist = merge_histogram_snapshots(
            p.get("histograms") or {} for p in payloads)
        return web.json_response({
            "app_id": app_id,
            "replicas": len(payloads),
            "metrics": merge_flat_snapshots(
                (p.get("metrics") or {} for p in payloads), kinds),
            "percentiles": summarize_histograms(merged_hist),
            "histograms": merged_hist,
        })

    async def _placement(self, request):
        """Cluster elastic-placement view: per app, per sharded store —
        routing epoch, shard→host assignment, hot/cold ranking, any
        in-flight migration, and the control loop's rebalance plan.
        With TASKSRUNNER_RESHARD on this serves the live controllers'
        last sweep; with it off it runs one sweep on demand, so
        ``tasksrunner shards`` always answers."""
        from aiohttp import web

        controllers = getattr(self.orch, "placement", {})
        apps = {}
        if controllers:
            for app_id, controller in sorted(controllers.items()):
                apps[app_id] = controller.snapshot()
            return web.json_response({"reshard": True, "apps": apps})
        from tasksrunner.orchestrator.placement import PlacementController

        tokens = self.orch.config.app_tokens
        for app_id in sorted(self.orch.replicas):
            controller = PlacementController(
                app_id,
                lambda a=app_id: self.orch._replica_info(a),
                api_token=(tokens.get(app_id) if tokens
                           else os.environ.get(TOKEN_ENV)),
            )
            try:
                await controller.step()
                apps[app_id] = controller.snapshot()
            finally:
                await asyncio.shield(controller.stop())
        return web.json_response({"reshard": False, "apps": apps})

    async def _actors(self, request):
        """Cluster actor view: the placement table (type → id → owner →
        lease age / fencing epoch) plus each replica's local summary.
        Every replica computes the same table from the shared store, so
        the first reachable sidecar per app supplies it; the per-replica
        summaries still come from every replica we can reach."""
        import aiohttp
        from aiohttp import web

        token = os.environ.get(TOKEN_ENV)
        headers = {TOKEN_HEADER: token} if token else {}
        placement: list[dict] = []
        replicas: list[dict] = []
        async with aiohttp.ClientSession() as session:
            for app_id, app_replicas in sorted(self.orch.replicas.items()):
                have_table = False
                for replica in app_replicas:
                    if not replica.ports:
                        continue
                    url = f"http://127.0.0.1:{replica.ports[1]}/v1.0/actors"
                    try:
                        async with session.get(
                                url, headers=headers,
                                timeout=aiohttp.ClientTimeout(total=5)) as resp:
                            if resp.status != 200:
                                continue
                            doc = await resp.json()
                    except (aiohttp.ClientError, asyncio.TimeoutError):
                        continue  # a dead replica must not fail the view
                    if doc.get("replica"):
                        replicas.append({"app_id": app_id, **doc["replica"]})
                    if not have_table and doc.get("placement"):
                        placement.extend(doc["placement"])
                        have_table = True
        placement.sort(key=lambda r: (r.get("type") or "", r.get("id") or ""))
        return web.json_response(
            {"placement": placement, "replicas": replicas})

    async def _traces(self, request):
        """Cross-replica trace assembly: every replica records spans
        into its own local span DB, so one logical request's trace is
        scattered across processes. Fan out to every sidecar's
        ``/v1.0/traces/{id}``, merge and dedup by span id, and hand
        back the whole tree — the raw material for ``traces show`` /
        ``traces critical`` against a multi-replica app."""
        import aiohttp
        from aiohttp import web

        from tasksrunner.observability.spans import assemble_trace

        trace_id = request.match_info["trace_id"]
        token = os.environ.get(TOKEN_ENV)
        headers = {TOKEN_HEADER: token} if token else {}
        sources: list[list[dict]] = []
        replicas = 0
        async with aiohttp.ClientSession() as session:
            for app_id, app_replicas in sorted(self.orch.replicas.items()):
                for replica in app_replicas:
                    if not replica.ports:
                        continue
                    url = (f"http://127.0.0.1:{replica.ports[1]}"
                           f"/v1.0/traces/{trace_id}")
                    try:
                        async with session.get(
                                url, headers=headers,
                                timeout=aiohttp.ClientTimeout(total=5)) as resp:
                            if resp.status != 200:
                                continue
                            doc = await resp.json()
                    except (aiohttp.ClientError, asyncio.TimeoutError):
                        continue  # a dead replica must not fail the view
                    replicas += 1
                    if doc.get("spans"):
                        sources.append(doc["spans"])
        spans = await asyncio.to_thread(assemble_trace, sources, trace_id)
        return web.json_response({
            "trace_id": trace_id,
            "replicas": replicas,
            "spans": spans,
        })

    async def _scale(self, request):
        from aiohttp import web

        app_id = self._resolve_app(request)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "body must be JSON"}),
                content_type="application/json")
        try:
            entry = await self.orch.update_scale(
                app_id,
                min_replicas=(int(body["min_replicas"])
                              if "min_replicas" in body else None),
                max_replicas=(int(body["max_replicas"])
                              if "max_replicas" in body else None),
            )
        except (ValueError, TypeError) as exc:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": str(exc)}),
                content_type="application/json")
        return web.json_response({"updated": app_id, "revision": entry})
