"""The orchestrator's elastic-placement control loop.

Closes the loop the state layer's heat telemetry opens
(state/placement.py): every sharded store publishes per-shard write
rates, hot-key sketches, and its routing epoch through the sidecar
metadata endpoint; this controller sweeps those documents across an
app's replicas, merges them into one hot/cold ranking per store, and
keeps a rebalance *plan* — "split shard 2, it is hot across many
keys" / "move shard 0 to the coldest host, one key dominates it".

Deliberately advisory in this milestone: the controller computes and
publishes the plan (``/admin/placement``, ``tasksrunner shards``); the
migrations themselves run through
:meth:`~tasksrunner.state.sharding.ShardedStateStore.migrate_shard` /
``split_shard`` on the store's owning process, because only that
process can hold the write-pause barrier. Auto-executing the plan is
the same wiring the autoscaler uses for ``set_replicas`` and can be
layered on without touching the data plane.

Gated by ``TASKSRUNNER_RESHARD`` — off by default, like every control
loop in this repo; the telemetry underneath is always on (it is a few
counters per write).

The poll cadence is deliberately lazier than the autoscaler's 0.5 s:
heat EWMAs move on multi-second half-lives and hysteresis windows are
~10 s, so polling faster than ~2 s buys nothing but sidecar load.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable

from tasksrunner.state.placement import (
    heat_threshold_default,
    merge_heat_docs,
    plan_rebalance,
    rank_shards,
)

logger = logging.getLogger(__name__)


class PlacementController:
    """Per-app sweep of sidecar placement telemetry → ranked plan."""

    def __init__(self, app_id: str,
                 replica_info: Callable[[], list[dict]], *,
                 api_token: str | None = None,
                 interval: float = 2.0):
        self.app_id = app_id
        self.replica_info = replica_info
        self.api_token = api_token
        self.interval = interval
        #: store name → merged view (epoch, ranking, plan, migration);
        #: replaced wholesale each sweep, read by the admin endpoint
        self.view: dict[str, dict] = {}
        self.last_sweep: float | None = None
        self._task: asyncio.Task | None = None
        self._warned_unreachable = False

    # -- one sweep -------------------------------------------------------

    async def _fetch_metadata(self) -> list[dict]:
        """Collect ``/v1.0/metadata`` from every live replica sidecar
        (the autoscaler's target-p99 sweep, reused verbatim in shape).
        Unreachable replicas contribute nothing — a mid-restart replica
        must not wedge the control loop."""
        import aiohttp

        from tasksrunner.security import TOKEN_HEADER

        headers = {TOKEN_HEADER: self.api_token} if self.api_token else {}
        docs: list[dict] = []
        async with aiohttp.ClientSession() as session:
            for info in self.replica_info():
                port = info.get("sidecar_port")
                if not port:
                    continue
                url = f"http://127.0.0.1:{port}/v1.0/metadata"
                try:
                    async with session.get(
                            url, headers=headers,
                            timeout=aiohttp.ClientTimeout(total=5)) as resp:
                        if resp.status == 200:
                            docs.append(await resp.json())
                except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                    continue
        return docs

    def _merge(self, docs: list[dict]) -> dict[str, dict]:
        """Fold each replica's per-store placement documents into one
        view per store: epoch/shards/assignment come from the document
        with the HIGHEST epoch (the freshest routing truth wins — a
        replica that missed a flip reports a stale map), heat rates are
        summed across replicas before ranking."""
        per_store: dict[str, list[dict]] = {}
        for doc in docs:
            for store, pdoc in (doc.get("placement") or {}).items():
                if isinstance(pdoc, dict):
                    per_store.setdefault(store, []).append(pdoc)
        threshold = heat_threshold_default()
        view: dict[str, dict] = {}
        for store, pdocs in sorted(per_store.items()):
            freshest = max(pdocs, key=lambda d: int(d.get("epoch", 0)))
            rates = merge_heat_docs(pdocs)
            ranking = rank_shards(rates, threshold=threshold)
            # cluster-level planning doc: the freshest routing truth
            # carrying the SUMMED rates, the union of past-hysteresis
            # shards, and every replica's hot-key sketch
            hot: set[int] = set()
            top_keys: dict[str, list[str]] = {}
            for d in pdocs:
                heat = d.get("heat") or {}
                hot.update(int(i) for i in (heat.get("hot") or []))
                for shard, keys in (heat.get("top_keys") or {}).items():
                    bucket = top_keys.setdefault(str(shard), [])
                    bucket.extend(k for k in keys if k not in bucket)
            merged_doc = dict(freshest)
            merged_doc["heat"] = {"rates": rates, "hot": sorted(hot),
                                  "top_keys": top_keys}
            plan = plan_rebalance(merged_doc, threshold=threshold)
            view[store] = {
                "store": store,
                "epoch": int(freshest.get("epoch", 0)),
                "shards": int(freshest.get("shards", 0)),
                "assignment": freshest.get("assignment") or {},
                "leaders": freshest.get("leaders") or {},
                "migration": freshest.get("migration"),
                "replicas_reporting": len(pdocs),
                "ranking": ranking,
                "plan": plan,
            }
        return view

    async def step(self) -> dict[str, dict]:
        docs = await self._fetch_metadata()
        if not docs:
            if not self._warned_unreachable:
                self._warned_unreachable = True
                logger.warning("placement sweep for %s reached no replicas",
                               self.app_id)
            return self.view
        self._warned_unreachable = False
        self.view = self._merge(docs)
        self.last_sweep = time.time()
        for store, entry in self.view.items():
            plan = entry.get("plan")
            if plan and plan.get("action"):
                logger.info(
                    "placement plan for %s/%s: %s shard %s (%s)",
                    self.app_id, store, plan["action"], plan.get("shard"),
                    plan.get("reason"))
        return self.view

    def snapshot(self) -> dict:
        """The admin endpoint's document for this app."""
        return {
            "app_id": self.app_id,
            "last_sweep": self.last_sweep,
            "stores": self.view,
        }

    # -- lifecycle (AutoscaleController's shape) -------------------------

    async def run(self) -> None:
        while True:
            try:
                await self.step()
            except Exception:
                logger.exception("placement sweep failed for %s", self.app_id)
            await asyncio.sleep(self.interval)

    def start(self) -> None:
        self._task = asyncio.create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
