"""Multi-app run configuration.

The analog of the reference's local launch tooling: three ``dapr run``
terminals (snippets/dapr-run-*.md) or the VS Code compound launcher
(.vscode/tasks.json + launch.json:64-80), declaratively in one YAML —
plus the KEDA-style scale block each ACA app carries in Bicep
(processor-backend-service.bicep:158-181) brought down to local
semantics.

```yaml
resources_path: ./components
registry_file: .tasksrunner/apps.json
apps:
  - app_id: tasksmanager-backend-api
    module: samples.tasks_tracker.backend_api:make_app
    app_port: 5103
    sidecar_port: 3500
    env: { TASKS_MANAGER: store }
  - app_id: tasksmanager-backend-processor
    module: samples.tasks_tracker.processor:make_app
    app_port: 5217
    sidecar_port: 3502
    scale:
      min_replicas: 1
      max_replicas: 5
      rules:
        - type: pubsub-backlog        # ≙ KEDA azure-servicebus scaler
          metadata:
            component: dapr-pubsub-servicebus
            topic: tasksavedtopic
            messageCount: "10"
```
"""

from __future__ import annotations

import pathlib
from dataclasses import dataclass, field

import yaml

from tasksrunner.errors import ComponentError


#: every scale-rule type the autoscaler implements (autoscale.py
#: dispatches on these; load_run_config rejects anything else at parse
#: time so `deploy validate` catches the typo, not the first step())
RULE_TYPES = ("pubsub-backlog", "queue-backlog", "http-concurrency",
              "cpu", "memory", "target-p99", "loop-lag")


@dataclass
class ScaleRule:
    type: str  # one of RULE_TYPES
    metadata: dict[str, str] = field(default_factory=dict)


@dataclass
class ScaleSpec:
    min_replicas: int = 1
    max_replicas: int = 1
    rules: list[ScaleRule] = field(default_factory=list)
    #: seconds of low backlog before scaling down (KEDA cooldown analog)
    cooldown_seconds: float = 5.0


@dataclass
class HealthSpec:
    """Liveness probing (≙ ACA's container probes; the platform-side
    restart behavior in SURVEY.md §5.3). The orchestrator GETs the
    app's ``/healthz`` — apps may register their own ``/healthz`` route
    to report real health; the builtin one always returns 204."""

    enabled: bool = True
    interval_seconds: float = 5.0
    #: consecutive failures before the replica is killed + restarted
    failure_threshold: int = 3
    #: grace period after start before the first probe
    initial_delay_seconds: float = 2.0
    #: per-probe timeout
    timeout_seconds: float = 2.0


@dataclass
class AppSpec:
    app_id: str
    module: str  # "pkg.mod:factory"
    app_port: int = 0
    sidecar_port: int = 0
    #: bind address for the app server; "0.0.0.0" = external ingress,
    #: "127.0.0.1" = internal-only (≙ the ACA ingress block,
    #: webapi-backend-service.bicep:94-97)
    host: str = "127.0.0.1"
    env: dict[str, str] = field(default_factory=dict)
    scale: ScaleSpec = field(default_factory=ScaleSpec)
    health: HealthSpec = field(default_factory=HealthSpec)
    #: per-app component grants (≙ the reference's per-app role
    #: assignments, SURVEY.md §5.10). None = unrestricted; a mapping =
    #: least-privilege whitelist (see tasksrunner/security.py).
    grants: dict | None = None


@dataclass
class RunConfig:
    apps: list[AppSpec]
    resources_path: str | None = None
    registry_file: str = ".tasksrunner/apps.json"
    base_dir: pathlib.Path = field(default_factory=pathlib.Path.cwd)

    @property
    def registry_path(self) -> pathlib.Path:
        """``registry_file`` resolved against ``base_dir`` — the ONE
        way to locate the registry. Every consumer must use this: a
        raw ``Path(registry_file)`` resolves against the launching
        shell's cwd instead, silently targeting a different file when
        the config was emitted by ``deploy apply`` elsewhere."""
        p = pathlib.Path(self.registry_file)
        return p if p.is_absolute() else self.base_dir / p
    #: localhost control-plane port (0 = ephemeral). The admin API is
    #: the `az containerapp update / revision restart / logs show`
    #: surface of the orchestrator; its address is advertised in
    #: ``<registry dir>/orchestrator.json`` for the CLI.
    admin_port: int = 0
    #: stamped by `deploy apply` for require_api_token manifests: the
    #: orchestrator refuses to start unauthenticated, no matter which
    #: shell launches the emitted run config
    require_api_token: bool = False
    #: one generated token per app instead of a single shared secret
    #: (≙ one managed identity per container app): each replica gets
    #: only ITS app's token; sidecars accept peer tokens solely for
    #: inbound service invocation
    per_app_tokens: bool = False
    #: mesh lane mTLS (≙ Dapr sentry workload certs): the orchestrator
    #: generates an environment CA + per-app certificates at start and
    #: each replica's sidecar requires/presents them on peer dials
    mesh_tls: bool = False
    #: filled by the orchestrator at start when per_app_tokens is on
    #: (app_id → generated token); not read from YAML
    app_tokens: dict[str, str] = field(default_factory=dict)
    #: path of the emitted token map file (set with app_tokens)
    tokens_file: str | None = None
    #: filled by the orchestrator at start when mesh_tls is on
    #: (app_id → {ca, cert, key} PEM paths); not read from YAML
    mesh_certs: dict[str, dict[str, str]] = field(default_factory=dict)
    #: re-adopt live replicas a previous orchestrator left registered
    #: (crash/kill -9 of the control plane) instead of respawning them —
    #: a control-plane restart must not bounce a healthy data plane
    adopt: bool = True
    #: wait for the control-plane lease instead of exiting when another
    #: orchestrator already holds it; on the holder's death this
    #: process takes over (and, with adopt, inherits its replicas)
    standby: bool = False


def parse_health(health_raw: object) -> HealthSpec:
    """Parse a manifest/run-config ``health:`` block; raises
    ComponentError on bad shape OR bad inner values, so `deploy
    validate` catches what would otherwise crash at run time."""
    if health_raw is None or health_raw is True:
        # bare "health:" / "health: true" = probing with defaults
        health_raw = {}
    if health_raw is False:
        return HealthSpec(enabled=False)
    if not isinstance(health_raw, dict):
        raise ComponentError("health must be a mapping or boolean")
    try:
        return HealthSpec(
            enabled=bool(health_raw.get("enabled", True)),
            interval_seconds=float(health_raw.get("interval_seconds", 5.0)),
            failure_threshold=int(health_raw.get("failure_threshold", 3)),
            initial_delay_seconds=float(
                health_raw.get("initial_delay_seconds", 2.0)),
            timeout_seconds=float(health_raw.get("timeout_seconds", 2.0)),
        )
    except (TypeError, ValueError) as exc:
        raise ComponentError(f"bad health block value: {exc}") from exc


def load_run_config(path: str | pathlib.Path) -> RunConfig:
    path = pathlib.Path(path)
    try:
        doc = yaml.safe_load(path.read_text()) or {}
    except OSError as exc:
        raise ComponentError(f"cannot read run config {path}: {exc}") from exc
    except yaml.YAMLError as exc:
        raise ComponentError(f"cannot parse run config {path}: {exc}") from exc

    apps = []
    for raw in doc.get("apps") or []:
        if "app_id" not in raw or "module" not in raw:
            raise ComponentError("each app needs app_id and module")
        scale_raw = raw.get("scale") or {}
        rules = [
            ScaleRule(type=r.get("type", ""), metadata={
                str(k): str(v) for k, v in (r.get("metadata") or {}).items()
            })
            for r in scale_raw.get("rules") or []
        ]
        for rule in rules:
            if rule.type not in RULE_TYPES:
                raise ComponentError(
                    f"app {raw['app_id']}: unknown scale rule type "
                    f"{rule.type!r} (known: {', '.join(RULE_TYPES)})")
        health = parse_health(raw.get("health", {}))
        grants = raw.get("grants")
        if grants is not None:
            # parse now so `deploy validate` / startup rejects a bad
            # grants block instead of the first denied call at runtime
            from tasksrunner.security import AppGrants
            grants = AppGrants.parse(grants, app_id=str(raw["app_id"])).to_json()
        apps.append(AppSpec(
            app_id=str(raw["app_id"]),
            module=str(raw["module"]),
            app_port=int(raw.get("app_port", 0)),
            sidecar_port=int(raw.get("sidecar_port", 0)),
            host=str(raw.get("host", "127.0.0.1")),
            env={str(k): str(v) for k, v in (raw.get("env") or {}).items()},
            scale=ScaleSpec(
                min_replicas=int(scale_raw.get("min_replicas", 1)),
                max_replicas=int(scale_raw.get("max_replicas", 1)),
                rules=rules,
                cooldown_seconds=float(scale_raw.get("cooldown_seconds", 5.0)),
            ),
            health=health,
            grants=grants,
        ))
    if not apps:
        raise ComponentError(f"run config {path} declares no apps")

    # an explicit base_dir (deploy-apply-emitted configs) anchors all
    # relative paths at the manifest's directory; hand-written configs
    # default to their own directory. A RELATIVE base_dir resolves
    # against the config file, never the launch cwd.
    base = path.resolve().parent
    if doc.get("base_dir"):
        declared = pathlib.Path(doc["base_dir"])
        base = declared if declared.is_absolute() else (base / declared).resolve()
    resources = doc.get("resources_path")
    if resources is not None and not pathlib.Path(resources).is_absolute():
        resources = str(base / resources)
    return RunConfig(
        apps=apps,
        resources_path=resources,
        registry_file=str(doc.get("registry_file", ".tasksrunner/apps.json")),
        base_dir=base,
        admin_port=int(doc.get("admin_port", 0)),
        require_api_token=bool(doc.get("require_api_token", False)),
        per_app_tokens=bool(doc.get("per_app_tokens", False)),
        mesh_tls=bool(doc.get("mesh_tls", False)),
        adopt=bool(doc.get("adopt", True)),
        standby=bool(doc.get("standby", False)),
    )
