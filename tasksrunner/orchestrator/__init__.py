from tasksrunner.orchestrator.config import AppSpec, RunConfig, ScaleRule, load_run_config
from tasksrunner.orchestrator.autoscale import AutoscaleController, read_backlog
from tasksrunner.orchestrator.run import Orchestrator

__all__ = [
    "AppSpec",
    "RunConfig",
    "ScaleRule",
    "load_run_config",
    "AutoscaleController",
    "read_backlog",
    "Orchestrator",
]
