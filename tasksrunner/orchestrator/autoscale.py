"""KEDA-style autoscaling for the local orchestrator.

Replicates the reference's only parallelism mechanism (SURVEY.md §5.8):
the processor scales 1→5 replicas, +1 per 10 messages of Service Bus
topic-subscription backlog
(bicep/modules/container-apps/processor-backend-service.bicep:158-181).
Here the scaler watches the sqlite broker/queue files directly — the
same out-of-band position KEDA occupies (it reads the broker, not the
app) — and tells the orchestrator the desired replica count.

The full ACA trigger taxonomy
(docs/aca/09-aca-autoscale-keda/index.md:27-35) is covered:

==================  ====================================================
``pubsub-backlog``   ≙ the ``azure-servicebus`` custom scaler
                     (+1 replica per ``messageCount`` backlog)
``queue-backlog``    ≙ ``azure-queue`` custom scaler
``http-concurrency`` ≙ the HTTP rule: +1 replica per
                     ``concurrentRequests`` in flight, summed by
                     polling each replica's ``/tasksrunner/stats``
``cpu``              ≙ the CPU rule: replicas sized so per-replica
                     CPU stays under ``utilization`` percent
                     (measured from /proc/<pid>/stat deltas)
``memory``           ≙ the Memory rule: +1 replica per ``megabytes``
                     of total RSS (measured from /proc/<pid>/status)
``target-p99``       latency-target rule: reads each replica's merged
                     histogram view (sidecar ``/v1.0/metadata``),
                     windows the p99 between evaluations, and sizes
                     the fleet to ``ceil(n * p99 / targetSeconds)``
``loop-lag``         saturation rule: +1 replica while any replica's
                     ``event_loop_lag_seconds`` exceeds
                     ``maxLagSeconds`` — the earliest overload signal
                     (docs module 08)
==================  ====================================================

The last two close the loop the observability layer opened: the
autoscaler consumes the replicas' own telemetry instead of polling
proc files, so latency — not just backlog — adds replicas. Their
signal source is the sidecar metadata endpoint, which is
admission-exempt (sidecar.py): a shedding replica still reports the
saturation that should scale it out.

Scale-to-zero is deliberately NOT implemented, for the reason the
workshop rejects it: it would starve cron and input bindings
(docs/aca/09-aca-autoscale-keda/index.md:150-160); min_replicas >= 1
is enforced in config.
"""

from __future__ import annotations

import asyncio
import logging
import math
import pathlib
import time
from typing import Callable

from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import ComponentError
from tasksrunner.observability.metrics import (
    estimate_percentile,
    merge_histogram_snapshots,
    metrics,
)
from tasksrunner.orchestrator.config import RULE_TYPES, AppSpec, ScaleRule

logger = logging.getLogger(__name__)


def read_backlog(rule: ScaleRule, *, app_id: str,
                 components: list[ComponentSpec],
                 base_dir: pathlib.Path) -> int:
    """Read the current backlog the rule watches (opens its own
    connection to the shared file, as KEDA connects to the broker)."""
    meta = rule.metadata
    comp_name = meta.get("component")
    spec = next((s for s in components if s.name == comp_name), None)

    if rule.type == "pubsub-backlog":
        if spec is None:
            raise ComponentError(f"scale rule references unknown component {comp_name!r}")
        topic = meta.get("topic", "")
        group = meta.get("group", app_id)  # subscription named after the app
        from tasksrunner.pubsub.sqlite import open_for_inspection
        # must_exist=False: nothing published yet just means backlog 0
        # (a redisHost component still raises — that broker's backlog
        # is not in any local file and silence would mask the misconfig)
        broker = open_for_inspection(spec, base_dir, must_exist=False)
        try:
            return broker.backlog(topic, group)
        finally:
            broker.close_sync()
    if rule.type == "queue-backlog":
        if spec is None:
            raise ComponentError(f"scale rule references unknown component {comp_name!r}")
        from tasksrunner.bindings.localqueue import open_queue_for_inspection
        queue = open_queue_for_inspection(spec, base_dir, must_exist=False)
        try:
            return queue.backlog()
        finally:
            queue.close()
    raise ComponentError(f"unknown scale rule type {rule.type!r}")


def _read_inflight(replicas: list[dict], timeout: float = 0.5,
                   api_token: str | None = None) -> int:
    """Sum in-flight requests across replicas by polling each one's
    ``/tasksrunner/stats`` (the position of ACA's HTTP scaler: it
    watches traffic, not app internals). Unreachable replicas count 0
    — mid-restart must not wedge the scaler."""
    import json as _json
    import urllib.request

    from tasksrunner.security import TOKEN_HEADER

    total = 0
    for info in replicas:
        port = info.get("app_port")
        if not port:
            continue
        host = info.get("host") or "127.0.0.1"
        if host in ("", "0.0.0.0"):
            host = "127.0.0.1"
        try:
            req = urllib.request.Request(
                f"http://{host}:{port}/tasksrunner/stats",
                headers={TOKEN_HEADER: api_token} if api_token else {})
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                total += int(_json.loads(resp.read()).get("inflight", 0))
        except (OSError, ValueError):
            continue
    return total


def _fetch_replica_metadata(replicas: list[dict], timeout: float = 0.5,
                            api_token: str | None = None) -> list[dict]:
    """GET each replica's sidecar ``/v1.0/metadata`` — the merged
    metrics view PR 3 built (flat snapshot + histograms + kinds).
    Unreachable replicas are skipped, same posture as the stats probe:
    a replica mid-boot or mid-restart must not wedge the scaler.
    Runs inside ``asyncio.to_thread`` via ``desired_replicas``."""
    import json as _json
    import urllib.request

    from tasksrunner.security import TOKEN_HEADER

    docs = []
    for info in replicas:
        port = info.get("sidecar_port")
        if not port:
            continue
        host = info.get("host") or "127.0.0.1"
        if host in ("", "0.0.0.0"):
            host = "127.0.0.1"
        try:
            req = urllib.request.Request(
                f"http://{host}:{port}/v1.0/metadata",
                headers={TOKEN_HEADER: api_token} if api_token else {})
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                docs.append(_json.loads(resp.read()))
        except (OSError, ValueError):
            continue
    return docs


def _read_proc_cpu_ticks(pid: int) -> int | None:
    """utime+stime clock ticks from /proc/<pid>/stat (Linux)."""
    try:
        text = pathlib.Path(f"/proc/{pid}/stat").read_text()
    except OSError:
        return None
    # comm (field 2) may contain spaces — split after the closing paren
    rest = text.rpartition(")")[2].split()
    # rest[0] is field 3 (state); utime/stime are fields 14/15
    return int(rest[11]) + int(rest[12])


def _read_proc_rss_mb(pid: int) -> float:
    try:
        for line in pathlib.Path(f"/proc/{pid}/status").read_text().splitlines():
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0  # kB -> MB
    except OSError:
        pass
    return 0.0


class AutoscaleController:
    """Computes desired replicas per app and drives a scaling callback."""

    def __init__(
        self,
        app: AppSpec,
        components: list[ComponentSpec],
        set_replicas: Callable[[int], "asyncio.Future | None"],
        *,
        base_dir: pathlib.Path | None = None,
        interval: float = 0.5,
        replica_info: Callable[[], list[dict]] | None = None,
        api_token: str | None = None,
    ):
        self.app = app
        self.components = components
        self.set_replicas = set_replicas
        self.base_dir = base_dir or pathlib.Path.cwd()
        self.interval = interval
        #: the app's API token — the stats probe is token-gated when
        #: the replica runs with one (see hosting.build_app_server)
        self.api_token = api_token
        #: live replica inventory ({pid, app_port, host} per replica),
        #: supplied by the orchestrator — the http/cpu/memory rules
        #: measure the replicas themselves, not a shared broker file
        self.replica_info = replica_info or (lambda: [])
        self.current = app.scale.min_replicas
        self._low_since: float | None = None
        self._task: asyncio.Task | None = None
        #: pid -> (monotonic_time, cpu_ticks) from the previous poll,
        #: for CPU-utilization deltas
        self._cpu_prev: dict[int, tuple[float, int]] = {}
        #: metric name -> summed bucket counts at the previous
        #: evaluation, for the target-p99 delta window
        self._p99_prev: dict[str, list[int]] = {}
        #: per-desired_replicas-call metadata cache: one sidecar sweep
        #: feeds every telemetry rule in the same evaluation
        self._metadata_docs: list[dict] | None = None
        #: rules that already logged a full traceback (keyed by
        #: rule type + exception class) — repeats log one line
        self._rule_failed: set[tuple[str, str]] = set()

    def _cpu_percent_total(self, replicas: list[dict]) -> float:
        """Summed per-process CPU%, from /proc tick deltas between
        polls (100 = one fully-busy core). First sight of a pid
        contributes 0 — a delta needs two samples."""
        import os

        clk_tck = os.sysconf("SC_CLK_TCK")
        now = time.monotonic()
        total = 0.0
        live: set[int] = set()
        for info in replicas:
            pid = info.get("pid")
            if not pid:
                continue
            ticks = _read_proc_cpu_ticks(pid)
            if ticks is None:
                continue
            live.add(pid)
            prev = self._cpu_prev.get(pid)
            self._cpu_prev[pid] = (now, ticks)
            if prev is None:
                continue
            dt = now - prev[0]
            if dt <= 0:
                continue
            total += 100.0 * (ticks - prev[1]) / clk_tck / dt
        # drop exited pids so a recycled pid can't inherit stale ticks
        for pid in list(self._cpu_prev):
            if pid not in live:
                del self._cpu_prev[pid]
        return total

    def _replica_metadata(self) -> list[dict]:
        """Sidecar metadata docs for this evaluation (fetched once per
        ``desired_replicas`` call, shared by every telemetry rule)."""
        if self._metadata_docs is None:
            self._metadata_docs = _fetch_replica_metadata(
                self.replica_info(), api_token=self.api_token)
        return self._metadata_docs

    def _target_p99_desired(self, rule: ScaleRule) -> int:
        """Latency-target rule: size the fleet so the *recent* p99 of
        ``metric`` stays at or under ``targetSeconds``.

        Histogram counts are cumulative since process start, so the raw
        p99 would remember the overload forever and the fleet would
        never scale back in. Instead each evaluation diffs the summed
        bucket counts against the previous evaluation (the ``rate()``
        a Prometheus deployment would take) and estimates p99 over just
        that window. Negative deltas — a replica restarted or left the
        fleet — clamp to 0. Fewer than ``minSamples`` new observations
        means no verdict, not pressure.
        """
        meta = rule.metadata
        metric = meta.get("metric", "sidecar_request_latency_seconds")
        target = max(float(meta.get("targetSeconds", 0.5)), 1e-6)
        min_samples = max(int(meta.get("minSamples", 10)), 1)
        docs = self._replica_metadata()
        merged = merge_histogram_snapshots(
            [d.get("histograms") or {} for d in docs])
        hist = merged.get(metric)
        if hist is None:
            self._p99_prev.pop(metric, None)
            return 0
        bounds = hist["bounds"]
        totals = [0] * (len(bounds) + 1)
        for series in hist["series"]:
            for i, c in enumerate(series["counts"]):
                totals[i] += int(c)
        prev = self._p99_prev.get(metric)
        self._p99_prev[metric] = totals
        if prev is None or len(prev) != len(totals):
            window = totals  # first sight: all-time is the best window
        else:
            window = [max(0, c - p) for c, p in zip(totals, prev)]
        if sum(window) < min_samples:
            return 0
        p99 = estimate_percentile(bounds, window, 0.99)
        if p99 <= target:
            return 0
        # latency scales down roughly with fleet size when the load is
        # parallelizable — ask for the proportional fleet, clamped to
        # max_replicas by the caller
        live = max(len(docs), 1)
        return math.ceil(live * p99 / target)

    def _loop_lag_desired(self, rule: ScaleRule) -> int:
        """Saturation rule: any replica's event loop running
        ``maxLagSeconds`` late adds that much latency to everything it
        serves — add a replica until no loop lags. Incremental (+1 per
        evaluation) rather than proportional: lag does not predict how
        many replicas the work needs, only that this fleet is too
        small."""
        max_lag = max(float(rule.metadata.get("maxLagSeconds", 0.1)), 1e-6)
        worst = 0.0
        for doc in self._replica_metadata():
            for key, value in (doc.get("metrics") or {}).items():
                if key.split("{", 1)[0] == "event_loop_lag_seconds":
                    worst = max(worst, float(value))
        if worst <= max_lag:
            return 0
        return self.current + 1

    def _rule_desired(self, rule: ScaleRule) -> int:
        meta = rule.metadata
        if rule.type in ("pubsub-backlog", "queue-backlog"):
            backlog = read_backlog(rule, app_id=self.app.app_id,
                                   components=self.components,
                                   base_dir=self.base_dir)
            per = max(int(meta.get("messageCount", 10)), 1)
            return math.ceil(backlog / per)
        if rule.type == "http-concurrency":
            per = max(int(meta.get("concurrentRequests", 10)), 1)
            return math.ceil(_read_inflight(
                self.replica_info(), api_token=self.api_token) / per)
        if rule.type == "cpu":
            threshold = max(float(meta.get("utilization", 70)), 1.0)
            return math.ceil(
                self._cpu_percent_total(self.replica_info()) / threshold)
        if rule.type == "memory":
            # Per-replica memory budget, stable under BOTH memory
            # shapes. The two naive formulas each fail one of them:
            # KEDA's ceil(sum/budget) ratchets to max_replicas whenever
            # a FIXED per-replica baseline exceeds the budget (each new
            # replica adds its own baseline to the signal, so desired
            # only grows); plain ceil(mean/budget) flip-flops for
            # LOAD-PROPORTIONAL memory (scale-out halves the mean,
            # which immediately argues for scale-in). Composite:
            #   scale-out pressure from the mean (some replica over
            #   budget), scale-in only if the whole footprint would
            #   still fit the smaller fleet (sum), never exceeding the
            #   current count on the sum term (breaks the ratchet).
            per_mb = max(float(meta.get("megabytes", 512)), 1.0)
            rss = [_read_proc_rss_mb(info["pid"])
                   for info in self.replica_info() if info.get("pid")]
            if not rss:
                return 0
            n = len(rss)
            mean_term = math.ceil((sum(rss) / n) / per_mb)
            sum_term = min(n, math.ceil(sum(rss) / per_mb))
            return max(mean_term, sum_term)
        if rule.type == "target-p99":
            return self._target_p99_desired(rule)
        if rule.type == "loop-lag":
            return self._loop_lag_desired(rule)
        raise ComponentError(f"unknown scale rule type {rule.type!r} "
                             f"(known: {RULE_TYPES})")

    def desired_replicas(self) -> int:
        """Max over all rules' desired counts, clamped to bounds —
        the KEDA multi-trigger formula.

        Rules are isolated: one raising rule (a deleted queue file, an
        unreachable replica set) is logged and skipped, not allowed to
        abort the evaluation — the old behavior silently froze ALL
        scaling while one signal was broken. Only if every rule fails
        does the scaler hold the current count (a telemetry blackout
        is not evidence that the load went away). The verdict lands in
        the ``autoscale_desired_replicas`` gauge either way, so the
        decision stream is observable next to the signals that fed it.
        """
        scale = self.app.scale
        self._metadata_docs = None  # fresh sidecar sweep per evaluation
        if not scale.rules:
            return scale.min_replicas
        verdicts = []
        for rule in scale.rules:
            try:
                verdicts.append(self._rule_desired(rule))
            except Exception as exc:
                key = (rule.type, type(exc).__name__)
                if key not in self._rule_failed:
                    self._rule_failed.add(key)
                    logger.exception(
                        "scale rule %s for %s failed; skipping it",
                        rule.type, self.app.app_id)
                else:
                    logger.warning(
                        "scale rule %s for %s still failing (%s); "
                        "skipping it", rule.type, self.app.app_id, exc)
        desired = max(verdicts) if verdicts else self.current
        desired = max(scale.min_replicas, min(scale.max_replicas, desired))
        # set_gauge is thread-safe; this runs under asyncio.to_thread
        metrics.set_gauge("autoscale_desired_replicas", float(desired),
                          app=self.app.app_id)
        return desired

    async def step(self) -> int:
        desired = await asyncio.to_thread(self.desired_replicas)
        now = time.monotonic()
        if desired > self.current:
            # scale out immediately (KEDA behavior)
            self._low_since = None
            logger.info("scaling %s out: %d -> %d replicas",
                        self.app.app_id, self.current, desired)
            self.current = desired
            result = self.set_replicas(desired)
            if asyncio.isfuture(result) or asyncio.iscoroutine(result):
                await result
        elif desired < self.current:
            # scale in only after sustained low backlog (cooldown)
            if self._low_since is None:
                self._low_since = now
            elif now - self._low_since >= self.app.scale.cooldown_seconds:
                logger.info("scaling %s in: %d -> %d replicas",
                            self.app.app_id, self.current, desired)
                self.current = desired
                self._low_since = None
                result = self.set_replicas(desired)
                if asyncio.isfuture(result) or asyncio.iscoroutine(result):
                    await result
        else:
            self._low_since = None
        return self.current

    async def run(self) -> None:
        while True:
            try:
                await self.step()
            except Exception:
                logger.exception("autoscale step failed for %s", self.app.app_id)
            await asyncio.sleep(self.interval)

    def start(self) -> None:
        self._task = asyncio.create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
