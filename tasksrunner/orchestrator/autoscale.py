"""KEDA-style backlog autoscaling for the local orchestrator.

Replicates the reference's only parallelism mechanism (SURVEY.md §5.8):
the processor scales 1→5 replicas, +1 per 10 messages of Service Bus
topic-subscription backlog
(bicep/modules/container-apps/processor-backend-service.bicep:158-181).
Here the scaler watches the sqlite broker/queue files directly — the
same out-of-band position KEDA occupies (it reads the broker, not the
app) — and tells the orchestrator the desired replica count.

Scale-to-zero is deliberately NOT implemented, for the reason the
workshop rejects it: it would starve cron and input bindings
(docs/aca/09-aca-autoscale-keda/index.md:150-160); min_replicas >= 1
is enforced in config.
"""

from __future__ import annotations

import asyncio
import logging
import math
import pathlib
import time
from typing import Callable

from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import ComponentError
from tasksrunner.orchestrator.config import AppSpec, ScaleRule

logger = logging.getLogger(__name__)


def read_backlog(rule: ScaleRule, *, app_id: str,
                 components: list[ComponentSpec],
                 base_dir: pathlib.Path) -> int:
    """Read the current backlog the rule watches (opens its own
    connection to the shared file, as KEDA connects to the broker)."""
    meta = rule.metadata
    comp_name = meta.get("component")
    spec = next((s for s in components if s.name == comp_name), None)

    if rule.type == "pubsub-backlog":
        if spec is None:
            raise ComponentError(f"scale rule references unknown component {comp_name!r}")
        topic = meta.get("topic", "")
        group = meta.get("group", app_id)  # subscription named after the app
        from tasksrunner.pubsub.sqlite import open_for_inspection
        # must_exist=False: nothing published yet just means backlog 0
        # (a redisHost component still raises — that broker's backlog
        # is not in any local file and silence would mask the misconfig)
        broker = open_for_inspection(spec, base_dir, must_exist=False)
        try:
            return broker.backlog(topic, group)
        finally:
            broker.close_sync()
    if rule.type == "queue-backlog":
        if spec is None:
            raise ComponentError(f"scale rule references unknown component {comp_name!r}")
        from tasksrunner.bindings.localqueue import open_queue_for_inspection
        queue = open_queue_for_inspection(spec, base_dir, must_exist=False)
        try:
            return queue.backlog()
        finally:
            queue.close()
    raise ComponentError(f"unknown scale rule type {rule.type!r}")


class AutoscaleController:
    """Computes desired replicas per app and drives a scaling callback."""

    def __init__(
        self,
        app: AppSpec,
        components: list[ComponentSpec],
        set_replicas: Callable[[int], "asyncio.Future | None"],
        *,
        base_dir: pathlib.Path | None = None,
        interval: float = 0.5,
    ):
        self.app = app
        self.components = components
        self.set_replicas = set_replicas
        self.base_dir = base_dir or pathlib.Path.cwd()
        self.interval = interval
        self.current = app.scale.min_replicas
        self._low_since: float | None = None
        self._task: asyncio.Task | None = None

    def desired_replicas(self) -> int:
        """+1 replica per messageCount of backlog, clamped to bounds
        (the KEDA azure-servicebus formula)."""
        scale = self.app.scale
        if not scale.rules:
            return scale.min_replicas
        desired = 0
        for rule in scale.rules:
            backlog = read_backlog(rule, app_id=self.app.app_id,
                                   components=self.components,
                                   base_dir=self.base_dir)
            per = max(int(rule.metadata.get("messageCount", 10)), 1)
            desired = max(desired, math.ceil(backlog / per))
        return max(scale.min_replicas, min(scale.max_replicas, desired))

    async def step(self) -> int:
        desired = await asyncio.to_thread(self.desired_replicas)
        now = time.monotonic()
        if desired > self.current:
            # scale out immediately (KEDA behavior)
            self._low_since = None
            logger.info("scaling %s out: %d -> %d replicas",
                        self.app.app_id, self.current, desired)
            self.current = desired
            result = self.set_replicas(desired)
            if asyncio.isfuture(result) or asyncio.iscoroutine(result):
                await result
        elif desired < self.current:
            # scale in only after sustained low backlog (cooldown)
            if self._low_since is None:
                self._low_since = now
            elif now - self._low_since >= self.app.scale.cooldown_seconds:
                logger.info("scaling %s in: %d -> %d replicas",
                            self.app.app_id, self.current, desired)
                self.current = desired
                self._low_since = None
                result = self.set_replicas(desired)
                if asyncio.isfuture(result) or asyncio.iscoroutine(result):
                    await result
        else:
            self._low_since = None
        return self.current

    async def run(self) -> None:
        while True:
            try:
                await self.step()
            except Exception:
                logger.exception("autoscale step failed for %s", self.app.app_id)
            await asyncio.sleep(self.interval)

    def start(self) -> None:
        self._task = asyncio.create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
