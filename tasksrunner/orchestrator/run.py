"""The multi-app orchestrator: process supervision + log multiplexing
+ autoscaling.

≙ running three ``dapr run`` terminals (snippets/dapr-run-*.md), the
VS Code compound launcher, ACA's restart-on-crash (single-revision
mode, SURVEY.md §5.3), and the KEDA scaler (§5.8) — in one local
process.

Each replica is a subprocess running ``python -m tasksrunner host
<module>`` (app server + sidecar in one process, HTTP between them).
Replica 0 owns the configured ports and the name-registry entry;
scale-out replicas get ephemeral ports and skip registration — they
participate through competing consumption on the shared broker, which
is exactly how extra ACA replicas of the processor participate.
"""

from __future__ import annotations

import asyncio
import logging
import os
import re
import signal
import sys
import time

from tasksrunner.orchestrator.autoscale import AutoscaleController
from tasksrunner.orchestrator.config import AppSpec, RunConfig
from tasksrunner.component.loader import load_components

logger = logging.getLogger(__name__)

RESTART_BACKOFF = [0.2, 0.5, 1.0, 2.0, 5.0]

#: emitted by ``tasksrunner host`` once its servers are listening;
#: parsed here so the orchestrator learns ephemeral replica ports
_READY_RE = re.compile(
    r"ready app=\S+ app_port=(\d+) sidecar_port=(\d+)")


class Replica:
    def __init__(self, app: AppSpec, index: int, config: RunConfig):
        self.app = app
        self.index = index
        self.config = config
        self.proc: asyncio.subprocess.Process | None = None
        self._pump: asyncio.Task | None = None
        self._prober: asyncio.Task | None = None
        self.restarts = 0
        #: restarts forced by failed liveness probes (vs. crashes)
        self.health_restarts = 0
        self.stopping = False
        #: (app_port, sidecar_port) parsed from the host's ready line
        self.ports: tuple[int, int] | None = None
        self.ready = asyncio.Event()

    @property
    def tag(self) -> str:
        return f"{self.app.app_id}·{self.index}"

    def _command(self) -> list[str]:
        cmd = [
            sys.executable, "-m", "tasksrunner", "host", self.app.module,
            "--app-id", self.app.app_id,
            "--registry-file", self.config.registry_file,
        ]
        if self.config.resources_path:
            cmd += ["--components", self.config.resources_path]
        cmd += ["--host", self.app.host]
        if self.index == 0:
            cmd += ["--app-port", str(self.app.app_port),
                    "--sidecar-port", str(self.app.sidecar_port)]
        else:
            cmd += ["--app-port", "0", "--sidecar-port", "0", "--no-register"]
        return cmd

    async def start(self) -> None:
        # retire the previous incarnation's log pump first — a stale
        # pump could deliver the old buffered ready line into the new
        # incarnation's readiness state (wrong ports)
        if self._pump is not None:
            self._pump.cancel()
            try:
                await self._pump
            except asyncio.CancelledError:
                pass
            self._pump = None
        # fresh readiness state per incarnation (ports may change)
        self.ports = None
        self.ready = asyncio.Event()
        env = dict(os.environ)
        env.update(self.app.env)
        env["TASKSRUNNER_APP_ID"] = self.app.app_id
        env["TASKSRUNNER_REPLICA"] = str(self.index)
        # the orchestrator's import context must reach the replicas
        # (run configs may live outside the package root)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (os.getcwd(), env.get("PYTHONPATH")) if p)
        self.proc = await asyncio.create_subprocess_exec(
            *self._command(),
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            env=env,
            cwd=self.config.base_dir,
        )
        self._pump = asyncio.create_task(self._pump_logs())
        if self.app.health.enabled:
            if self._prober is not None:
                self._prober.cancel()
            self._prober = asyncio.create_task(self._probe_liveness())
        logger.info("started replica %s (pid %d)", self.tag, self.proc.pid)

    async def _pump_logs(self) -> None:
        assert self.proc is not None and self.proc.stdout is not None
        async for line in self.proc.stdout:
            text = line.decode("utf-8", "replace").rstrip()
            m = _READY_RE.search(text)
            if m:
                self.ports = (int(m.group(1)), int(m.group(2)))
                self.ready.set()
            print(f"[{self.tag}] {text}", flush=True)

    async def _probe_liveness(self) -> None:
        """GET the app's /healthz; kill the process after N consecutive
        failures so supervise() restarts it (≙ ACA liveness probes +
        restart-on-unhealthy, SURVEY.md §5.3)."""
        import aiohttp

        health = self.app.health
        try:
            await asyncio.wait_for(self.ready.wait(), timeout=60.0)
        except asyncio.TimeoutError:
            logger.warning("replica %s never reported ready; liveness "
                           "probing disabled for this incarnation", self.tag)
            return
        app_port = self.ports[0]
        probe_host = ("127.0.0.1" if self.app.host in ("", "0.0.0.0")
                      else self.app.host)
        url = f"http://{probe_host}:{app_port}/healthz"
        failures = 0
        await asyncio.sleep(health.initial_delay_seconds)
        timeout = aiohttp.ClientTimeout(total=health.timeout_seconds)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            while not self.stopping:
                try:
                    async with session.get(url) as resp:
                        healthy = resp.status < 500
                except (OSError, asyncio.TimeoutError, aiohttp.ClientError):
                    # ClientError covers aiohttp failures that are NOT
                    # OSErrors (e.g. ServerDisconnectedError) — any of
                    # them is a failed probe, never a dead prober
                    healthy = False
                if healthy:
                    failures = 0
                else:
                    failures += 1
                    logger.warning("liveness probe failed for %s (%d/%d)",
                                   self.tag, failures, health.failure_threshold)
                    if failures >= health.failure_threshold:
                        logger.warning(
                            "replica %s unhealthy — killing for restart", self.tag)
                        self.health_restarts += 1
                        if self.proc is not None and self.proc.returncode is None:
                            self.proc.kill()
                        return  # supervise() restarts us with a new prober
                await asyncio.sleep(health.interval_seconds)

    async def stop(self) -> None:
        self.stopping = True
        if self._prober is not None:
            self._prober.cancel()
            try:
                await self._prober
            except asyncio.CancelledError:
                pass
            self._prober = None
        if self.proc is not None and self.proc.returncode is None:
            self.proc.terminate()
            try:
                await asyncio.wait_for(self.proc.wait(), timeout=5.0)
            except asyncio.TimeoutError:
                self.proc.kill()
                await self.proc.wait()
        if self._pump is not None:
            self._pump.cancel()
            try:
                await self._pump
            except asyncio.CancelledError:
                pass

    async def supervise(self) -> None:
        """Restart on crash with bounded backoff (ACA restart analog)."""
        while not self.stopping:
            assert self.proc is not None
            code = await self.proc.wait()
            if self.stopping:
                return
            backoff = RESTART_BACKOFF[min(self.restarts, len(RESTART_BACKOFF) - 1)]
            logger.warning("replica %s exited with %s; restarting in %.1fs",
                           self.tag, code, backoff)
            self.restarts += 1
            await asyncio.sleep(backoff)
            if not self.stopping:
                await self.start()


class Orchestrator:
    def __init__(self, config: RunConfig):
        self.config = config
        self.replicas: dict[str, list[Replica]] = {}
        self._supervisors: list[asyncio.Task] = []
        self._scalers: list[AutoscaleController] = []
        self._components = (
            load_components(config.resources_path) if config.resources_path else []
        )

    async def start(self) -> None:
        for app in self.config.apps:
            self.replicas[app.app_id] = []
            for i in range(app.scale.min_replicas):
                await self._add_replica(app)
            if app.scale.rules:
                scaler = AutoscaleController(
                    app, self._components,
                    lambda n, a=app: self._set_replicas(a, n),
                    base_dir=self.config.base_dir,
                )
                scaler.start()
                self._scalers.append(scaler)

    async def _add_replica(self, app: AppSpec) -> None:
        replica = Replica(app, len(self.replicas[app.app_id]), self.config)
        self.replicas[app.app_id].append(replica)
        await replica.start()
        self._supervisors.append(asyncio.create_task(replica.supervise()))

    async def _set_replicas(self, app: AppSpec, desired: int) -> None:
        current = self.replicas[app.app_id]
        while len(current) < desired:
            await self._add_replica(app)
        while len(current) > desired:
            victim = current.pop()  # never replica 0 (desired >= min >= 1)
            await victim.stop()

    def replica_count(self, app_id: str) -> int:
        return len(self.replicas.get(app_id, []))

    async def wait(self) -> None:
        """Run until interrupted."""
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # pragma: no cover - non-unix
                pass
        await stop.wait()

    async def stop(self) -> None:
        for scaler in self._scalers:
            await scaler.stop()
        for group in self.replicas.values():
            for replica in group:
                await replica.stop()
        for task in self._supervisors:
            task.cancel()
        for task in self._supervisors:
            try:
                await task
            except asyncio.CancelledError:
                pass
        self._supervisors.clear()


async def run_from_config(config: RunConfig) -> None:
    orch = Orchestrator(config)
    await orch.start()
    apps = ", ".join(a.app_id for a in config.apps)
    logger.info("orchestrator running apps: %s (ctrl-c to stop)", apps)
    try:
        await orch.wait()
    finally:
        await orch.stop()
