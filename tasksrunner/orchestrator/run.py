"""The multi-app orchestrator: process supervision + log multiplexing
+ autoscaling.

≙ running three ``dapr run`` terminals (snippets/dapr-run-*.md), the
VS Code compound launcher, ACA's restart-on-crash (single-revision
mode, SURVEY.md §5.3), and the KEDA scaler (§5.8) — in one local
process.

Each replica is a subprocess running ``python -m tasksrunner host
<module>`` (app server + sidecar in one process, HTTP between them).
Replica 0 owns the configured ports; scale-out replicas take
ephemeral ports. Every replica registers under the app-id (the
registry holds a replica list, and peers' invokes round-robin across
it — ACA's ingress load-balancing) and competes on the shared broker,
which is exactly how extra ACA replicas participate on both planes.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import re
import signal
import sys
import time
import uuid

from tasksrunner.envflag import env_flag
from tasksrunner.orchestrator.autoscale import AutoscaleController
from tasksrunner.orchestrator.config import AppSpec, RunConfig
from tasksrunner.security import TOKEN_ENV as _TOKEN_ENV
from tasksrunner.component.loader import load_components

logger = logging.getLogger(__name__)

RESTART_BACKOFF = [0.2, 0.5, 1.0, 2.0, 5.0]

#: emitted by ``tasksrunner host`` once its servers are listening;
#: parsed here so the orchestrator learns ephemeral replica ports
_READY_RE = re.compile(
    r"ready app=\S+ app_port=(\d+) sidecar_port=(\d+)")


class _AdoptedProc:
    """The supervisor-facing slice of an asyncio subprocess Process,
    duck-typed around a replica process a PREVIOUS orchestrator
    spawned. A restarted (or standby-takeover) control plane cannot
    ``waitpid`` a process it never forked, so liveness comes from the
    registry's one predicate (``NameResolver.local_pid_dead``, with
    its pid-recycling guard) and ``wait()`` polls it. The exact exit
    code of a non-child is unknowable; a detected death reports -9."""

    def __init__(self, pid: int, registered_at: float | None):
        self.pid = pid
        self._registered_at = registered_at
        self._code: int | None = None

    @property
    def returncode(self) -> int | None:
        if self._code is None:
            from tasksrunner.invoke.resolver import NameResolver
            if NameResolver.local_pid_dead(
                    "127.0.0.1", self.pid, self._registered_at):
                self._code = -9
        return self._code

    async def wait(self) -> int:
        while self.returncode is None:
            await asyncio.sleep(0.2)
        return self._code

    def terminate(self) -> None:
        try:
            os.kill(self.pid, signal.SIGTERM)
        except ProcessLookupError:
            self._code = -15

    def kill(self) -> None:
        try:
            os.kill(self.pid, signal.SIGKILL)
        except ProcessLookupError:
            self._code = -9


class Replica:
    def __init__(self, app: AppSpec, index: int, config: RunConfig):
        self.app = app
        self.index = index
        self.config = config
        self.proc: asyncio.subprocess.Process | None = None
        self._pump: asyncio.Task | None = None
        self._prober: asyncio.Task | None = None
        self.restarts = 0
        #: restarts forced by failed liveness probes (vs. crashes)
        self.health_restarts = 0
        self.stopping = False
        #: set by the admin API before terminating: supervise() then
        #: restarts immediately without counting it as a crash
        self.manual_restart = False
        #: (app_port, sidecar_port) parsed from the host's ready line
        self.ports: tuple[int, int] | None = None
        self.ready = asyncio.Event()
        self.started_at: float | None = None
        #: recent output lines, served by `tasksrunner logs`
        #: (≙ `az containerapp logs show`)
        self.log_buffer: collections.deque[str] = collections.deque(maxlen=2000)

    @property
    def tag(self) -> str:
        return f"{self.app.app_id}·{self.index}"

    def _command(self) -> list[str]:
        cmd = [
            sys.executable, "-m", "tasksrunner", "host", self.app.module,
            "--app-id", self.app.app_id,
            "--registry-file", self.config.registry_file,
        ]
        if self.config.resources_path:
            cmd += ["--components", self.config.resources_path]
        cmd += ["--host", self.app.host]
        if self.index == 0:
            cmd += ["--app-port", str(self.app.app_port),
                    "--sidecar-port", str(self.app.sidecar_port)]
        else:
            # scale-out replicas take ephemeral ports and REGISTER them
            # (round 4): every serving replica joins the app's entry in
            # the registry, and peers' invokes round-robin across them —
            # ACA's ingress load-balancing, not just competing consumers
            cmd += ["--app-port", "0", "--sidecar-port", "0"]
        return cmd

    async def start(self) -> None:
        # retire the previous incarnation's log pump first — a stale
        # pump could deliver the old buffered ready line into the new
        # incarnation's readiness state (wrong ports)
        if self._pump is not None:
            self._pump.cancel()
            try:
                await self._pump
            except asyncio.CancelledError:
                pass
            self._pump = None
        # fresh readiness state per incarnation (ports may change)
        self.ports = None
        self.ready = asyncio.Event()
        env = dict(os.environ)
        env.update(self.app.env)
        env["TASKSRUNNER_APP_ID"] = self.app.app_id
        env["TASKSRUNNER_REPLICA"] = str(self.index)
        if self.app.grants is not None:
            # least-privilege grants ride to the replica's runtime
            # (security.py AppGrants; ≙ per-app role assignments)
            import json as _json
            env["TASKSRUNNER_GRANTS"] = _json.dumps(self.app.grants)
        if self.config.app_tokens:
            # per-app identity: the replica gets ONLY its own token;
            # the map file lets its sidecar verify inbound peers
            from tasksrunner.security import TOKENS_FILE_ENV
            env[_TOKEN_ENV] = self.config.app_tokens[self.app.app_id]
            env[TOKENS_FILE_ENV] = self.config.tokens_file or ""
        if self.config.mesh_certs:
            # mesh mTLS (≙ Dapr sentry workload certs): each replica
            # gets the environment CA + ITS app's cert/key paths
            from tasksrunner.invoke.pki import CA_ENV, CERT_ENV, KEY_ENV
            paths = self.config.mesh_certs[self.app.app_id]
            env[CA_ENV] = paths["ca"]
            env[CERT_ENV] = paths["cert"]
            env[KEY_ENV] = paths["key"]
        # the orchestrator's import context must reach the replicas
        # (run configs may live outside the package root)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (os.getcwd(), env.get("PYTHONPATH")) if p)
        self.proc = await asyncio.create_subprocess_exec(
            *self._command(),
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            env=env,
            cwd=self.config.base_dir,
        )
        self.started_at = time.time()
        self._pump = asyncio.create_task(self._pump_logs())
        if self.app.health.enabled:
            if self._prober is not None:
                self._prober.cancel()
            self._prober = asyncio.create_task(self._probe_liveness())
        logger.info("started replica %s (pid %d)", self.tag, self.proc.pid)

    def adopt(self, addr) -> None:
        """Wire this Replica around an ALREADY RUNNING host process a
        previous orchestrator registered, instead of spawning one. No
        log pump (its stdout pipe belongs to the dead parent) — but
        readiness, ports, liveness probing, and supervise() all work;
        when the adopted process eventually dies, supervise() respawns
        a normal child in its place."""
        self.proc = _AdoptedProc(addr.pid, addr.registered_at)
        self.ports = (addr.app_port or 0, addr.sidecar_port)
        self.ready.set()
        self.started_at = addr.registered_at or time.time()
        self.log_buffer.append(
            f"(adopted running pid {addr.pid}; earlier output went to "
            "the previous orchestrator)")
        if self.app.health.enabled:
            self._prober = asyncio.create_task(self._probe_liveness())

    async def _pump_logs(self) -> None:
        assert self.proc is not None and self.proc.stdout is not None
        async for line in self.proc.stdout:
            text = line.decode("utf-8", "replace").rstrip()
            m = _READY_RE.search(text)
            if m:
                self.ports = (int(m.group(1)), int(m.group(2)))
                self.ready.set()
            self.log_buffer.append(text)
            print(f"[{self.tag}] {text}", flush=True)

    async def _probe_liveness(self) -> None:
        """GET the app's /healthz; kill the process after N consecutive
        failures so supervise() restarts it (≙ ACA liveness probes +
        restart-on-unhealthy, SURVEY.md §5.3)."""
        import aiohttp

        health = self.app.health
        try:
            await asyncio.wait_for(self.ready.wait(), timeout=60.0)
        except asyncio.TimeoutError:
            logger.warning("replica %s never reported ready; liveness "
                           "probing disabled for this incarnation", self.tag)
            return
        app_port = self.ports[0]
        probe_host = ("127.0.0.1" if self.app.host in ("", "0.0.0.0")
                      else self.app.host)
        url = f"http://{probe_host}:{app_port}/healthz"
        failures = 0
        await asyncio.sleep(health.initial_delay_seconds)
        timeout = aiohttp.ClientTimeout(total=health.timeout_seconds)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            while not self.stopping:
                try:
                    async with session.get(url) as resp:
                        healthy = resp.status < 500
                except (OSError, asyncio.TimeoutError, aiohttp.ClientError):
                    # ClientError covers aiohttp failures that are NOT
                    # OSErrors (e.g. ServerDisconnectedError) — any of
                    # them is a failed probe, never a dead prober
                    healthy = False
                if healthy:
                    failures = 0
                else:
                    failures += 1
                    logger.warning("liveness probe failed for %s (%d/%d)",
                                   self.tag, failures, health.failure_threshold)
                    if failures >= health.failure_threshold:
                        logger.warning(
                            "replica %s unhealthy — killing for restart", self.tag)
                        self.health_restarts += 1
                        if self.proc is not None and self.proc.returncode is None:
                            self.proc.kill()
                        return  # supervise() restarts us with a new prober
                await asyncio.sleep(health.interval_seconds)

    async def stop(self) -> None:
        self.stopping = True
        if self._prober is not None:
            self._prober.cancel()
            try:
                await self._prober
            except asyncio.CancelledError:
                pass
            self._prober = None
        if self.proc is not None and self.proc.returncode is None:
            self.proc.terminate()
            try:
                await asyncio.wait_for(self.proc.wait(), timeout=5.0)
            except asyncio.TimeoutError:
                self.proc.kill()
                await self.proc.wait()
        if self._pump is not None:
            self._pump.cancel()
            try:
                await self._pump
            except asyncio.CancelledError:
                pass

    async def supervise(self) -> None:
        """Restart on crash with bounded backoff (ACA restart analog)."""
        while not self.stopping:
            assert self.proc is not None
            dead_pid = self.proc.pid
            code = await self.proc.wait()
            # evict the dead incarnation's registry entry NOW: a
            # SIGKILLed replica never unregistered itself, and leaving
            # it in rotation turns every Nth invoke into a
            # connect-refused retry until the restart lands
            try:
                from tasksrunner.invoke.resolver import NameResolver
                # off-loop: the registry mutation busy-waits on a lock
                # file (worst case seconds if the dead replica held it)
                # and must not freeze the supervisor's event loop
                await asyncio.to_thread(
                    NameResolver(registry_file=self.config.registry_path
                                 ).unregister,
                    self.app.app_id, pid=dead_pid)
            except OSError:  # pragma: no cover - registry dir gone at teardown
                pass
            if self.stopping:
                return
            if self.manual_restart:
                # operator-requested (admin restart / env update): not a
                # crash — no backoff, no crash-counter increment
                self.manual_restart = False
                logger.info("replica %s restarting on request", self.tag)
                await self.start()
                continue
            backoff = RESTART_BACKOFF[min(self.restarts, len(RESTART_BACKOFF) - 1)]
            logger.warning("replica %s exited with %s; restarting in %.1fs",
                           self.tag, code, backoff)
            self.restarts += 1
            await asyncio.sleep(backoff)
            if not self.stopping:
                await self.start()


class Orchestrator:
    def __init__(self, config: RunConfig):
        self.config = config
        self.replicas: dict[str, list[Replica]] = {}
        self._supervisors: list[asyncio.Task] = []
        self._scalers: list[AutoscaleController] = []
        #: per-app elastic-placement sweeps (TASKSRUNNER_RESHARD);
        #: app_id → controller, read by /admin/placement
        self.placement: dict[str, "PlacementController"] = {}
        self._components = (
            load_components(config.resources_path) if config.resources_path else []
        )
        #: per-app config-change history (≙ ACA revisions: every env or
        #: scale template change makes a new numbered revision; the
        #: newest is the active one — single-revision mode, SURVEY §5.3)
        self.revisions: dict[str, list[dict]] = {}
        self._admin = None
        #: control-plane lease: at most one live orchestrator per
        #: registry dir; a standby waits on it and takes over (reusing
        #: the shard-leadership Lease — same fencing, same liveness)
        self._cp_store = None
        self._cp_lease = None
        # pid alone is not unique enough: a standby in the SAME process
        # (tests, embedded control planes) must not alias the holder's
        # identity, or its acquire would read as the holder renewing
        self._cp_owner = f"orchestrator-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self._cp_epoch: int | None = None
        self._cp_renewer: asyncio.Task | None = None

    def _record_revision(self, app_id: str, reason: str, **details) -> dict:
        history = self.revisions.setdefault(app_id, [])
        for rev in history:
            rev["active"] = False
        entry = {"revision": len(history) + 1, "created": time.time(),
                 "reason": reason, "active": True, **details}
        history.append(entry)
        return entry

    async def start(self) -> None:
        # control plane first: two orchestrators adopting/spawning over
        # one registry would fight for ports and registry entries
        await self._acquire_control_plane()
        # sweep entries a previous SIGKILLed topology left behind —
        # without this, the new replicas share ports with ghost entries
        # that `ps` then reports healthy (the live process answers the
        # dead entry's probe) and invokes gamble on the rotation
        try:
            from tasksrunner.invoke.resolver import NameResolver
            registry = self.config.registry_path
            if registry.is_file():
                pruned = await asyncio.get_running_loop().run_in_executor(
                    None, NameResolver(registry_file=registry).prune_dead_local)
                if pruned:
                    logger.info("pruned %d stale registry entr%s from a "
                                "previous run: %s", len(pruned),
                                "y" if len(pruned) == 1 else "ies",
                                ", ".join(f"{a} (pid {p})" for a, p in pruned))
        except OSError:  # pragma: no cover - registry unreadable
            pass
        if self.config.per_app_tokens and not self.config.app_tokens:
            self._issue_app_tokens()
        if self.config.mesh_tls and not self.config.mesh_certs:
            # key generation + PEM writes are real disk work — keep the
            # loop responsive during startup
            await asyncio.to_thread(self._issue_mesh_certs)
        adopted: dict[str, list] = {}
        if self.config.adopt:
            # registry reads busy-wait on the lock file — off-loop
            adopted = await asyncio.to_thread(self._find_adoptable)
        for app in self.config.apps:
            self.replicas[app.app_id] = []
            survivors = adopted.get(app.app_id, [])
            for addr in survivors[:app.scale.max_replicas]:
                self._adopt_replica(app, addr)
            if survivors:
                # a control-plane restart re-adopts the healthy data
                # plane instead of bouncing it: no respawn, no dropped
                # in-flight work, same pids
                self._record_revision(
                    app.app_id,
                    f"adopted {len(self.replicas[app.app_id])} running "
                    "replica(s) from a previous orchestrator")
            else:
                self._record_revision(app.app_id, "initial deploy")
            while len(self.replicas[app.app_id]) < app.scale.min_replicas:
                await self._add_replica(app)
            if app.scale.rules:
                scaler = AutoscaleController(
                    app, self._components,
                    lambda n, a=app: self._set_replicas(a, n),
                    base_dir=self.config.base_dir,
                    replica_info=lambda a=app: self._replica_info(a.app_id),
                    # replicas gate /tasksrunner/stats on their token;
                    # the scaler must authenticate like any client
                    api_token=(self.config.app_tokens.get(app.app_id)
                               if self.config.app_tokens
                               else os.environ.get(_TOKEN_ENV)),
                )
                scaler.start()
                self._scalers.append(scaler)
            if env_flag("TASKSRUNNER_RESHARD", default=False):
                from tasksrunner.orchestrator.placement import (
                    PlacementController,
                )
                controller = PlacementController(
                    app.app_id,
                    lambda a=app: self._replica_info(a.app_id),
                    api_token=(self.config.app_tokens.get(app.app_id)
                               if self.config.app_tokens
                               else os.environ.get(_TOKEN_ENV)),
                )
                controller.start()
                self.placement[app.app_id] = controller
        from tasksrunner.orchestrator.admin import AdminServer
        self._admin = AdminServer(self, port=self.config.admin_port)
        await self._admin.start()

    async def _acquire_control_plane(self) -> None:
        """Acquire (or, in standby mode, wait for) the per-registry-dir
        orchestrator lease. Epoch-fenced exactly like shard leadership:
        the record names owner/pid/expiry, takeover needs the holder
        dead or expired, and every acquisition bumps the epoch."""
        from tasksrunner.state.replication import Lease, lease_seconds_default
        from tasksrunner.state.sqlite import SqliteStateStore

        registry_dir = self.config.registry_path.parent
        await asyncio.to_thread(
            lambda: registry_dir.mkdir(parents=True, exist_ok=True))
        self._cp_store = SqliteStateStore(
            "orchestrator.control-plane", registry_dir / "control-plane.db")
        self._cp_lease = Lease(self._cp_store, "control-plane")
        lease_s = lease_seconds_default()
        announced = False
        while True:
            epoch = await self._cp_lease.acquire(self._cp_owner)
            if epoch is not None:
                self._cp_epoch = epoch
                break
            holder = await self._cp_lease.peek() or {}
            if not self.config.standby:
                await self._cp_store.aclose()
                self._cp_store = self._cp_lease = None
                raise SystemExit(
                    f"another orchestrator (pid {holder.get('pid')}) holds "
                    f"the control plane for {registry_dir} — stop it, or "
                    "start this one with --standby to take over when it "
                    "dies")
            if not announced:
                logger.info(
                    "standby: control plane held by pid %s; waiting for "
                    "the lease (epoch %s)",
                    holder.get("pid"), holder.get("epoch"))
                announced = True
            await asyncio.sleep(max(lease_s / 3.0, 0.05))
        self._cp_renewer = asyncio.create_task(self._renew_control_plane())
        logger.info("control-plane lease acquired (owner %s, epoch %d)",
                    self._cp_owner, self._cp_epoch)

    async def _renew_control_plane(self) -> None:
        from tasksrunner.state.replication import lease_seconds_default

        interval = max(lease_seconds_default() / 3.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            try:
                renewed = await self._cp_lease.renew(self._cp_owner)
            except Exception:
                logger.debug("control-plane renew failed", exc_info=True)
                continue
            if not renewed:
                # a standby fenced us out — it now owns the registry
                # and the replicas; mutating anything past this point
                # would be the zombie-orchestrator bug
                holder = await self._cp_lease.peek() or {}
                logger.critical(
                    "control-plane lease lost to pid %s — this "
                    "orchestrator is fenced; stop it", holder.get("pid"))
                return

    def _find_adoptable(self) -> dict[str, list]:
        """Live local registry entries for the configured apps — what a
        previous orchestrator's data plane left running."""
        from tasksrunner.invoke.resolver import NameResolver

        registry = self.config.registry_path
        if not registry.is_file():
            return {}
        resolver = NameResolver(registry_file=registry)
        out: dict[str, list] = {}
        for app in self.config.apps:
            live = [
                addr for addr in resolver.resolve_all(app.app_id)
                if addr.pid is not None and not NameResolver.local_pid_dead(
                    addr.host, addr.pid, addr.registered_at)
            ]
            if live:
                out[app.app_id] = sorted(live, key=lambda a: a.registered_at)
        return out

    def _adopt_replica(self, app: AppSpec, addr) -> None:
        replica = Replica(app, len(self.replicas[app.app_id]), self.config)
        replica.adopt(addr)
        self.replicas[app.app_id].append(replica)
        self._supervisors.append(asyncio.create_task(replica.supervise()))
        logger.info("adopted running replica %s (pid %d, app_port %s, "
                    "sidecar_port %s)", replica.tag, addr.pid,
                    addr.app_port, addr.sidecar_port)

    def _issue_mesh_certs(self) -> None:
        """Generate the environment CA + one workload certificate per
        app (playing Dapr's sentry) under <registry dir>/pki; replicas
        receive the CA cert (to verify peers) and only their OWN leaf
        pair. Fresh PKI per orchestrator start — short-lived certs,
        nothing to rotate."""
        from tasksrunner.invoke.pki import write_pki

        pki_dir = self.config.registry_path.parent / "pki"
        self.config.mesh_certs = write_pki(
            pki_dir, [app.app_id for app in self.config.apps])
        logger.info("mesh mTLS on: environment CA + %d workload cert(s) "
                    "under %s", len(self.config.mesh_certs), pki_dir)

    def _issue_app_tokens(self) -> None:
        """Generate one token per app and write the app_id→sha256-digest
        map beside the name registry (mode 0600). Each replica receives
        only its OWN plaintext token; sidecars read the digest map to
        *verify* inbound peer invocations without being able to
        impersonate any peer (≙ one managed identity per container app
        instead of a shared secret, SURVEY.md §5.10). Plaintext tokens
        exist only in the orchestrator's memory and each owner's env."""
        import json as _json
        import pathlib
        import secrets as _secrets

        from tasksrunner.security import hash_token

        self.config.app_tokens = {
            app.app_id: _secrets.token_hex(16) for app in self.config.apps
        }
        digests = {
            app_id: hash_token(token)
            for app_id, token in self.config.app_tokens.items()
        }
        tokens_path = self.config.registry_path.parent / "tokens.json"
        tokens_path.parent.mkdir(parents=True, exist_ok=True)
        # created 0600 from the first byte — chmod-after-write would
        # leave a readable window (and 0600 regardless: the digests
        # leak which apps exist)
        fd = os.open(tokens_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(_json.dumps(digests, indent=2))
        tokens_path.chmod(0o600)  # pre-existing file: tighten it too
        self.config.tokens_file = str(tokens_path)
        logger.info("issued per-app tokens for %d apps -> digest map %s",
                    len(self.config.app_tokens), tokens_path)

    async def _add_replica(self, app: AppSpec) -> None:
        replica = Replica(app, len(self.replicas[app.app_id]), self.config)
        self.replicas[app.app_id].append(replica)
        await replica.start()
        self._supervisors.append(asyncio.create_task(replica.supervise()))

    async def _set_replicas(self, app: AppSpec, desired: int) -> None:
        current = self.replicas[app.app_id]
        while len(current) < desired:
            await self._add_replica(app)
        while len(current) > desired:
            victim = current.pop()  # never replica 0 (desired >= min >= 1)
            await victim.stop()

    def replica_count(self, app_id: str) -> int:
        return len(self.replicas.get(app_id, []))

    def _replica_info(self, app_id: str) -> list[dict]:
        """Live {pid, app_port, sidecar_port, host} per replica — the
        measurement inventory for the http/cpu/memory scale rules and
        the sidecar-metadata sweep behind target-p99/loop-lag."""
        out = []
        for r in self.replicas.get(app_id, []):
            running = r.proc is not None and r.proc.returncode is None
            out.append({
                "pid": r.proc.pid if running else None,
                "app_port": r.ports[0] if r.ports else None,
                "sidecar_port": r.ports[1] if r.ports else None,
                "host": r.app.host,
            })
        return out

    # -- admin operations (≙ the `az containerapp` verbs the workshop
    # -- uses: update / revision restart / revision list / logs show) --

    def _app_spec(self, app_id: str) -> AppSpec:
        for app in self.config.apps:
            if app.app_id == app_id:
                return app
        raise KeyError(app_id)

    async def _rolling_restart(self, app_id: str) -> None:
        """Restart replicas one at a time, waiting for each to come
        back ready, so at least one replica keeps serving throughout."""
        for replica in list(self.replicas[app_id]):
            # a replica already down is mid-crash-restart: setting the
            # manual flag now would mis-classify its NEXT crash as a
            # requested restart (no backoff, no counter) — skip it
            if replica.stopping or replica.proc is None \
                    or replica.proc.returncode is not None:
                continue
            old_pid = replica.proc.pid
            replica.manual_restart = True
            replica.proc.terminate()
            deadline = asyncio.get_running_loop().time() + 30
            killed = False
            while (replica.proc is None or replica.proc.pid == old_pid
                   or not replica.ready.is_set()):
                if asyncio.get_running_loop().time() > deadline:
                    if not killed and replica.proc is not None \
                            and replica.proc.pid == old_pid \
                            and replica.proc.returncode is None:
                        # SIGTERM trapped/ignored: escalate so the flag
                        # can't go stale on a process that never exits
                        logger.warning("replica %s ignored SIGTERM for 30s; "
                                       "killing", replica.tag)
                        replica.proc.kill()
                        killed = True
                        deadline = asyncio.get_running_loop().time() + 10
                        continue
                    logger.warning("replica %s did not come back ready "
                                   "in time", replica.tag)
                    break
                if replica.stopping:
                    return
                await asyncio.sleep(0.1)

    async def restart_app(self, app_id: str) -> dict:
        """≙ `az containerapp revision restart`."""
        entry = self._record_revision(app_id, "manual restart")
        await self._rolling_restart(app_id)
        return entry

    async def update_env(self, app_id: str, *, set_env: dict[str, str],
                         remove: list[str]) -> dict:
        """≙ `az containerapp update --set-env-vars/--remove-env-vars`:
        a config change makes a new revision; replicas restart into it."""
        app = self._app_spec(app_id)
        for key in remove:
            app.env.pop(key, None)
        app.env.update({str(k): str(v) for k, v in set_env.items()})
        entry = self._record_revision(
            app_id, "env update",
            env_set=sorted(set_env), env_removed=sorted(remove))
        await self._rolling_restart(app_id)
        return entry

    async def update_scale(self, app_id: str, *, min_replicas: int | None,
                           max_replicas: int | None) -> dict:
        """≙ `az containerapp update --min-replicas/--max-replicas`.
        No restart needed — the bounds steer the autoscaler and the
        floor is applied immediately."""
        app = self._app_spec(app_id)
        new_min = app.scale.min_replicas if min_replicas is None else min_replicas
        new_max = app.scale.max_replicas if max_replicas is None else max_replicas
        if new_min < 1:
            raise ValueError("min_replicas must be >= 1 (scale-to-zero "
                             "would starve cron/input bindings)")
        if new_min > new_max:
            raise ValueError(
                f"min_replicas {new_min} exceeds max_replicas {new_max}; "
                "pass both to raise the ceiling")
        app.scale.min_replicas = new_min
        app.scale.max_replicas = new_max
        entry = self._record_revision(
            app_id, "scale update",
            min_replicas=app.scale.min_replicas,
            max_replicas=app.scale.max_replicas)
        current = len(self.replicas[app_id])
        floor = app.scale.min_replicas
        ceil = app.scale.max_replicas
        desired = min(max(current, floor), ceil)
        if desired != current:
            await self._set_replicas(app, desired)
        return entry

    def status(self) -> dict:
        now = time.time()
        apps = []
        for app in self.config.apps:
            group = self.replicas.get(app.app_id, [])
            active = next(
                (r for r in self.revisions.get(app.app_id, []) if r["active"]),
                None)
            apps.append({
                "app_id": app.app_id,
                "module": app.module,
                "revision": active["revision"] if active else None,
                "scale": {"min": app.scale.min_replicas,
                          "max": app.scale.max_replicas},
                "env_keys": sorted(app.env),
                "replicas": [
                    {
                        "index": r.index,
                        "pid": r.proc.pid if r.proc else None,
                        "running": bool(r.proc and r.proc.returncode is None),
                        "app_port": r.ports[0] if r.ports else None,
                        "sidecar_port": r.ports[1] if r.ports else None,
                        "restarts": r.restarts,
                        "health_restarts": r.health_restarts,
                        "uptime_seconds": (round(now - r.started_at, 1)
                                           if r.started_at else None),
                    }
                    for r in group
                ],
            })
        return {"apps": apps}

    def app_logs(self, app_id: str, *, tail: int = 100,
                 replica: int | None = None) -> list[dict]:
        """≙ `az containerapp logs show --tail N`."""
        group = self.replicas.get(app_id)
        if group is None:
            raise KeyError(app_id)
        out = []
        for r in group:
            if replica is not None and r.index != replica:
                continue
            for line in list(r.log_buffer)[-tail:]:
                out.append({"replica": r.index, "line": line})
        return out

    async def wait(self) -> None:
        """Run until interrupted."""
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # pragma: no cover - non-unix
                pass
        await stop.wait()

    async def stop(self) -> None:
        if self._admin is not None:
            await self._admin.stop()
            self._admin = None
        for scaler in self._scalers:
            await scaler.stop()
        for controller in self.placement.values():
            await controller.stop()
        self.placement.clear()
        for group in self.replicas.values():
            for replica in group:
                await replica.stop()
        for task in self._supervisors:
            task.cancel()
        for task in self._supervisors:
            try:
                await task
            except asyncio.CancelledError:
                pass
        self._supervisors.clear()
        await self._release_control_plane()

    async def _release_control_plane(self) -> None:
        if self._cp_renewer is not None:
            self._cp_renewer.cancel()
            try:
                await self._cp_renewer
            except asyncio.CancelledError:
                pass
            self._cp_renewer = None
        if self._cp_lease is not None:
            try:
                await self._cp_lease.release(self._cp_owner)
            except Exception:  # pragma: no cover - store already gone
                logger.debug("control-plane release failed", exc_info=True)
            self._cp_lease = None
        if self._cp_store is not None:
            await self._cp_store.aclose()
            self._cp_store = None

    async def abandon(self) -> None:
        """Walk away from everything WITHOUT stopping it — the test
        double for ``kill -9`` of the orchestrator process. Replicas
        keep running and stay registered; the control-plane lease
        record and ``orchestrator.json`` stay on disk exactly as a
        dead process would leave them (no release, no unlink); only
        this process's tasks and sockets are torn down. A successor
        with ``adopt`` then takes the lease on expiry and re-adopts
        the data plane."""
        if self._admin is not None:
            await self._admin.abandon()
            self._admin = None
        for scaler in self._scalers:
            await scaler.stop()
        self._scalers.clear()
        for controller in self.placement.values():
            await controller.stop()
        self.placement.clear()
        doomed: list[asyncio.Task] = list(self._supervisors)
        self._supervisors.clear()
        for task in doomed:
            # a supervisor blocks in proc.wait() — and the proc, by
            # design, keeps running; cancel rather than wait it out
            task.cancel()
        for group in self.replicas.values():
            for replica in group:
                replica.stopping = True  # a dead parent restarts nothing
                for task in (replica._pump, replica._prober):
                    if task is not None:
                        task.cancel()
                        doomed.append(task)
                replica._pump = replica._prober = None
        if self._cp_renewer is not None:
            self._cp_renewer.cancel()
            doomed.append(self._cp_renewer)
            self._cp_renewer = None
        for task in doomed:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._cp_lease = None
        if self._cp_store is not None:
            # close the handle only — the lease record stays unreleased
            await self._cp_store.aclose()
            self._cp_store = None


async def run_from_config(config: RunConfig) -> None:
    if config.require_api_token:
        from tasksrunner.security import TOKEN_ENV
        if not os.environ.get(TOKEN_ENV):
            raise SystemExit(
                f"this run config requires an API token but {TOKEN_ENV} is "
                "not set — the manifest was deployed with "
                "require_api_token: true (secure baseline); refusing to "
                "start unauthenticated")
    orch = Orchestrator(config)
    try:
        await orch.start()
    except BaseException:
        # e.g. a fixed admin_port already bound: replicas are already
        # spawned by now — stop them rather than orphaning children
        await orch.stop()
        raise
    apps = ", ".join(a.app_id for a in config.apps)
    logger.info("orchestrator running apps: %s (ctrl-c to stop)", apps)
    try:
        await orch.wait()
    finally:
        # ctrl-c cancels us mid-wait; the children must still be reaped
        await asyncio.shield(orch.stop())
