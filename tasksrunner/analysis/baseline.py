"""Checked-in baseline of grandfathered findings.

The baseline lets tasklint land with strict rules on an imperfect tree:
pre-existing findings are recorded (fingerprint → count) and stop
failing the build, while anything *new* still does. The shipped
baseline is empty — every finding on the current tree was fixed or
explicitly suppressed inline — but the mechanism stays so a future rule
can be introduced without a flag day.

Format (JSON, sorted, diff-friendly)::

    {"version": 1,
     "findings": {"<fingerprint>": {"rule": ..., "path": ...,
                                    "message": ..., "count": N}}}

Fingerprints exclude line numbers (see ``Finding.fingerprint``), so
unrelated edits don't churn this file. Entries that no longer match
anything are *stale*; ``--update-baseline`` expires them (and records
any new findings).
"""

from __future__ import annotations

import collections
import json
import pathlib

from tasksrunner.analysis.core import Finding

VERSION = 1


def load(path: pathlib.Path) -> dict[str, dict]:
    """fingerprint → entry; empty dict when the file is absent."""
    if not path.is_file():
        return {}
    doc = json.loads(path.read_text() or "{}")
    if doc.get("version") not in (None, VERSION):
        raise ValueError(
            f"baseline {path} has version {doc.get('version')!r}, "
            f"this engine understands {VERSION}")
    return dict(doc.get("findings") or {})


def apply(findings: list[Finding], baseline: dict[str, dict],
          ) -> tuple[list[Finding], int, dict[str, dict]]:
    """Split findings against the baseline.

    Returns ``(new_findings, matched_count, stale_entries)`` where
    ``stale_entries`` are baseline records that matched nothing — the
    grandfathered problem was fixed and the entry should be expired.
    """
    budget = {fp: int(entry.get("count", 1))
              for fp, entry in baseline.items()}
    fresh: list[Finding] = []
    matched = 0
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            matched += 1
        else:
            fresh.append(f)
    stale = {fp: baseline[fp] for fp, left in budget.items()
             if left == int(baseline[fp].get("count", 1))}
    return fresh, matched, stale


def write(path: pathlib.Path, findings: list[Finding]) -> dict[str, dict]:
    """Rewrite the baseline to exactly the given findings (add new,
    expire stale) and return the written table."""
    table: dict[str, dict] = {}
    counts = collections.Counter(f.fingerprint() for f in findings)
    for f in findings:
        fp = f.fingerprint()
        table[fp] = {"rule": f.rule, "path": f.path,
                     "message": f.message, "count": counts[fp]}
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(
        {"version": VERSION, "findings": dict(sorted(table.items()))},
        indent=2, sort_keys=False) + "\n")
    return table
