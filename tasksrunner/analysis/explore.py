"""Schedule exploration — executable protocol kernels under every
interleaving.

The interleave phase (:mod:`tasksrunner.analysis.interleave`) reasons
about the *code*; this module checks the *protocols* the code
implements. Each kernel is a small executable model of one fenced lane
— lease takeover with an epoch fence, quorum append with the resync
ladder, workflow turn commit — written as cooperative processes that
``yield`` at every point where the real implementation suspends. A
deterministic scheduler then runs the model under **exhaustive
interleavings**, including crash points, and asserts the lane's
invariant at quiescence: no two owners commit at the same epoch, no
acked write is lost, replay converges on one contiguous history.

The search is stateless-model-checking style: a *schedule* is the
sequence of choice indices the scheduler took (which process steps
next, or which process crashes); replaying a schedule from a fresh
model is cheap, so the explorer enumerates the choice tree by
replaying prefixes (the classic systematic-testing loop) rather than
snapshotting state. Choice 0 always means "continue the first runnable
process", so the number of non-zero choices in a schedule counts its
*preemptions* — :func:`shortest_repro` iterates a preemption bound
upward and therefore prints the simplest schedule that breaks a seeded
bug, which is the repro a human can actually read.

Every kernel ships a ``buggy=True`` twin with the fencing discipline
removed (a blind acquire, a premature ack, an unguarded commit).
``tasksrunner verify`` runs both: the correct kernels must survive
every schedule, and the seeded twins must be *caught* — the buggy
variants are the explorer's own regression test.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterator

#: hard ceiling on schedules per exploration — the kernels sit around
#: a few thousand; hitting this means a model diverged
MAX_RUNS = 200_000


class InvariantViolation(Exception):
    """A protocol invariant failed under some schedule."""


@dataclasses.dataclass(frozen=True)
class Run:
    """One executed schedule: the choices taken, the branching factor
    at each choice (for sibling enumeration), the human-readable step
    trace, and the invariant violation if any."""

    schedule: tuple[int, ...]
    options: tuple[int, ...]
    trace: tuple[str, ...]
    violation: str | None

    def preemptions(self) -> int:
        return sum(1 for c in self.schedule if c)


@dataclasses.dataclass
class ExploreResult:
    runs: int
    crash_runs: int
    violation: Run | None


class Model:
    """One protocol kernel. ``procs()`` returns the initial processes
    as ``(name, generator)`` pairs; each generator yields a step label
    (or ``(label, True)`` for a crashable point) *before* the atomic
    block the label names — resuming the generator executes that block
    up to the next yield. ``on_crash`` may return recovery processes;
    ``check()`` raises :class:`InvariantViolation` at quiescence."""

    name = "model"
    max_crashes = 1

    def procs(self) -> list[tuple[str, Iterator]]:
        raise NotImplementedError

    def on_crash(self, name: str) -> list[tuple[str, Iterator]]:
        return []

    def check(self) -> None:
        pass


class _Proc:
    __slots__ = ("name", "gen", "pending", "crashable", "alive")

    def __init__(self, name: str, gen: Iterator):
        self.name = name
        self.gen = gen
        self.pending = ""
        self.crashable = False
        self.alive = True
        self._advance()

    def _advance(self) -> None:
        try:
            label = next(self.gen)
        except StopIteration:
            self.alive = False
            return
        if isinstance(label, tuple):
            self.pending, self.crashable = label[0], bool(label[1])
        else:
            self.pending, self.crashable = str(label), False


def _execute(factory: Callable[[], Model],
             schedule: tuple[int, ...]) -> Run:
    """Replay ``schedule`` against a fresh model, extending greedily
    with choice 0 once the schedule runs out."""
    model = factory()
    procs = [_Proc(name, gen) for name, gen in model.procs()]
    choices: list[int] = []
    options: list[int] = []
    trace: list[str] = []
    crashes = 0
    violation: str | None = None
    step = 0
    while violation is None:
        opts: list[tuple[str, _Proc]] = [
            ("step", p) for p in procs if p.alive]
        if crashes < model.max_crashes:
            opts.extend(("crash", p) for p in procs
                        if p.alive and p.crashable)
        if not opts:
            break
        pick = schedule[step] if step < len(schedule) else 0
        pick = min(pick, len(opts) - 1)
        step += 1
        choices.append(pick)
        options.append(len(opts))
        kind, proc = opts[pick]
        if kind == "step":
            trace.append(f"{proc.name}: {proc.pending}")
            try:
                proc._advance()
            except InvariantViolation as exc:
                violation = str(exc)
        else:
            trace.append(f"{proc.name}: CRASH before [{proc.pending}]")
            proc.alive = False
            proc.gen.close()
            crashes += 1
            procs.extend(_Proc(n, g) for n, g in model.on_crash(proc.name))
    if violation is None:
        try:
            model.check()
        except InvariantViolation as exc:
            violation = str(exc)
    return Run(schedule=tuple(choices), options=tuple(options),
               trace=tuple(trace), violation=violation)


def explore(factory: Callable[[], Model], *,
            max_preemptions: int | None = None,
            stop_on_violation: bool = True) -> ExploreResult:
    """Enumerate every schedule of the model (bounded by
    ``max_preemptions`` non-zero choices when given). Each executed
    prefix enqueues the unexplored siblings of every choice it made
    past the prefix — the standard replay-based systematic search."""
    stack: list[tuple[int, ...]] = [()]
    runs = 0
    crash_runs = 0
    violation: Run | None = None
    while stack:
        prefix = stack.pop()
        run = _execute(factory, prefix)
        runs += 1
        if any("CRASH" in t for t in run.trace):
            crash_runs += 1
        if run.violation is not None and violation is None:
            violation = run
            if stop_on_violation:
                break
        if runs >= MAX_RUNS:
            raise RuntimeError(
                f"{factory().name}: exceeded {MAX_RUNS} schedules — "
                f"the model does not quiesce")
        for pos in range(len(prefix), len(run.schedule)):
            base = run.schedule[:pos]
            for alt in range(1, run.options[pos]):
                cand = base + (alt,)
                if max_preemptions is not None and \
                        sum(1 for c in cand if c) > max_preemptions:
                    continue
                stack.append(cand)
    return ExploreResult(runs=runs, crash_runs=crash_runs,
                         violation=violation)


def shortest_repro(factory: Callable[[], Model]) -> Run | None:
    """Minimal-preemption failing schedule, or None when every
    schedule upholds the invariants. Iterating the preemption bound
    upward makes the first hit the simplest repro."""
    for bound in range(0, 33):
        found = explore(factory, max_preemptions=bound).violation
        if found is not None:
            return found
    return None


def format_repro(run: Run) -> str:
    lines = [f"schedule {list(run.schedule)} "
             f"({run.preemptions()} preemption(s)):"]
    lines += [f"  {i:2d}. {step}" for i, step in enumerate(run.trace, 1)]
    lines.append(f"  => {run.violation}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# kernel 1: lease takeover + epoch fence
# ---------------------------------------------------------------------------

class LeaseTakeoverModel(Model):
    """Two nodes race for an expired lease. Acquisition is an etag CAS
    that bumps the epoch (state/replication.py ``Lease.acquire``); the
    data commit is fenced by the highest epoch the store has seen.
    Invariant: no two owners ever commit at the same epoch.

    ``buggy=True`` drops the CAS — both racers adopt the same bumped
    epoch, exactly the blind takeover the etag chain exists to stop."""

    name = "lease-takeover"

    def __init__(self, buggy: bool = False):
        self.buggy = buggy
        self.lease = {"owner": "dead", "epoch": 1, "etag": 7,
                      "expired": True}
        self.fence = 1          # highest epoch the store has committed
        self.commits: list[tuple[str, int]] = []

    def procs(self):
        return [("node-a", self._node("node-a")),
                ("node-b", self._node("node-b"))]

    def on_crash(self, name: str):
        # the crashed owner's lease runs out; a successor contends
        if self.lease["owner"] == name:
            self.lease["expired"] = True
        return [(f"{name}'", self._node(f"{name}'"))]

    def _node(self, me: str):
        yield "peek lease"
        snap = dict(self.lease)
        if not snap["expired"]:
            return
        yield ("acquire (etag CAS, bump epoch)", True)
        if not self.buggy and self.lease["etag"] != snap["etag"]:
            return  # lost the takeover race — stand down
        epoch = snap["epoch"] + 1
        self.lease = {"owner": me, "epoch": epoch,
                      "etag": snap["etag"] + 1, "expired": False}
        yield ("commit at acquired epoch", True)
        if epoch < self.fence:
            return  # fenced by a newer owner — commit rejected
        self.fence = epoch
        self.commits.append((me, epoch))

    def check(self):
        seen: dict[int, str] = {}
        for owner, epoch in self.commits:
            if epoch in seen and seen[epoch] != owner:
                raise InvariantViolation(
                    f"two owners committed at epoch {epoch}: "
                    f"{seen[epoch]} and {owner}")
            seen[epoch] = owner


# ---------------------------------------------------------------------------
# kernel 2: quorum append + resync ladder
# ---------------------------------------------------------------------------

class QuorumAppendModel(Model):
    """A leader appends two records, ships each to its follower, and
    acks only at quorum (both copies durable). On a leader crash the
    follower promotes at the next epoch, writes the leadership
    barrier, and resyncs the ex-leader from its own log — the ladder
    truncates any divergent (necessarily unacked) suffix. Invariants:
    every acked record survives in the new leader's log, and the logs
    converge at quiescence.

    ``buggy=True`` acks at quorum 1 (local append only) — a crash
    before shipping then loses an acked record."""

    name = "quorum-append"

    def __init__(self, buggy: bool = False):
        self.buggy = buggy
        self.logs: dict[str, list[tuple[int, str]]] = {"A": [], "B": []}
        self.acked: list[str] = []
        self.leader = "A"
        self.epoch = 1

    def procs(self):
        return [("leader-A", self._leader())]

    def on_crash(self, name: str):
        return [("takeover-B", self._takeover())]

    def _leader(self):
        for rec in ("r1", "r2"):
            yield (f"append {rec} to local log", True)
            self.logs["A"].append((1, rec))
            if self.buggy:
                yield f"ack {rec} at quorum=1 (SEEDED BUG)"
                self.acked.append(rec)
            yield (f"ship {rec} to B", True)
            self.logs["B"].append((1, rec))
            if not self.buggy:
                yield f"ack {rec} at quorum=2"
                self.acked.append(rec)

    def _takeover(self):
        yield "B acquires lease at epoch 2"
        self.epoch = 2
        self.leader = "B"
        yield "B writes leadership barrier"
        self.logs["B"].append((2, "barrier"))
        yield "resync ladder: A adopts B's log"
        self.logs["A"] = list(self.logs["B"])

    def check(self):
        authoritative = [rec for _, rec in self.logs[self.leader]]
        for rec in self.acked:
            if rec not in authoritative:
                raise InvariantViolation(
                    f"acked record {rec!r} lost: leader {self.leader} "
                    f"log is {authoritative}")
        if self.logs["A"] != self.logs["B"]:
            raise InvariantViolation(
                f"logs diverged at quiescence: A={self.logs['A']} "
                f"B={self.logs['B']}")


# ---------------------------------------------------------------------------
# kernel 3: workflow turn commit
# ---------------------------------------------------------------------------

class TurnCommitModel(Model):
    """Two drivers race to advance one workflow instance. A turn is
    replay (read the history, compute the next event from its length)
    plus one etag-guarded commit; a fenced driver replays and retries.
    A crashed driver is replaced by a recovery driver that replays
    from the committed prefix. Invariants: every acked event is in the
    history exactly once, and the history is one contiguous replay
    order (no gaps, no forks).

    ``buggy=True`` commits blind (no etag guard) — the last writer
    clobbers the other driver's acked event."""

    name = "turn-commit"

    def __init__(self, buggy: bool = False):
        self.buggy = buggy
        self.record = {"history": ("started",), "etag": 0}
        self.acked: list[str] = []

    def procs(self):
        return [("driver-0", self._driver("d0")),
                ("driver-1", self._driver("d1"))]

    def on_crash(self, name: str):
        return [("recovery", self._driver("rc"))]

    def _driver(self, me: str):
        for _attempt in (1, 2):
            yield "read record (replay history)"
            hist = self.record["history"]
            etag = self.record["etag"]
            event = f"e{len(hist)}.{me}"
            yield ("commit turn (append event)", True)
            if not self.buggy and self.record["etag"] != etag:
                continue  # fenced: replay from the new history, retry
            self.record = {"history": hist + (event,), "etag": etag + 1}
            self.acked.append(event)
            return

    def check(self):
        hist = self.record["history"][1:]
        for ev in self.acked:
            n = hist.count(ev)
            if n != 1:
                raise InvariantViolation(
                    f"acked event {ev!r} appears {n} times in history "
                    f"{list(hist)}")
        for i, ev in enumerate(hist, start=1):
            if not ev.startswith(f"e{i}."):
                raise InvariantViolation(
                    f"history diverged from replay order at index {i}: "
                    f"{list(hist)}")


KERNELS: dict[str, Callable[[bool], Model]] = {
    LeaseTakeoverModel.name: LeaseTakeoverModel,
    QuorumAppendModel.name: QuorumAppendModel,
    TurnCommitModel.name: TurnCommitModel,
}


def verify(kernels: list[str] | None = None, *,
           out=None) -> int:
    """Run the selected kernels (default: all) exhaustively — correct
    variants must pass every schedule, seeded-bug twins must be caught
    and get their minimal repro printed. Returns a process exit code."""
    import sys
    out = out or sys.stdout
    names = kernels or sorted(KERNELS)
    failed = False
    for name in names:
        kernel = KERNELS[name]
        res = explore(lambda: kernel(False), stop_on_violation=True)
        if res.violation is not None:
            failed = True
            out.write(f"FAIL {name}: invariant violated under a "
                      f"legal schedule\n")
            out.write(format_repro(res.violation) + "\n")
        else:
            out.write(f"ok   {name}: {res.runs} schedules "
                      f"({res.crash_runs} with a crash), "
                      f"invariants hold\n")
        repro = shortest_repro(lambda: kernel(True))
        if repro is None:
            failed = True
            out.write(f"FAIL {name}: seeded bug NOT caught — the "
                      f"explorer lost its teeth\n")
        else:
            out.write(f"ok   {name}: seeded bug caught; minimal "
                      f"repro:\n")
            for line in format_repro(repro).splitlines():
                out.write(f"       {line}\n")
    return 1 if failed else 0
