"""``python -m tasksrunner.analysis`` — the tasklint CLI."""

from __future__ import annotations

import sys

from tasksrunner.analysis.engine import main

if __name__ == "__main__":
    sys.exit(main())
