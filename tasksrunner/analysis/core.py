"""Rule-engine primitives: findings, file context, and the registry.

A rule is a stateless object with an ``id``, a one-line ``doc``, and a
``check(ctx)`` generator over :class:`Finding`. Rules register
themselves with the :func:`register` decorator at import time
(``rules/__init__.py`` imports every rule module), so the engine, the
CLI's ``--rules`` filter, and the suppression validator all share one
table.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import pathlib
import re
from typing import Iterable, Iterator

#: ``tasklint: disable=<rule>[,<rule>]`` in a comment suppresses those
#: rules' findings on that line; ``disable-file=<rule>`` anywhere
#: suppresses for the whole file.
SUPPRESS_RE = re.compile(
    r"#\s*tasklint:\s*(disable|disable-file)=([A-Za-z0-9_,\- ]+)")

#: ``# tasklint: off-loop`` on a ``def`` line declares the function a
#: dedicated-thread entrypoint: blocking calls inside it are expected.
OFF_LOOP_RE = re.compile(r"#\s*tasklint:\s*off-loop\b")

#: ``# tasklint: fenced-lane`` on a ``def`` line declares the function
#: a fenced protocol lane (actor turn commit, replication leader
#: append, workflow history append): every state-plane write inside it
#: must thread an etag obtained in the same atomic scope, and every
#: epoch comparison must be >=-monotone.
FENCED_LANE_RE = re.compile(r"#\s*tasklint:\s*fenced-lane\b")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at one source location.

    Interprocedural rules additionally carry ``chain`` — the full call
    path as ``file:line`` frames, first frame = the entry site the
    finding is reported at, last frame = the offending leaf. Editors
    render it as a navigable path; ``--json`` emits it verbatim.
    """

    path: str  # repo-relative posix path
    line: int
    col: int  # 1-based, for editors
    rule: str
    message: str
    chain: tuple[str, ...] = ()

    def format(self) -> str:
        text = f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"
        if self.chain:
            text += "\n    chain: " + " -> ".join(self.chain)
        return text

    def fingerprint(self) -> str:
        """Baseline identity. Deliberately excludes the line number so
        unrelated edits above a grandfathered finding don't churn the
        baseline file; two identical findings in one file share a
        fingerprint and are matched by count. The chain is excluded for
        the same reason — its frames are line numbers."""
        raw = f"{self.rule}|{self.path}|{self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "chain": list(self.chain),
            "fingerprint": self.fingerprint(),
        }

    @classmethod
    def from_json(cls, doc: dict) -> "Finding":
        return cls(path=doc["path"], line=doc["line"], col=doc["col"],
                   rule=doc["rule"], message=doc["message"],
                   chain=tuple(doc.get("chain") or ()))


class FileContext:
    """Everything a rule may inspect about one source file."""

    def __init__(self, path: pathlib.Path, relpath: str, source: str,
                 tree: ast.Module):
        self.path = path
        #: repo-relative posix path ("tasksrunner/state/sqlite.py") —
        #: rules scope themselves by prefix on this
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._constants: dict[str, str] | None = None

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(path=self.relpath,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       rule=rule, message=message)

    @property
    def constants(self) -> dict[str, str]:
        """Module-level ``NAME = "literal"`` assignments — lets rules
        see through the ``TOKEN_ENV = "TASKSRUNNER_API_TOKEN"`` idiom."""
        if self._constants is None:
            table: dict[str, str] = {}
            for node in self.tree.body:
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            table[tgt.id] = node.value.value
            self._constants = table
        return self._constants

    def resolve_str(self, node: ast.AST) -> str | None:
        """A string literal, or a Name bound to one at module level."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return self.constants.get(node.id)
        return None

    def marked_off_loop(self, node: ast.AST) -> bool:
        """``# tasklint: off-loop`` on the def line (or any decorator
        line) of a function node."""
        first = min(getattr(node, "lineno", 1),
                    *[d.lineno for d in getattr(node, "decorator_list", [])]
                    or [getattr(node, "lineno", 1)])
        for lineno in range(first, getattr(node, "lineno", first) + 1):
            if 0 < lineno <= len(self.lines) and \
                    OFF_LOOP_RE.search(self.lines[lineno - 1]):
                return True
        return False


def dotted_name(node: ast.AST) -> str | None:
    """"time.sleep" for ``Attribute(Name)`` chains; None otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_table(tree: ast.Module) -> dict[str, str]:
    """local alias → fully qualified name, from import statements.

    ``import time`` → {"time": "time"}; ``from time import sleep as s``
    → {"s": "time.sleep"}. Lets rules match on canonical names no
    matter how the module spells the import.
    """
    table: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                table[alias.asname or alias.name.split(".")[0]] = \
                    alias.name if alias.asname else alias.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for alias in node.names:
                table[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return table


def resolve_call(ctx_imports: dict[str, str], func: ast.AST) -> str | None:
    """Canonical dotted name of a call target, resolving import
    aliases: ``s(...)`` after ``from time import sleep as s`` resolves
    to "time.sleep"."""
    dotted = dotted_name(func)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    base = ctx_imports.get(head, head)
    return f"{base}.{rest}" if rest else base


class Rule:
    """Base class; subclasses set ``id``/``doc`` and yield findings."""

    id: str = ""
    doc: str = ""

    def check(self, ctx: FileContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def walk(self, ctx: FileContext) -> Iterator[ast.AST]:
        yield from ast.walk(ctx.tree)


class ProgramRule:
    """Base class for whole-program rules: ``check`` sees the
    :class:`~tasksrunner.analysis.program.ProgramGraph` built over the
    whole lint target, not one file. Findings still flow through the
    same suppression / baseline / JSON machinery as per-file rules."""

    id: str = ""
    doc: str = ""

    def check(self, graph) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


class DataflowRule:
    """Base class for dataflow rules: ``check`` sees a
    :class:`~tasksrunner.analysis.dataflow.DataflowAnalysis` — the
    ProgramGraph plus per-function CFGs and the shared taint /
    exception-escape engines. Findings carry source→sink chains and
    flow through the same chain-aware suppression as program rules."""

    id: str = ""
    doc: str = ""

    def check(self, dfa) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


class InterleaveRule:
    """Base class for interleaving rules: ``check`` sees an
    :class:`~tasksrunner.analysis.interleave.InterleaveAnalysis` —
    every async function partitioned into atomic sections (maximal
    await-free regions) with per-section shared-state footprints.
    Findings carry *labelled* chain frames (``file:line [label]``); the
    label names the frame's role in the interleaving window (check /
    await boundary / write / rival writer)."""

    id: str = ""
    doc: str = ""

    def check(self, ia) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


#: rule id → singleton instance; populated at import of ``.rules``
RULES: dict[str, Rule] = {}

#: whole-program rule id → singleton; shares the id namespace with
#: RULES (the suppression validator and ``--rules`` see one table)
PROGRAM_RULES: dict[str, ProgramRule] = {}

#: dataflow rule id → singleton; same shared id namespace
DATAFLOW_RULES: dict[str, DataflowRule] = {}

#: interleave rule id → singleton; same shared id namespace
INTERLEAVE_RULES: dict[str, InterleaveRule] = {}


def known_rule_ids() -> set[str]:
    return set(RULES) | set(PROGRAM_RULES) | set(DATAFLOW_RULES) \
        | set(INTERLEAVE_RULES)


def _register_into(table: dict, inst) -> None:
    if not inst.id:
        raise ValueError(f"{type(inst).__name__} has no rule id")
    if inst.id in RULES or inst.id in PROGRAM_RULES or \
            inst.id in DATAFLOW_RULES or inst.id in INTERLEAVE_RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    table[inst.id] = inst


def register(cls: type[Rule]) -> type[Rule]:
    _register_into(RULES, cls())
    return cls


def register_program(cls: type[ProgramRule]) -> type[ProgramRule]:
    _register_into(PROGRAM_RULES, cls())
    return cls


def register_dataflow(cls: type[DataflowRule]) -> type[DataflowRule]:
    _register_into(DATAFLOW_RULES, cls())
    return cls


def register_interleave(cls: type[InterleaveRule]) -> type[InterleaveRule]:
    _register_into(INTERLEAVE_RULES, cls())
    return cls
