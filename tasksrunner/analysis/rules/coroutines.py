"""unawaited-coroutine — discarded coroutines and orphaned tasks.

Calling an ``async def`` without ``await`` builds a coroutine object
and throws it away: the operation silently never runs (Python warns
only at GC time, to stderr, in whatever process happened to collect
it). ``asyncio.create_task`` without a retained reference is subtler —
the event loop holds tasks weakly, so a GC pass can cancel a running
task mid-flight; every long-lived task in this codebase is retained on
``self`` (see ``pubsub/sqlite.py`` poll loops) for exactly that reason.

Detection is name-based within the file: a bare expression statement
calling a function *defined* ``async def`` in the same module (by name
for module-level functions, by ``self.<attr>`` for methods) is flagged,
as is a bare ``asyncio.create_task(...)`` / ``ensure_future`` /
``loop.create_task(...)`` whose result nothing captures.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tasksrunner.analysis.core import (
    FileContext, Finding, Rule, import_table, register, resolve_call,
)

_SPAWNERS = {"asyncio.create_task", "asyncio.ensure_future"}


def _async_names(tree: ast.Module) -> set[str]:
    """Names defined *only* as async in this module — a name that is
    also a sync ``def`` somewhere (cli.py's module-level ``main`` vs
    the nested ``async def main`` helpers) is ambiguous and skipped."""
    async_names = {node.name for node in ast.walk(tree)
                   if isinstance(node, ast.AsyncFunctionDef)}
    sync_names = {node.name for node in ast.walk(tree)
                  if isinstance(node, ast.FunctionDef)}
    return async_names - sync_names


@register
class UnawaitedCoroutine(Rule):
    id = "unawaited-coroutine"
    doc = ("bare calls to local coroutine functions and create_task "
           "without a retained reference")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        imports = import_table(ctx.tree)
        async_names = _async_names(ctx.tree)
        for node in self.walk(ctx):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            yield from self._check_bare_call(ctx, imports, async_names, call)

    def _check_bare_call(self, ctx: FileContext, imports: dict[str, str],
                         async_names: set[str], call: ast.Call,
                         ) -> Iterator[Finding]:
        target = resolve_call(imports, call.func)
        if target in _SPAWNERS or (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "create_task"):
            yield ctx.finding(
                self.id, call,
                "task reference discarded: the loop holds tasks weakly, so "
                "GC can cancel it mid-flight — retain it (self._task = ...) "
                "and cancel it on close")
            return
        name: str | None = None
        if isinstance(call.func, ast.Name):
            name = call.func.id
        elif (isinstance(call.func, ast.Attribute)
              and isinstance(call.func.value, ast.Name)
              and call.func.value.id in ("self", "cls")):
            name = call.func.attr
        if name is not None and name in async_names:
            yield ctx.finding(
                self.id, call,
                f"coroutine {name!r} called without await — the call builds "
                "a coroutine object and discards it; the body never runs")
