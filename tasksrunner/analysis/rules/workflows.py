"""workflow-determinism — orchestrators observe the world through ctx.

The workflow engine replays an orchestrator from its recorded history
after every suspension and every crash (``workflows/engine.py``). The
correctness of replay rests on the orchestrator being a *deterministic
function of (history, input)*: re-executing it must take the same
branches and create the same task sequence, or the recorded outcomes
no longer line up and the engine fails the instance with
``WorkflowNondeterminismError`` — at runtime, possibly days in.

This rule moves that failure to lint time by flagging the two ways
orchestrators go nondeterministic:

* **ambient inputs** — wall clock (``time.time()``,
  ``datetime.now()``), randomness (``random.*``, ``uuid.uuid4()``),
  and process environment (``os.environ`` / ``os.getenv``) differ
  between the original run and its replays. The deterministic
  equivalents live on the context: ``ctx.now()``, ``ctx.random()``,
  ``ctx.uuid4()``.
* **direct side effects** — calling state / pubsub / invocation APIs
  from the orchestrator body re-executes them on every replay. Effects
  belong in activities (exactly-once via the history commit) —
  ``ctx.call_activity`` is the only sanctioned way to touch the world.

Activities (``@app.activity``) are intentionally NOT checked: they are
the effectful half and may do anything an actor turn may do.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tasksrunner.analysis.core import FileContext, Finding, Rule, register

#: module-level calls whose results differ between replay passes:
#: root name -> attribute names (empty set = every attribute)
AMBIENT_CALLS: dict[str, set[str]] = {
    "time": {"time", "time_ns", "monotonic", "monotonic_ns",
             "perf_counter", "perf_counter_ns"},
    "datetime": {"now", "utcnow", "today"},
    "random": set(),  # every random.* draw is nondeterministic
    "uuid": {"uuid1", "uuid4"},
    "os": {"getenv"},
}

#: Runtime/AppClient effect-surface methods that must not be called
#: from an orchestrator body — effects ride activities, which the
#: history commit makes exactly-once
EFFECT_API_ATTRS = {
    "save_state", "save_state_item", "get_state", "delete_state",
    "get_bulk_state", "publish", "invoke", "invoke_output_binding",
    "invoke_actor",
}


def _is_workflow_decorator(dec: ast.expr) -> bool:
    """``@app.workflow("name")`` — a call of an attribute ``workflow``."""
    return (isinstance(dec, ast.Call)
            and isinstance(dec.func, ast.Attribute)
            and dec.func.attr == "workflow")


def _root_name(node: ast.expr) -> str | None:
    """The leftmost Name of an attribute chain: ``datetime.datetime.now``
    → ``datetime``; ``self.x.y`` → None."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


@register
class WorkflowDeterminism(Rule):
    id = "workflow-determinism"
    doc = ("workflow orchestrators must be deterministic: no wall clock "
           "/ random / uuid / environ reads (use ctx.now / ctx.random / "
           "ctx.uuid4) and no direct state/pubsub/invoke calls (do "
           "effects in activities via ctx.call_activity)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in self.walk(ctx):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(_is_workflow_decorator(d)
                       for d in node.decorator_list):
                continue
            yield from self._scan_body(ctx, node)

    def _scan_body(self, ctx: FileContext, fn: ast.AST) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                yield from self._check_call(ctx, node)
            elif (isinstance(node, ast.Attribute)
                    and node.attr == "environ"
                    and _root_name(node) == "os"):
                yield ctx.finding(
                    self.id, node,
                    "os.environ read inside a workflow orchestrator — "
                    "the environment differs between replays; resolve "
                    "config in an activity and pass it through history")

    def _check_call(self, ctx: FileContext,
                    node: ast.Call) -> Iterator[Finding]:
        func = node.func
        if not isinstance(func, ast.Attribute):
            return
        root = _root_name(func)
        ambient = AMBIENT_CALLS.get(root) if root is not None else None
        if ambient is not None and (not ambient or func.attr in ambient):
            hint = {
                "time": "use ctx.now()",
                "datetime": "use ctx.now()",
                "random": "use ctx.random()",
                "uuid": "use ctx.uuid4()",
                "os": "resolve config in an activity",
            }[root]
            yield ctx.finding(
                self.id, node,
                f"{root}.{func.attr}() inside a workflow orchestrator "
                f"replays to a different value — {hint}; orchestrators "
                "must be deterministic functions of (history, input)")
        elif func.attr in EFFECT_API_ATTRS:
            yield ctx.finding(
                self.id, node,
                f".{func.attr}() inside a workflow orchestrator re-runs "
                "on every replay — move the effect into an activity "
                "(ctx.call_activity), which the history commit makes "
                "exactly-once")
