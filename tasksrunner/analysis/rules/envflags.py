"""env-flag-discipline — every boolean knob goes through envflag.

``envflag.env_flag`` exists because a per-call-site spelling tuple
drifts: one reader learns "off", another only knows "0", and the same
deploy config flips one subsystem but not the other. The inventory in
``envflag.FLAGS`` extends that contract to *existence*: a knob nobody
declared is a knob the docs, the inventory test, and operators can't
see.

Flags:

* any raw ``os.environ.get`` / ``os.getenv`` / ``os.environ[...]`` /
  ``... in os.environ`` read of a ``TASKSRUNNER_*`` name declared
  boolean in the inventory — those must call ``env_flag``;
* any ``TASKSRUNNER_*`` name read that the inventory doesn't declare
  at all.

Names are resolved through module-level string constants, so the
``TOKEN_ENV = "TASKSRUNNER_API_TOKEN"`` idiom is seen through.
``envflag.py`` itself is exempt (it is the sanctioned reader).
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tasksrunner.analysis.core import (
    FileContext, Finding, Rule, import_table, register, resolve_call,
)

_EXEMPT = ("tasksrunner/envflag.py",)


def _environ_sites(tree: ast.Module, imports: dict[str, str],
                   ) -> Iterator[tuple[ast.AST, ast.AST]]:
    """(site node, name-expression node) for every os.environ read."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            target = resolve_call(imports, node.func)
            if target in ("os.getenv",) and node.args:
                yield node, node.args[0]
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "get"
                  and _is_environ(node.func.value, imports) and node.args):
                yield node, node.args[0]
        elif isinstance(node, ast.Subscript) and \
                _is_environ(node.value, imports):
            yield node, node.slice
        elif isinstance(node, ast.Compare) and \
                len(node.ops) == 1 and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and _is_environ(node.comparators[0], imports):
            yield node, node.left


def _is_environ(node: ast.AST, imports: dict[str, str]) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "environ" and \
            isinstance(node.value, ast.Name) and \
            imports.get(node.value.id, node.value.id) == "os":
        return True
    # "from os import environ"
    return isinstance(node, ast.Name) and \
        imports.get(node.id) == "os.environ"


@register
class EnvFlagDiscipline(Rule):
    id = "env-flag-discipline"
    doc = ("TASKSRUNNER_* booleans must be read via envflag.env_flag and "
           "every flag must be declared in envflag.FLAGS")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.relpath in _EXEMPT:
            return
        from tasksrunner.envflag import BOOL_FLAGS, FLAGS
        imports = import_table(ctx.tree)
        # env_flag("TASKSRUNNER_X") with an undeclared name: the right
        # reader, but the knob is still invisible to the inventory
        for node in self.walk(ctx):
            if not isinstance(node, ast.Call):
                continue
            target = resolve_call(imports, node.func) or ""
            if not (target == "env_flag" or target.endswith(".env_flag")):
                continue
            name = ctx.resolve_str(node.args[0]) if node.args else None
            if name and name.startswith("TASKSRUNNER_") and name not in FLAGS:
                yield ctx.finding(
                    self.id, node,
                    f"{name} is not declared in envflag.FLAGS — add it to "
                    "the inventory (name, kind, default, doc)")
        for site, name_node in _environ_sites(ctx.tree, imports):
            name = ctx.resolve_str(name_node)
            if name is None or not name.startswith("TASKSRUNNER_"):
                continue
            if name in BOOL_FLAGS:
                yield ctx.finding(
                    self.id, site,
                    f"boolean flag {name} read via os.environ — use "
                    "envflag.env_flag so every knob accepts the same "
                    "on/off spellings")
            elif name not in FLAGS:
                yield ctx.finding(
                    self.id, site,
                    f"{name} is not declared in envflag.FLAGS — add it to "
                    "the inventory (name, kind, default, doc) so operators "
                    "and the docs can see it")
