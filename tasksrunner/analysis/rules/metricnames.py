"""metric-names — instrumentation uses the declared name registry.

A typo'd metric name (or one name used as two instrument kinds) forks
a time series silently: dashboards, the autoscaler, and the percentile
views then disagree about which series is real. Every
``metrics.inc(...)`` / ``set_gauge(...)`` / ``observe(...)`` /
``recorder(...)`` call with a literal name must use a name declared in
``tasksrunner/observability/names.py`` under the matching kind.

Span identity gets the same discipline: a ``record_span(...)`` call's
``name=`` first token must appear in ``names.SPAN_NAMES`` and its
``kind=`` in ``names.SPAN_KINDS`` — a typo'd span name fractures the
service map and the critical-path blame chains exactly the way a
typo'd metric name forks a series. Names whose *leading* text is
dynamic (the HTTP server span's ``f"{method} {path}"``) are exempt by
design: their vocabulary is the app's routes, not ours.

This is the AST successor of ``scripts/check_metrics.py`` (the script
survives as a thin alias); being a registered rule it now shares
suppressions, the baseline, JSON output, and the cache with every
other invariant check.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tasksrunner.analysis.core import FileContext, Finding, Rule, register


def _kind_table() -> dict[str, tuple[str, dict]]:
    from tasksrunner.observability import names
    return {
        "inc": ("counter", names.COUNTERS),
        "set_gauge": ("gauge", names.GAUGES),
        "observe": ("histogram", names.HISTOGRAMS),
        "observe_many": ("histogram", names.HISTOGRAMS),
        "recorder": ("histogram", names.HISTOGRAMS),
    }


def _span_name_first_token(node: ast.expr) -> str | None:
    """The static first token of a span ``name=`` argument, or None
    when the leading text is dynamic (exempt)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value
    elif (isinstance(node, ast.JoinedStr) and node.values
            and isinstance(node.values[0], ast.Constant)
            and isinstance(node.values[0].value, str)):
        text = node.values[0].value
    else:
        return None
    tokens = text.split()
    return tokens[0] if tokens else None


def _is_record_span(node: ast.Call) -> bool:
    if isinstance(node.func, ast.Name):
        return node.func.id == "record_span"
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr == "record_span")


@register
class MetricNames(Rule):
    id = "metric-names"
    doc = ("every instrumentation site uses a name declared in "
           "observability/names.py, under the right instrument kind "
           "(span names/kinds included)")

    def _check_span(self, ctx: FileContext, node: ast.Call,
                    names) -> Iterable[Finding]:
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        token = _span_name_first_token(kwargs.get("name"))
        if token is not None and token not in names.SPAN_NAMES:
            yield ctx.finding(
                self.id, node,
                f"span name {token!r} is not declared in "
                "observability/names.py SPAN_NAMES — declare it (with a "
                "doc line) or fix the typo before it fractures the "
                "service map")
        kind_node = kwargs.get("kind")
        kind_literals = []
        if isinstance(kind_node, ast.Constant):
            kind_literals = [kind_node.value]
        elif isinstance(kind_node, ast.IfExp):
            # the app server's conditional kind= ("consumer" if ... else
            # "server"): both arms must be valid
            for arm in (kind_node.body, kind_node.orelse):
                if isinstance(arm, ast.Constant):
                    kind_literals.append(arm.value)
        for kind in kind_literals:
            if kind not in names.SPAN_KINDS:
                yield ctx.finding(
                    self.id, node,
                    f"span kind {kind!r} is not one of "
                    "observability/names.py SPAN_KINDS")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        from tasksrunner.observability import names
        table = _kind_table()
        for node in self.walk(ctx):
            if isinstance(node, ast.Call) and _is_record_span(node):
                yield from self._check_span(ctx, node, names)
                continue
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in table):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue  # dynamic names are the caller's problem
            kind, declared = table[node.func.attr]
            name = node.args[0].value
            if name in declared:
                continue
            if name in names.ALL:
                yield ctx.finding(
                    self.id, node,
                    f"{name!r} used as a {kind} but declared as a different "
                    "kind in observability/names.py — one name, one "
                    "instrument kind")
            else:
                yield ctx.finding(
                    self.id, node,
                    f"{kind} name {name!r} is not declared in "
                    "observability/names.py — declare it (with a doc line) "
                    "or fix the typo before it forks a series")
