"""metric-names — instrumentation uses the declared name registry.

A typo'd metric name (or one name used as two instrument kinds) forks
a time series silently: dashboards, the autoscaler, and the percentile
views then disagree about which series is real. Every
``metrics.inc(...)`` / ``set_gauge(...)`` / ``observe(...)`` /
``recorder(...)`` call with a literal name must use a name declared in
``tasksrunner/observability/names.py`` under the matching kind.

This is the AST successor of ``scripts/check_metrics.py`` (the script
survives as a thin alias); being a registered rule it now shares
suppressions, the baseline, JSON output, and the cache with every
other invariant check.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tasksrunner.analysis.core import FileContext, Finding, Rule, register


def _kind_table() -> dict[str, tuple[str, dict]]:
    from tasksrunner.observability import names
    return {
        "inc": ("counter", names.COUNTERS),
        "set_gauge": ("gauge", names.GAUGES),
        "observe": ("histogram", names.HISTOGRAMS),
        "observe_many": ("histogram", names.HISTOGRAMS),
        "recorder": ("histogram", names.HISTOGRAMS),
    }


@register
class MetricNames(Rule):
    id = "metric-names"
    doc = ("every instrumentation site uses a name declared in "
           "observability/names.py, under the right instrument kind")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        from tasksrunner.observability import names
        table = _kind_table()
        for node in self.walk(ctx):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in table):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue  # dynamic names are the caller's problem
            kind, declared = table[node.func.attr]
            name = node.args[0].value
            if name in declared:
                continue
            if name in names.ALL:
                yield ctx.finding(
                    self.id, node,
                    f"{name!r} used as a {kind} but declared as a different "
                    "kind in observability/names.py — one name, one "
                    "instrument kind")
            else:
                yield ctx.finding(
                    self.id, node,
                    f"{kind} name {name!r} is not declared in "
                    "observability/names.py — declare it (with a doc line) "
                    "or fix the typo before it forks a series")
