"""cancellation-safety — teardown must survive task cancellation.

Three asyncio hazards, all of which have bitten real runtimes:

* **await-in-finally** — when the task is cancelled, the first bare
  ``await`` inside a ``finally`` raises ``CancelledError`` immediately
  and the rest of the cleanup never runs (the socket stays open, the
  lease stays held). Safe forms: ``await asyncio.shield(...)``,
  ``await asyncio.wait_for(...)``, or a local
  ``try/except CancelledError`` around the await — catching the *new*
  CancelledError raised at that point does not swallow the one already
  propagating.

* **swallowed CancelledError** — an ``except CancelledError`` (alone
  or in a tuple) whose body neither re-raises nor is the *reap idiom*
  (``x.cancel()`` earlier in the same function, then
  ``try: await x / except CancelledError: pass`` — awaiting a task you
  just cancelled yourself is how asyncio says "collect the corpse").
  Anywhere else, eating CancelledError turns cooperative shutdown into
  a hang.

* **acquire without finally-release** — ``await x.acquire()`` paired
  with an ``x.release()`` that does not sit in a ``finally`` suite: a
  cancellation between the two leaks the lock/lease forever. Use
  ``async with`` or move the release into ``finally``.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tasksrunner.analysis.core import Finding, register_dataflow, DataflowRule
from tasksrunner.analysis.dataflow import (
    DataflowAnalysis,
    FunctionInfo,
    _handler_names,
)

_SAFE_AWAIT_WRAPPERS = frozenset({"asyncio.shield", "asyncio.wait_for"})


def _own_nodes(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _catches_cancel(handler: ast.ExceptHandler) -> bool:
    names = set(_handler_names(handler))
    return bool(names & {"CancelledError", "", "BaseException"})


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        return ""


@register_dataflow
class CancellationSafetyRule(DataflowRule):
    id = "cancellation-safety"
    doc = ("finally-blocks must not await unshielded, CancelledError "
           "must not be swallowed outside the cancel-then-reap idiom, "
           "and acquire() needs its release() in a finally")

    def check(self, dfa: DataflowAnalysis) -> Iterable[Finding]:
        for fn in sorted(dfa.graph.functions.values(),
                         key=lambda f: (f.relpath, f.lineno)):
            yield from self._await_in_finally(dfa, fn)
            yield from self._swallowed_cancel(dfa, fn)
            yield from self._acquire_release(dfa, fn)

    # -- (a) await inside finally ------------------------------------------

    def _await_in_finally(self, dfa: DataflowAnalysis,
                          fn: FunctionInfo) -> Iterable[Finding]:
        if not fn.is_async:
            return
        for node in _own_nodes(fn.node):
            if not isinstance(node, ast.Try) or not node.finalbody:
                continue
            for finding in self._scan_finally(dfa, fn, node.finalbody,
                                              guarded=False):
                yield finding

    def _scan_finally(self, dfa: DataflowAnalysis, fn: FunctionInfo,
                      stmts: list, guarded: bool) -> Iterator[Finding]:
        for stmt in stmts:
            yield from self._scan_node(dfa, fn, stmt, guarded)

    def _scan_node(self, dfa: DataflowAnalysis, fn: FunctionInfo,
                   node: ast.AST, guarded: bool) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Try):
            inner_guarded = guarded or any(
                _catches_cancel(h) for h in node.handlers)
            yield from self._scan_finally(
                dfa, fn, node.body + node.orelse, inner_guarded)
            for handler in node.handlers:
                yield from self._scan_finally(dfa, fn, handler.body, guarded)
            yield from self._scan_finally(dfa, fn, node.finalbody, guarded)
            return
        if isinstance(node, ast.Await) and not guarded:
            value = node.value
            wrapped = isinstance(value, ast.Call) and \
                dfa.resolve_dotted(fn, value.func) in _SAFE_AWAIT_WRAPPERS
            if not wrapped:
                yield Finding(
                    path=fn.relpath, line=node.lineno, col=1,
                    rule=self.id,
                    message=(f"await in finally of {fn.qualname} aborts "
                             "cleanup when the task is cancelled — wrap "
                             "in asyncio.shield()/wait_for() or catch "
                             "CancelledError around it"),
                    chain=(f"{fn.relpath}:{fn.lineno}",
                           f"{fn.relpath}:{node.lineno}"))
                return
        for child in ast.iter_child_nodes(node):
            yield from self._scan_node(dfa, fn, child, guarded)

    # -- (b) swallowed CancelledError --------------------------------------

    def _swallowed_cancel(self, dfa: DataflowAnalysis,
                          fn: FunctionInfo) -> Iterable[Finding]:
        cancelled = self._cancelled_exprs(fn)
        for node in _own_nodes(fn.node):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if "CancelledError" not in _handler_names(handler):
                    continue  # bare/BaseException: the coroutines rule's job
                if any(isinstance(n, ast.Raise)
                       for stmt in handler.body for n in ast.walk(stmt)):
                    continue
                if self._is_reap(node, cancelled):
                    continue
                yield Finding(
                    path=fn.relpath, line=handler.lineno, col=1,
                    rule=self.id,
                    message=(f"{fn.qualname} swallows CancelledError "
                             "without re-raising — shutdown will hang; "
                             "re-raise it (the cancel-then-reap idiom "
                             "is recognised and exempt)"),
                    chain=(f"{fn.relpath}:{fn.lineno}",
                           f"{fn.relpath}:{handler.lineno}"))

    def _cancelled_exprs(self, fn: FunctionInfo) -> set[str]:
        """Textual forms of every expression this function calls
        ``.cancel()`` on (``self._task``, ``task``...)."""
        out: set[str] = set()
        for node in _own_nodes(fn.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "cancel" and not node.args:
                text = _expr_text(node.func.value)
                if text:
                    out.add(text)
        return out

    def _is_reap(self, try_node: ast.Try, cancelled: set[str]) -> bool:
        """``try: await X`` where ``X.cancel()`` happens in the same
        function — awaiting a task you cancelled is the documented way
        to wait for it to actually die."""
        for stmt in try_node.body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Await):
                    target = node.value
                    if isinstance(target, ast.Call):
                        # asyncio.gather(*tasks) / wait_for(task, ...)
                        inner = [a.value if isinstance(a, ast.Starred) else a
                                 for a in target.args]
                    else:
                        inner = [target]
                    for expr in inner:
                        if _expr_text(expr) in cancelled:
                            return True
        return False

    # -- (c) acquire without finally-release -------------------------------

    def _acquire_release(self, dfa: DataflowAnalysis,
                         fn: FunctionInfo) -> Iterable[Finding]:
        acquires: dict[str, int] = {}
        releases: dict[str, list[tuple[int, bool]]] = {}
        # a release in a finally is safe; so is one in an except handler
        # that re-raises — the checkout idiom (release the permit on
        # failure, hold it past the return on success for a later
        # checkin) intentionally has no release on the happy path
        safe_lines = self._finally_linenos(fn) | self._reraise_linenos(fn)
        for node in _own_nodes(fn.node):
            if isinstance(node, ast.Await) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr == "acquire":
                acquires.setdefault(
                    _expr_text(node.value.func.value), node.lineno)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "release":
                text = _expr_text(node.func.value)
                releases.setdefault(text, []).append(
                    (node.lineno, node.lineno in safe_lines))
        for text, lineno in sorted(acquires.items(), key=lambda kv: kv[1]):
            sites = releases.get(text)
            if not sites or any(in_finally for _l, in_finally in sites):
                continue  # no release here (owner elsewhere) or safe
            yield Finding(
                path=fn.relpath, line=lineno, col=1, rule=self.id,
                message=(f"{text}.acquire() in {fn.qualname} releases at "
                         f"line {sites[0][0]} outside a finally — a "
                         "cancellation in between leaks the lock; use "
                         "async with or try/finally"),
                chain=(f"{fn.relpath}:{lineno}",
                       f"{fn.relpath}:{sites[0][0]}"))

    def _finally_linenos(self, fn: FunctionInfo) -> set[int]:
        lines: set[int] = set()
        for node in _own_nodes(fn.node):
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    lines.update(range(stmt.lineno,
                                       (stmt.end_lineno or stmt.lineno) + 1))
        return lines

    def _reraise_linenos(self, fn: FunctionInfo) -> set[int]:
        """Line ranges of except-handler bodies that end in a bare
        ``raise`` (failure-cleanup blocks)."""
        lines: set[int] = set()
        for node in _own_nodes(fn.node):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if any(isinstance(n, ast.Raise) and n.exc is None
                       for stmt in handler.body for n in ast.walk(stmt)):
                    for stmt in handler.body:
                        lines.update(range(
                            stmt.lineno, (stmt.end_lineno or stmt.lineno) + 1))
        return lines
