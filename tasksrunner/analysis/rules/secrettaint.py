"""secret-taint — no credential may reach an observable sink unredacted.

The runtime handles four kinds of secret: values resolved from
``tasksrunner/secrets`` stores, the API tokens (env, header, and the
orchestrator-issued per-app tokens), TLS key material from the mesh
PKI, and any env flag declared ``secret=True`` in
:data:`tasksrunner.envflag.FLAGS`. None of them may flow into a log
call, a metric label, a span record, or an HTTP *error* body unless it
first passes :func:`tasksrunner.security.redact` (or ``hash_token``,
whose digests are what sidecars legitimately store and compare).

The flow itself is solved by :class:`~tasksrunner.analysis.dataflow.
TaintEngine` — this module only supplies the policy (sources, sinks,
sanitizers) and turns the engine's sink hits into findings whose chain
walks source → intermediate calls → sink.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tasksrunner.analysis.core import Finding, register_dataflow, DataflowRule
from tasksrunner.analysis.dataflow import (
    DataflowAnalysis,
    FunctionInfo,
    TaintEngine,
    TaintSpec,
)
from tasksrunner.envflag import FLAGS

#: env names whose values are credentials (the inventory's secret
#: flags; TASKSRUNNER_API_TOKEN today)
SECRET_ENV = frozenset(n for n, f in FLAGS.items() if f.secret)

#: header names (lowercased) that carry tokens
SECRET_HEADERS = frozenset({"authorization", "tr-api-token",
                            "proxy-authorization"})

#: methods on secrets stores/resolvers whose results are secret values
_SECRET_METHODS = frozenset({"resolve_value", "resolve_metadata",
                             "get", "bulk", "keys"})

#: unresolved attribute calls distinctive enough to trust by name
_SECRET_ATTR_CALLS = frozenset({"resolve_value", "resolve_metadata",
                                "private_bytes", "load_pem_private_key"})

_LOG_METHODS = frozenset({"debug", "info", "warning", "error",
                          "exception", "critical", "log"})
_METRIC_METHODS = frozenset({"inc", "set_gauge", "observe",
                             "observe_many", "recorder", "labels"})
_SPAN_METHODS = frozenset({"set_attribute"})


def _module_constant(engine: TaintEngine, fn: FunctionInfo,
                     name: str) -> str | None:
    """Resolve ``NAME`` to its module-level string constant, following
    one ``from x import NAME`` hop (``TOKEN_HEADER`` etc.)."""
    mod = engine.dfa.module(fn)
    for target in (mod, None):
        if target is None:
            fq = mod.imports.get(name)
            if not fq or "." not in fq:
                return None
            owner, _, name = fq.rpartition(".")
            target = engine.dfa.graph.by_modname.get(owner)
            if target is None:
                return None
        for node in target.tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == name:
                        return node.value.value
    return None


def _literal(engine: TaintEngine, fn: FunctionInfo,
             expr: ast.AST) -> str | None:
    """String value of an expression: literal, module constant, or
    either with a trailing ``.lower()``/``.upper()``."""
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr in ("lower", "upper") and not expr.args:
        inner = _literal(engine, fn, expr.func.value)
        return inner.lower() if inner is not None else None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.Name):
        return _module_constant(engine, fn, expr.id)
    return None


class SecretTaintSpec(TaintSpec):
    def source(self, engine: TaintEngine, fn: FunctionInfo,
               call: ast.Call) -> str | None:
        func = call.func
        # resolved call into the secrets package
        for key in engine._callee_keys(fn, call):
            callee = engine.dfa.graph.functions.get(key)
            if callee is not None \
                    and callee.relpath.startswith("tasksrunner/secrets/") \
                    and callee.name in _SECRET_METHODS:
                return f"secret store {callee.qualname}()"
        if isinstance(func, ast.Attribute):
            if func.attr in _SECRET_ATTR_CALLS:
                return f".{func.attr}() result"
            # request.headers.get("authorization" | TOKEN_HEADER)
            if func.attr == "get" and isinstance(func.value, ast.Attribute) \
                    and func.value.attr == "headers" and call.args:
                header = _literal(engine, fn, call.args[0])
                if header and header.lower() in SECRET_HEADERS:
                    return f"{header} header"
            # os.environ.get(SECRET_ENV) / os.getenv(...)
            dotted = engine.dfa.resolve_dotted(fn, func)
            if dotted in ("os.environ.get", "os.getenv") and call.args:
                env = _literal(engine, fn, call.args[0])
                if env in SECRET_ENV:
                    return f"secret env {env}"
            # freshly minted token material (per-app tokens et al.)
            if dotted in ("secrets.token_hex", "secrets.token_bytes",
                          "secrets.token_urlsafe"):
                return f"{dotted}() token"
        return None

    def source_expr(self, engine: TaintEngine, fn: FunctionInfo,
                    expr: ast.AST) -> str | None:
        # request.headers[TOKEN_HEADER] / os.environ[SECRET]
        if isinstance(expr, ast.Subscript):
            base = expr.value
            key = _literal(engine, fn, expr.slice)
            if key is None:
                return None
            if isinstance(base, ast.Attribute) and base.attr == "headers" \
                    and key.lower() in SECRET_HEADERS:
                return f"{key} header"
            dotted = engine.dfa.resolve_dotted(fn, base)
            if dotted == "os.environ" and key in SECRET_ENV:
                return f"secret env {key}"
        return None

    def sink(self, engine: TaintEngine, fn: FunctionInfo,
             call: ast.Call) -> str | None:
        func = call.func
        if isinstance(func, ast.Attribute):
            base = func.value
            if func.attr in _LOG_METHODS and isinstance(base, ast.Name) \
                    and ("log" in base.id.lower() or base.id == "logging"):
                return "logging call"
            if func.attr in _LOG_METHODS:
                dotted = engine.dfa.resolve_dotted(fn, func)
                if dotted and dotted.startswith("logging."):
                    return "logging call"
            if func.attr in _SPAN_METHODS:
                return "span attribute"
            if func.attr in _METRIC_METHODS:
                for key in engine._callee_keys(fn, call):
                    callee = engine.dfa.graph.functions.get(key)
                    if callee is not None and callee.relpath.startswith(
                            "tasksrunner/observability/"):
                        return "metric label"
                if func.attr == "labels":
                    return "metric label"
        name = func.id if isinstance(func, ast.Name) else None
        if name == "record_span":
            return "span record"
        if name == "_json_error":
            return "HTTP error body"
        if name == "json_response" or (
                isinstance(func, ast.Attribute)
                and func.attr == "json_response"):
            for kw in call.keywords:
                if kw.arg == "status" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, int) \
                        and kw.value.value >= 400:
                    return "HTTP error body"
        return None

    def sanitizer(self, engine: TaintEngine, fn: FunctionInfo,
                  call: ast.Call) -> bool:
        func = call.func
        name = func.attr if isinstance(func, ast.Attribute) else \
            func.id if isinstance(func, ast.Name) else None
        if name in ("redact", "hash_token"):
            return True
        for key in engine._callee_keys(fn, call):
            if key in ("tasksrunner/security.py::redact",
                       "tasksrunner/security.py::hash_token"):
                return True
        return False


@register_dataflow
class SecretTaintRule(DataflowRule):
    id = "secret-taint"
    doc = ("secrets (store values, tokens, key material, secret env "
           "flags) must pass redact()/hash_token() before any log, "
           "metric, span, or HTTP error body")

    def check(self, dfa: DataflowAnalysis) -> Iterable[Finding]:
        engine = TaintEngine(dfa, SecretTaintSpec())
        engine.solve()
        for fn in sorted(dfa.graph.functions.values(),
                         key=lambda f: (f.relpath, f.lineno)):
            for hit in engine.sink_hits.get(fn.key, ()):
                for label in sorted(lb for lb in hit.labels
                                    if lb[0] == "SECRET"):
                    _, src_path, src_line, src_desc = label
                    chain = (f"{src_path}:{src_line}",
                             f"{fn.relpath}:{hit.lineno}") + hit.tail
                    yield Finding(
                        path=fn.relpath, line=hit.lineno, col=1,
                        rule=self.id,
                        message=(f"{src_desc} (from {src_path}:{src_line}) "
                                 f"reaches {hit.desc} in {fn.qualname} "
                                 "without redact()"),
                        chain=chain)
