"""lock-discipline — shared mutable state needs a declared lock.

The SQLite engines hand work between the event loop and dedicated
threads; the contract (PR 1) is that every attribute both sides mutate
is guarded by a ``threading.Lock`` held at *every* write. A write that
skips the lock is invisible until a torn list or lost update shows up
under load — exactly the class of bug code review misses because each
side looks correct alone.

Per class, the rule:

* finds lock attributes (``self.x = threading.Lock()`` /
  ``RLock`` / ``Condition``);
* finds *thread-context* methods — those passed to
  ``threading.Thread(target=self.m)``, ``executor.submit(self.m)`` or
  ``run_in_executor(..., self.m)``;
* flags attributes assigned both in a thread-context method and in an
  ``async def`` (loop-context) method when either write site is not
  inside a ``with self.<lock>:`` block (``__init__`` is exempt —
  construction happens-before both sides);
* flags inconsistent lock *ordering*: ``with self.a: with self.b:`` in
  one method and ``with self.b: with self.a:`` in another is a latent
  deadlock.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tasksrunner.analysis.core import (
    FileContext, Finding, Rule, import_table, register, resolve_call,
)

_LOCK_FACTORIES = {"threading.Lock", "threading.RLock",
                   "threading.Condition", "threading.Semaphore",
                   "threading.BoundedSemaphore"}


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, cls: ast.ClassDef, imports: dict[str, str]):
        self.node = cls
        self.locks: set[str] = set()
        self.thread_methods: set[str] = set()
        self.methods: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = {}
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        for node in ast.walk(cls):
            # self.x = threading.Lock()
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                target = resolve_call(imports, node.value.func)
                if target in _LOCK_FACTORIES:
                    for tgt in node.targets:
                        attr = _self_attr(tgt)
                        if attr:
                            self.locks.add(attr)
            # Thread(target=self.m) / submit(self.m) / run_in_executor(_, self.m)
            if isinstance(node, ast.Call):
                tname = resolve_call(imports, node.func) or ""
                attr_call = (node.func.attr
                             if isinstance(node.func, ast.Attribute) else "")
                candidates: list[ast.AST] = []
                if tname.endswith("threading.Thread") or tname == "Thread":
                    candidates += [kw.value for kw in node.keywords
                                   if kw.arg == "target"]
                elif attr_call == "submit" and node.args:
                    candidates.append(node.args[0])
                elif attr_call == "run_in_executor" and len(node.args) >= 2:
                    candidates.append(node.args[1])
                for cand in candidates:
                    attr = _self_attr(cand)
                    if attr:
                        self.thread_methods.add(attr)


@register
class LockDiscipline(Rule):
    id = "lock-discipline"
    doc = ("attributes mutated from both thread and loop contexts must "
           "hold a declared lock; nested locks must acquire in one order")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        imports = import_table(ctx.tree)
        for node in self.walk(ctx):
            if isinstance(node, ast.ClassDef):
                info = _ClassInfo(node, imports)
                if info.locks:
                    yield from self._check_shared_writes(ctx, info)
                    yield from self._check_ordering(ctx, info)

    # -- unguarded cross-context writes ---------------------------------

    def _writes(self, fn: ast.AST, locks: set[str],
                ) -> Iterator[tuple[str, ast.AST, bool]]:
        """(attr, site, guarded) for each ``self.attr`` store in fn.
        ``guarded`` means the write sits inside ``with self.<lock>:``."""

        def visit(node: ast.AST, held: bool) -> Iterator[tuple[str, ast.AST, bool]]:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # nested scope: runs elsewhere
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr in locks:
                        held = True
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr and attr not in locks:
                        yield attr, node, held
                # slice stores: self.x[k] = v mutates self.x
                for tgt in targets:
                    if isinstance(tgt, ast.Subscript):
                        attr = _self_attr(tgt.value)
                        if attr and attr not in locks:
                            yield attr, node, held
            for child in ast.iter_child_nodes(node):
                yield from visit(child, held)

        yield from visit(fn, False)

    def _check_shared_writes(self, ctx: FileContext, info: _ClassInfo,
                             ) -> Iterator[Finding]:
        per_method: dict[str, list[tuple[str, ast.AST, bool]]] = {}
        for name, fn in info.methods.items():
            if name == "__init__":
                continue
            per_method[name] = list(self._writes(fn, info.locks))

        def written_in(names: Iterable[str]) -> set[str]:
            return {attr for m in names for attr, _, _ in per_method.get(m, ())}

        thread_side = written_in(info.thread_methods)
        loop_side = written_in(
            m for m, fn in info.methods.items()
            if isinstance(fn, ast.AsyncFunctionDef)
            and m not in info.thread_methods)
        shared = thread_side & loop_side
        for method, writes in per_method.items():
            is_thread = method in info.thread_methods
            is_loop = isinstance(info.methods[method], ast.AsyncFunctionDef)
            if not (is_thread or is_loop):
                continue
            for attr, site, guarded in writes:
                if attr in shared and not guarded:
                    side = "thread" if is_thread else "event-loop"
                    yield ctx.finding(
                        self.id, site,
                        f"self.{attr} is written from both thread and loop "
                        f"contexts but this {side}-side write in "
                        f"{info.node.name}.{method}() holds none of the "
                        f"declared locks ({', '.join(sorted(info.locks))})")

    # -- acquisition ordering -------------------------------------------

    def _check_ordering(self, ctx: FileContext, info: _ClassInfo,
                        ) -> Iterator[Finding]:
        pairs: dict[tuple[str, str], ast.AST] = {}

        def visit(node: ast.AST, held: tuple[str, ...]) -> Iterator[Finding]:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr in info.locks:
                        for outer in held:
                            if outer != attr:
                                pair = (outer, attr)
                                pairs.setdefault(pair, node)
                                if (attr, outer) in pairs:
                                    yield ctx.finding(
                                        self.id, node,
                                        f"lock order conflict in "
                                        f"{info.node.name}: self.{attr} is "
                                        f"taken while holding self.{outer} "
                                        f"here, but elsewhere (line "
                                        f"{pairs[(attr, outer)].lineno}) the "
                                        "same two locks nest the other way — "
                                        "latent deadlock")
                        held = held + (attr,)
            for child in ast.iter_child_nodes(node):
                yield from visit(child, held)

        yield from visit(info.node, ())
