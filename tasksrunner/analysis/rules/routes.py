"""route-conformance: HTTP request sites vs the declared route tables.

The sidecar declares its surface with aiohttp ``RouteTableDef``
decorators (``@routes.post("/v1.0/state/{store}")``,
``routes.route("*", ...)``); the orchestrator admin plane registers
via ``app.router.add_get(...)``. The SDK (``client.py``), the CLI's
sidecar/admin helpers, and the actor runtime's peer-forwarding all
*construct* paths against those tables by hand — nothing checks them
against each other, so a renamed segment or a dropped parameter only
surfaces as a 404 at runtime. Same cross-artifact shape as the
metric-names and flag-inventory rules, one level up the stack.

Request paths are flattened conservatively: f-string interpolations
become ``{*}`` (matches any single segment), string concatenation
tails become ``{**}`` (matches any remaining segments), so only
*literal* drift is flagged — a site that is entirely dynamic can match
anything and never fires. Matching is site → route only: every
request site must match some declared route; unused routes are fine.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from tasksrunner.analysis.core import Finding, ProgramRule, register_program
from tasksrunner.analysis.program import ModuleInfo, ProgramGraph

_VERBS = {"get", "post", "put", "delete", "patch", "head", "options"}

#: request-helper call shapes: callable name → (method-arg index,
#: path-arg index, implicit path prefix the helper prepends)
_HELPERS = {
    "_request": (0, 1, ""),
    "_sidecar_request": (1, 2, "/v1.0/"),
    "_admin_request": (1, 2, ""),
    "_http_forward": (1, 2, ""),
}

#: only paths under these anchors are checkable — everything else
#: (external URLs, arbitrary strings) is out of scope
_ANCHORS = ("/v1.0/", "/admin/")


@dataclasses.dataclass(frozen=True)
class _Route:
    method: str          # upper-case verb or "*"
    path: str
    relpath: str
    lineno: int

    @property
    def segments(self) -> tuple[str, ...]:
        return tuple(s for s in self.path.split("/") if s)


@dataclasses.dataclass(frozen=True)
class _Site:
    method: str          # upper-case verb or "*"
    path: str            # flattened: literals, {*}, {**}
    relpath: str
    lineno: int

    @property
    def segments(self) -> tuple[str, ...]:
        return tuple(s for s in self.path.split("/") if s)


def _flatten(node: ast.AST) -> str | None:
    """Conservative string shape of a path expression; None = fully
    dynamic (nothing checkable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            else:
                parts.append("{*}")
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _flatten(node.left)
        return f"{left}{{**}}" if left is not None else None
    return None


def _is_rest(route_seg: str) -> bool:
    """aiohttp rest parameter: ``{name:.*}`` swallows the remainder."""
    return route_seg.startswith("{") and ":" in route_seg \
        and route_seg.endswith("}")


def _is_param(route_seg: str) -> bool:
    return route_seg.startswith("{") and route_seg.endswith("}")


def _seg_match(site_seg: str, route_seg: str) -> bool:
    if site_seg == "{*}" or site_seg == "{**}":
        return True
    if _is_param(route_seg):
        return True
    if "{*" in site_seg:
        # mixed segment like "logs{*}": the dynamic tail may be empty
        # (a query string, an optional suffix) — match on the literal
        # prefix only
        prefix = site_seg.split("{", 1)[0]
        return route_seg.startswith(prefix)
    return site_seg == route_seg


def _segments_match(site: tuple[str, ...], route: tuple[str, ...]) -> bool:
    def walk(i: int, j: int) -> bool:
        if j < len(route) and _is_rest(route[j]):
            return True  # rest param matches ≥0 remaining segments
        if i < len(site) and site[i] == "{**}":
            return True  # unknown site tail matches ≥0 remaining route
        if i == len(site) or j == len(route):
            return i == len(site) and j == len(route)
        return _seg_match(site[i], route[j]) and walk(i + 1, j + 1)

    return walk(0, 0)


def _match(site: _Site, route: _Route) -> bool:
    if site.method != "*" and route.method != "*" \
            and site.method != route.method:
        return False
    return _segments_match(site.segments, route.segments)


def _closest(site: _Site, routes: list[_Route]) -> _Route | None:
    def score(route: _Route) -> int:
        pts = sum(2 for a, b in zip(site.segments, route.segments)
                  if a == b) \
            + sum(1 for a, b in zip(site.segments, route.segments)
                  if a != b and _seg_match(a, b))
        if len(site.segments) == len(route.segments):
            pts += 1
        if site.method in ("*", route.method) or route.method == "*":
            pts += 1
        return pts

    return max(routes, key=score, default=None)


@register_program
class RouteConformance(ProgramRule):
    id = "route-conformance"
    doc = ("hand-built request path drifted from the declared "
           "sidecar/admin route tables")

    def check(self, graph: ProgramGraph) -> Iterable[Finding]:
        routes = self._routes(graph)
        if not routes:
            return
        for site in self._sites(graph):
            if any(_match(site, r) for r in routes):
                continue
            near = _closest(site, routes)
            hint = f" (closest route: {near.method} {near.path}, " \
                   f"{near.relpath}:{near.lineno})" if near else ""
            yield Finding(
                path=site.relpath, line=site.lineno, col=1, rule=self.id,
                message=f"request {site.method} {site.path} matches no "
                        f"declared route{hint}",
                chain=(f"{site.relpath}:{site.lineno}",)
                + ((f"{near.relpath}:{near.lineno}",) if near else ()))

    # -- route tables ------------------------------------------------------

    def _routes(self, graph: ProgramGraph) -> list[_Route]:
        routes: list[_Route] = []
        for mod in graph.modules.values():
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        routes.extend(self._route_from_call(mod, dec))
                elif isinstance(node, ast.Call):
                    routes.extend(self._router_add(mod, node))
        return routes

    def _route_from_call(self, mod: ModuleInfo,
                         call: ast.AST) -> list[_Route]:
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)):
            return []
        verb = call.func.attr
        if verb in _VERBS and call.args:
            path = _flatten(call.args[0])
            if path is not None and path.startswith("/"):
                return [_Route(verb.upper(), path, mod.relpath, call.lineno)]
        if verb == "route" and len(call.args) >= 2:
            method = _flatten(call.args[0])
            path = _flatten(call.args[1])
            if method and path is not None and path.startswith("/"):
                return [_Route(method.upper(), path, mod.relpath,
                               call.lineno)]
        return []

    def _router_add(self, mod: ModuleInfo, call: ast.Call) -> list[_Route]:
        if not isinstance(call.func, ast.Attribute):
            return []
        name = call.func.attr
        if name.startswith("add_") and name[4:] in _VERBS and call.args:
            path = _flatten(call.args[0])
            if path is not None and path.startswith("/"):
                return [_Route(name[4:].upper(), path, mod.relpath,
                               call.lineno)]
        if name == "add_route" and len(call.args) >= 2:
            method = _flatten(call.args[0])
            path = _flatten(call.args[1])
            if method and path is not None and path.startswith("/"):
                return [_Route(method.upper(), path, mod.relpath,
                               call.lineno)]
        return []

    # -- request sites -----------------------------------------------------

    def _sites(self, graph: ProgramGraph) -> list[_Site]:
        sites: list[_Site] = []
        seen: set[tuple[str, int, str]] = set()
        consumed: set[int] = set()

        def add(mod: ModuleInfo, lineno: int, method: str,
                flat: str) -> None:
            anchor = min((flat.find(a) for a in _ANCHORS
                          if flat.find(a) >= 0), default=-1)
            if anchor < 0:
                return
            path = flat[anchor:]
            key = (mod.relpath, lineno, path)
            if key not in seen:
                seen.add(key)
                sites.append(_Site(method, path, mod.relpath, lineno))

        def walk(mod: ModuleInfo, node: ast.AST, infn: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                infn = node.name
            if isinstance(node, ast.Call):
                fname = node.func.attr \
                    if isinstance(node.func, ast.Attribute) else (
                        node.func.id if isinstance(node.func, ast.Name)
                        else "")
                # inside a helper's own body every path is dynamic by
                # construction — the callers are the checkable sites
                if fname in _HELPERS and infn not in _HELPERS:
                    mi, pi, prefix = _HELPERS[fname]
                    if len(node.args) > pi:
                        consumed.add(id(node.args[pi]))
                        method = _flatten(node.args[mi]) or "*"
                        flat = _flatten(node.args[pi])
                        if flat is not None:
                            add(mod, node.lineno,
                                method.upper() if method != "*" else "*",
                                prefix + flat if not flat.startswith("/")
                                else flat)
                elif fname in _VERBS | {"request"} and infn not in _HELPERS:
                    for arg in node.args:
                        if id(arg) in consumed:
                            continue
                        flat = _flatten(arg)
                        if flat is not None:
                            method = fname.upper() \
                                if fname in _VERBS else "*"
                            add(mod, node.lineno, method, flat)
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, (ast.JoinedStr, ast.BinOp)) \
                    and infn not in _HELPERS:
                flat = _flatten(node.value)
                if flat is not None:
                    add(mod, node.lineno, "*", flat)
            for child in ast.iter_child_nodes(node):
                walk(mod, child, infn)

        for mod in graph.modules.values():
            walk(mod, mod.tree, "")
        return sites
