"""Whole-program lock discipline: ordering cycles and await-while-held.

``lock-order-cycle`` builds a global acquired-before relation over
every declared ``threading`` lock (class attributes and module-level
locks): ``A → B`` when some function acquires B while holding A —
either via a nested ``with`` in one body, or interprocedurally when a
function holding A calls (transitively) into code that acquires B. A
cycle in that relation is a deadlock waiting for the right
interleaving, and the two halves are usually in different files, which
is exactly why the per-file rule from PR 4 cannot see it.

``held-lock-across-await`` flags a ``with <threading lock>:`` block in
an async function whose body awaits. While the coroutine is suspended
the lock stays held; any other task (or thread) that touches the same
lock then blocks — and if that contender runs on the event loop, the
loop wedges entirely.
"""

from __future__ import annotations

from typing import Iterable

from tasksrunner.analysis.core import Finding, ProgramRule, register_program
from tasksrunner.analysis.program import FunctionInfo, ProgramGraph


def _short(lock: str) -> str:
    """Display name: drop the ``relpath::`` qualifier."""
    return lock.rsplit("::", 1)[-1]


@register_program
class HeldLockAcrossAwait(ProgramRule):
    id = "held-lock-across-await"
    doc = "threading lock held across an await suspends every contender"

    def check(self, graph: ProgramGraph) -> Iterable[Finding]:
        for fn in sorted(graph.functions.values(),
                         key=lambda f: (f.relpath, f.lineno)):
            if not fn.is_async:
                continue
            for site in fn.lock_sites:
                if not site.awaits_inside:
                    continue
                chain = (graph.frame(fn, site.lineno),
                         graph.frame(fn, site.await_lineno or site.lineno))
                yield Finding(
                    path=fn.relpath, line=site.lineno, col=1, rule=self.id,
                    message=f"threading lock {_short(site.lock)} is held "
                            f"across an await in {fn.qualname}; the loop "
                            "cannot run other tasks while a contender "
                            "blocks on it",
                    chain=chain)


@register_program
class LockOrderCycle(ProgramRule):
    id = "lock-order-cycle"
    doc = "global acquired-before relation over declared locks has a cycle"

    def check(self, graph: ProgramGraph) -> Iterable[Finding]:
        # acquired-before edges with one witness per ordered pair:
        # (outer, inner) → (fn, lineno, description)
        edges: dict[tuple[str, str], tuple[FunctionInfo, int, str]] = {}
        memo: dict[str, frozenset] = {}
        for fn in graph.functions.values():
            for site in fn.lock_sites:
                for inner in site.inner:
                    edges.setdefault((site.lock, inner), (
                        fn, site.lineno,
                        f"{fn.qualname} acquires {_short(inner)} while "
                        f"holding {_short(site.lock)}"))
            for edge in fn.edges:
                if edge.dispatch or not edge.held_locks:
                    continue
                callee = graph.functions.get(edge.callee)
                if callee is None:
                    continue
                for inner in sorted(self._acquires(graph, callee, memo,
                                                   frozenset())):
                    for outer in edge.held_locks:
                        if outer == inner:
                            continue
                        edges.setdefault((outer, inner), (
                            fn, edge.lineno,
                            f"{fn.qualname} calls {callee.qualname} "
                            f"(acquires {_short(inner)}) while holding "
                            f"{_short(outer)}"))
        adj: dict[str, set[str]] = {}
        for outer, inner in edges:
            adj.setdefault(outer, set()).add(inner)

        reported: set[frozenset] = set()
        for (outer, inner), (fn, lineno, _) in sorted(
                edges.items(), key=lambda kv: (kv[1][0].relpath, kv[1][1])):
            back = self._path(adj, inner, outer)
            if back is None:
                continue
            cycle = [outer] + back  # [outer, inner, ..., outer]
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            frames, notes = [], []
            for a, b in zip(cycle, cycle[1:]):
                wfn, wline, wdesc = edges[(a, b)]
                frames.append(graph.frame(wfn, wline))
                notes.append(wdesc)
            yield Finding(
                path=fn.relpath, line=lineno, col=1, rule=self.id,
                message="lock order cycle "
                        + " -> ".join(_short(n) for n in cycle)
                        + ": " + "; ".join(notes),
                chain=tuple(frames))

    def _acquires(self, graph: ProgramGraph, fn: FunctionInfo,
                  memo: dict[str, frozenset],
                  stack: frozenset) -> frozenset:
        """Locks ``fn`` may acquire, directly or via non-dispatch
        callees. Memoised; recursion through cycles contributes the
        partial set, which only under-approximates."""
        if fn.key in memo:
            return memo[fn.key]
        if fn.key in stack:
            return frozenset()
        acq = {site.lock for site in fn.lock_sites}
        for edge in fn.edges:
            if edge.dispatch:
                continue
            callee = graph.functions.get(edge.callee)
            if callee is not None:
                acq |= self._acquires(graph, callee, memo,
                                      stack | {fn.key})
        result = frozenset(acq)
        memo[fn.key] = result
        return result

    @staticmethod
    def _path(adj: dict[str, set[str]], src: str,
              dst: str) -> list[str] | None:
        """Shortest src→…→dst node list (starting at src), else None."""
        if src == dst:
            return [src]
        prev: dict[str, str] = {src: ""}
        queue = [src]
        while queue:
            node = queue.pop(0)
            for nxt in sorted(adj.get(node, ())):
                if nxt in prev:
                    continue
                prev[nxt] = node
                if nxt == dst:
                    path = [nxt]
                    while path[-1] != src:
                        path.append(prev[path[-1]])
                    return list(reversed(path))
                queue.append(nxt)
        return None
