"""exception-flow — route handlers may only escape taxonomy types.

The static twin of the chaos harness (module 16): chaos *injects*
faults at runtime and asserts the error surface stays typed; this rule
*computes* the surface. For every aiohttp route handler (``@routes
.verb`` decorated, or registered through ``router.add_*``), the
interprocedural escape sets from :func:`~tasksrunner.analysis.dataflow
.solve_escapes` give the exception types that can reach the route
boundary. The sidecar's ``_traced`` wrapper translates
``TasksRunnerError`` subclasses to their ``http_status`` and
``json.JSONDecodeError`` to 400 — anything else becomes a raw 500
with a stack trace in the log, which is exactly the "it just blew up"
behaviour the errors.py taxonomy exists to prevent.

Allowed at the boundary: the errors.py taxonomy (and its in-package
subclasses), aiohttp's ``HTTPException`` family (web-layer redirects
and 4xx raised on purpose), ``JSONDecodeError`` (mapped to 400), and
``CancelledError`` (the client went away — aiohttp handles it). Every
other escaping type is a finding whose chain walks handler → call →
leaf ``raise``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tasksrunner.analysis.core import Finding, register_dataflow, DataflowRule
from tasksrunner.analysis.dataflow import DataflowAnalysis, FunctionInfo

_VERBS = frozenset({"get", "post", "put", "delete", "patch", "head",
                    "options", "route", "view"})
_ADD_VERBS = frozenset({"add_get", "add_post", "add_put", "add_delete",
                        "add_patch", "add_head", "add_route", "add_view"})

#: escaping these at the boundary is fine (translated or intentional)
_BOUNDARY_OK = frozenset({"JSONDecodeError", "CancelledError",
                          "StopAsyncIteration"})


def _taxonomy(dfa: DataflowAnalysis) -> frozenset:
    """Names of errors.py classes plus their in-package subclasses."""
    graph = dfa.graph
    allowed: set[str] = set()
    for cinfo in graph.classes.values():
        if cinfo.relpath == "tasksrunner/errors.py":
            allowed.add(cinfo.name)
    grew = True
    while grew:
        grew = False
        for cinfo in graph.classes.values():
            if cinfo.name not in allowed and \
                    any(b in allowed for b in cinfo.base_names):
                allowed.add(cinfo.name)
                grew = True
    return frozenset(allowed)


def _route_handlers(dfa: DataflowAnalysis) -> list[FunctionInfo]:
    """Functions declared as HTTP route handlers: decorator form
    (``@routes.post(...)``, ``@x.route(...)``) or registration form
    (``router.add_get("/p", handler)``)."""
    graph = dfa.graph
    handlers: dict[str, FunctionInfo] = {}
    for fn in graph.functions.values():
        for dec in getattr(fn.node, "decorator_list", []):
            if isinstance(dec, ast.Call) \
                    and isinstance(dec.func, ast.Attribute) \
                    and dec.func.attr in _VERBS:
                handlers[fn.key] = fn
    for mod in graph.modules.values():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ADD_VERBS):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    fn = mod.functions.get(arg.id)
                    if fn is not None:
                        handlers[fn.key] = fn
                elif isinstance(arg, ast.Attribute) \
                        and isinstance(arg.value, ast.Name):
                    # self.handler / obj.handler registrations
                    for cinfo in mod.classes.values():
                        hit = cinfo.methods.get(arg.attr)
                        if hit is not None:
                            handlers[hit.key] = hit
    return sorted(handlers.values(), key=lambda f: (f.relpath, f.lineno))


@register_dataflow
class ExceptionFlowRule(DataflowRule):
    id = "exception-flow"
    doc = ("route handlers may only let errors.py taxonomy types (or "
           "web.HTTPException) escape — anything else surfaces as a "
           "raw 500")

    def check(self, dfa: DataflowAnalysis) -> Iterable[Finding]:
        allowed = _taxonomy(dfa)
        for fn in _route_handlers(dfa):
            escapes = dfa.escapes.get(fn.key, {})
            for name in sorted(escapes):
                if name in allowed or name in _BOUNDARY_OK:
                    continue
                if name.startswith("HTTP"):  # web.HTTPNotFound & co
                    continue
                lineno, _via = escapes[name]
                chain = (f"{fn.relpath}:{fn.lineno}",) + \
                    dfa.escape_chain(fn.key, name)
                yield Finding(
                    path=fn.relpath, line=lineno, col=1, rule=self.id,
                    message=(f"route handler {fn.qualname} may raise "
                             f"{name}, which is outside the errors.py "
                             "taxonomy — the sidecar will answer a raw "
                             "500; translate it to a TasksRunnerError "
                             "subclass"),
                    chain=chain)
