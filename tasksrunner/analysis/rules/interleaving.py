"""Interleave-phase rules: await-atomicity and fencing discipline.

``interleave-check-act`` flags the classic TOCTOU shape of cooperative
concurrency: a branch tests shared state, the coroutine suspends, and
the guarded region then writes the same location — by which time any
other task may have invalidated the test. Only locations some *other*
function also writes are reported (single-writer state cannot race),
and the three guards that make the window benign — both ends under the
same asyncio lock, an etag-threaded CAS write, a ``>=``-monotone epoch
fence — suppress the finding, so what remains is an unguarded window
over genuinely contested state.

``fenced-etag-origin`` and ``fenced-epoch-monotone`` police the
protocol lanes marked ``# tasklint: fenced-lane`` (actor turn commit,
replication leader append, workflow history append). On those lanes
the *only* thing standing between a zombie owner and a lost write is
the fencing discipline itself: every state-plane write must thread an
etag obtained by a read or commit in the same atomic scope (a constant
or a token cached on ``self`` across turns defeats the fence), and
every epoch comparison must be monotone (equality fences reject
legitimately newer epochs and accept replayed older ones
symmetrically).

Findings carry labelled v4 chain frames — ``file:line [label]`` — that
step through the window: the check, the await that opens it, the
write, and one rival writer of the same footprint.
"""

from __future__ import annotations

from typing import Iterable

from tasksrunner.analysis.core import (
    Finding,
    InterleaveRule,
    register_interleave,
)


@register_interleave
class CheckThenActAcrossAwait(InterleaveRule):
    id = "interleave-check-act"
    doc = ("branch on shared state guarding a write to the same "
           "location across an await, with no lock/etag/epoch guard")

    def check(self, ia) -> Iterable[Finding]:
        for fn in ia.iter_async_functions():
            model = ia.model(fn)
            seen: set[tuple] = set()
            for win in model.windows:
                chk, wr = win.check, win.write
                if chk.held_locks & wr.held_locks:
                    continue  # same asyncio lock spans both sections
                if wr.etag_threaded:
                    continue  # CAS re-validates; stale writer loses
                if chk.monotone_epoch:
                    continue  # the branch is itself a monotone fence
                if model.window_joins_checked(win):
                    continue  # teardown/join idiom: awaiting the
                    # checked object, then clearing it
                if wr.in_handler:
                    continue  # except-body write: acts on the fresh
                    # exception, not the stale check
                if any(c2.loc == chk.loc and c2.section == wr.section
                       and c2.lineno <= wr.lineno and c2 is not chk
                       for c2 in model.checks):
                    continue  # re-checked in the write's own atomic
                    # section — the recommended fix
                rivals = ia.rival_writers(fn, chk.loc)
                if not rivals:
                    continue  # nobody else writes it: cannot race
                dedup = (chk.lineno, wr.lineno, wr.via, chk.loc)
                if dedup in seen:
                    continue
                seen.add(dedup)
                rival_key = sorted(rivals)[0]
                rival = ia.graph.functions[rival_key]
                chain = [
                    ia.frame(fn.relpath, chk.lineno,
                             f"checks {chk.loc.render()}"),
                    ia.frame(fn.relpath, win.open_await,
                             "await opens window"),
                    ia.frame(fn.relpath, wr.lineno,
                             f"writes {chk.loc.render()}"),
                ]
                if wr.via is not None:
                    rel, _, line = wr.via.rpartition(":")
                    chain.append(ia.frame(rel, int(line),
                                          "write inside callee"))
                rline = ia.writer_site(rival_key, chk.loc)
                if rline is not None:
                    chain.append(ia.frame(rival.relpath, rline,
                                          f"also written by "
                                          f"{rival.qualname}"))
                yield Finding(
                    path=fn.relpath, line=chk.lineno, col=1, rule=self.id,
                    message=(
                        f"check-then-act across await in {fn.qualname}: "
                        f"{chk.loc.render()} is tested in one atomic "
                        f"section and written in a later one with no "
                        f"interposed guard; {rival.qualname} also writes "
                        f"it and can interleave at the await — re-check "
                        f"after the suspension, hold one asyncio lock "
                        f"across both, or thread an etag"),
                    chain=tuple(chain))


@register_interleave
class FencedEtagOrigin(InterleaveRule):
    id = "fenced-etag-origin"
    doc = ("state-plane write on a fenced lane whose etag does not "
           "data-flow from a read in the same atomic scope")

    def check(self, ia) -> Iterable[Finding]:
        for fn in ia.iter_async_functions():
            if not ia.fenced_lane(fn):
                continue
            model = ia.model(fn)
            for use in model.etag_uses:
                if use.origin == "read":
                    continue
                if use.origin == "constant":
                    why = (f"the token is the constant {use.detail} — "
                           f"the store cannot reject a stale owner")
                else:
                    why = (f"the token ({use.detail or use.kwarg}) is "
                           f"not derived from a read or commit in this "
                           f"atomic scope — a value cached across turns "
                           f"lets a fenced zombie win the CAS")
                chain = (
                    ia.frame(fn.relpath, fn.lineno, "fenced lane"),
                    ia.frame(fn.relpath, use.lineno,
                             f"{use.kwarg} not from a same-scope read"),
                )
                yield Finding(
                    path=fn.relpath, line=use.lineno, col=1, rule=self.id,
                    message=(
                        f"fenced-lane etag discipline in {fn.qualname}: "
                        f"{why}; thread the etag returned by the read "
                        f"or previous commit of the same turn"),
                    chain=chain)


@register_interleave
class FencedEpochMonotone(InterleaveRule):
    id = "fenced-epoch-monotone"
    doc = ("epoch comparison on a fenced lane that is not "
           ">=-monotone (equality fences break on takeover)")

    def check(self, ia) -> Iterable[Finding]:
        for fn in ia.iter_async_functions():
            if not ia.fenced_lane(fn):
                continue
            model = ia.model(fn)
            for cmp in model.epoch_compares:
                if cmp.monotone:
                    continue
                chain = (
                    ia.frame(fn.relpath, fn.lineno, "fenced lane"),
                    ia.frame(fn.relpath, cmp.lineno,
                             f"non-monotone {cmp.op} epoch compare"),
                )
                yield Finding(
                    path=fn.relpath, line=cmp.lineno, col=1, rule=self.id,
                    message=(
                        f"fenced-lane epoch discipline in {fn.qualname}: "
                        f"comparison uses {cmp.op} where the fence must "
                        f"be >=-monotone — an equality fence rejects a "
                        f"legitimately newer epoch and passes a replayed "
                        f"older one symmetrically; compare with >=/<= "
                        f"against the stored epoch"),
                    chain=chain)
