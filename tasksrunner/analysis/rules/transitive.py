"""transitive-blocking: blocking leaves reachable from async context.

The per-file ``async-blocking-call`` rule only sees blocking calls
written directly inside an ``async def``. The real offenders hide one
or more calls deep: an async handler calls a sync helper which calls
another helper which does ``time.sleep`` / ``sqlite3`` / ``pathlib``
I/O. This rule walks the ProgramGraph's call graph from every async
function through sync callees — stopping at dispatch sites
(``to_thread`` / ``run_in_executor`` / ``Thread(target=...)``) and at
functions declared ``# tasklint: off-loop`` — and reports the first
path that ends at a direct blocking operation. The finding carries the
full chain as ``file:line`` frames: entry call site first, blocking
leaf last.
"""

from __future__ import annotations

from typing import Iterable

from tasksrunner.analysis.core import Finding, ProgramRule, register_program
from tasksrunner.analysis.program import (
    BlockingOp,
    FunctionInfo,
    ProgramGraph,
)


@register_program
class TransitiveBlocking(ProgramRule):
    id = "transitive-blocking"
    doc = ("sync call chain from an async function reaches a blocking "
           "operation with no off-loop dispatch on the path")

    def check(self, graph: ProgramGraph) -> Iterable[Finding]:
        reported: set[tuple[str, str]] = set()
        for fn in sorted(graph.functions.values(),
                         key=lambda f: (f.relpath, f.lineno)):
            if not fn.is_async:
                continue
            for edge in sorted(fn.edges, key=lambda e: e.lineno):
                if edge.dispatch:
                    continue
                callee = graph.functions.get(edge.callee)
                if callee is None or callee.is_async or callee.off_loop:
                    continue
                hit = self._dfs(graph, callee, frozenset({fn.key, callee.key}))
                if hit is None:
                    continue
                frames, op, leaf = hit
                if (fn.key, leaf.key) in reported:
                    continue
                reported.add((fn.key, leaf.key))
                chain = (graph.frame(fn, edge.lineno),) + frames
                yield Finding(
                    path=fn.relpath, line=edge.lineno, col=1, rule=self.id,
                    message=f"async {fn.qualname} reaches blocking "
                            f"{op.target} in {leaf.qualname} with no "
                            f"off-loop dispatch on the path ({op.message})",
                    chain=chain)

    def _dfs(self, graph: ProgramGraph, fn: FunctionInfo,
             seen: frozenset,
             ) -> tuple[tuple[str, ...], BlockingOp, FunctionInfo] | None:
        """First (frames, blocking op, leaf fn) reachable from ``fn``
        over sync, non-dispatch, non-off-loop edges. ``fn`` itself is
        already at least one call away from the async entry."""
        if fn.blocking:
            op = min(fn.blocking, key=lambda b: b.lineno)
            return (graph.frame(fn, op.lineno),), op, fn
        for edge in sorted(fn.edges, key=lambda e: e.lineno):
            if edge.dispatch:
                continue
            callee = graph.functions.get(edge.callee)
            if callee is None or callee.is_async or callee.off_loop \
                    or callee.key in seen:
                continue
            hit = self._dfs(graph, callee, seen | {callee.key})
            if hit is not None:
                frames, op, leaf = hit
                return (graph.frame(fn, edge.lineno),) + frames, op, leaf
        return None
