"""actor-turn-discipline — actor handlers stay async and hands-off.

The actor runtime's zero-lost-acked-turns guarantee
(``actors/runtime.py``) rests on two properties of the handler:

* **turns are awaitable** — the runtime serializes turns per actor id
  under an asyncio lock and bounds each with
  ``TASKSRUNNER_ACTOR_TURN_TIMEOUT_SECONDS``. A synchronous handler
  can't be timed out or interleaved; ``App.actor`` rejects it at
  registration, and this rule rejects it at lint time so the mistake
  never reaches a running host.
* **state goes through the turn** — the handler mutates ``turn.state``
  and the runtime commits it atomically with the turn under the
  fencing etag. A handler that calls the state APIs directly
  (``save_state`` / ``get_state`` / ...) writes OUTSIDE the fence:
  a zombie replica replaying that turn would not get the
  ``ActorFencedError`` the design depends on, and the write survives
  even when the turn's own commit is rejected.

Blocking calls inside handlers are already covered by
``blocking-call-in-async`` once the handler is async; this rule makes
sure it *is* async, and adds the store-API check on top.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tasksrunner.analysis.core import FileContext, Finding, Rule, register

#: Runtime/AppClient state-surface methods a turn handler must not call
#: directly — state changes ride the turn commit or they break fencing.
STATE_API_ATTRS = {
    "save_state", "save_state_item", "get_state", "delete_state",
    "get_bulk_state",
}


def _is_actor_decorator(dec: ast.expr) -> bool:
    """``@app.actor("Type")`` — a call of an attribute named ``actor``."""
    return (isinstance(dec, ast.Call)
            and isinstance(dec.func, ast.Attribute)
            and dec.func.attr == "actor")


@register
class ActorTurnDiscipline(Rule):
    id = "actor-turn-discipline"
    doc = ("actor turn handlers must be 'async def' and must not call "
           "state APIs directly (mutate turn.state; the runtime commits "
           "it under the fencing etag)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in self.walk(ctx):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(_is_actor_decorator(d) for d in node.decorator_list):
                continue
            if isinstance(node, ast.FunctionDef):
                yield ctx.finding(
                    self.id, node,
                    f"actor turn handler {node.name!r} must be 'async def' "
                    "— the runtime serializes and times out turns, which "
                    "needs an awaitable")
            yield from self._scan_body(ctx, node)

    def _scan_body(self, ctx: FileContext,
                   fn: ast.AST) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in STATE_API_ATTRS):
                yield ctx.finding(
                    self.id, node,
                    f".{node.func.attr}() inside an actor turn handler "
                    "writes outside the fencing etag — mutate turn.state "
                    "instead; the runtime commits it atomically with the "
                    "turn")
