"""Rule modules — importing this package populates ``core.RULES``
and ``core.PROGRAM_RULES``.

Import order note: the whole-program modules (transitive, lockgraph,
threadshared, routes) import :mod:`tasksrunner.analysis.program`,
which reuses the blocking-call tables from :mod:`.blocking`.
"""

from __future__ import annotations

from tasksrunner.analysis.rules import (  # noqa: F401
    actors,
    blocking,
    coroutines,
    envflags,
    lockgraph,
    locks,
    metricnames,
    routes,
    taxonomy,
    threadshared,
    transitive,
)
