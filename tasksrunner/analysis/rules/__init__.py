"""Rule modules — importing this package populates ``core.RULES``."""

from __future__ import annotations

from tasksrunner.analysis.rules import (  # noqa: F401
    actors,
    blocking,
    coroutines,
    envflags,
    locks,
    metricnames,
    taxonomy,
)
