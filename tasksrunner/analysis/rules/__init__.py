"""Rule modules — importing this package populates ``core.RULES``,
``core.PROGRAM_RULES``, ``core.DATAFLOW_RULES``, and
``core.INTERLEAVE_RULES``.

Import order note: the whole-program modules (transitive, lockgraph,
threadshared, routes) import :mod:`tasksrunner.analysis.program`,
which reuses the blocking-call tables from :mod:`.blocking`; the
dataflow modules (secrettaint, lifetime, cancelsafety, exflow) import
:mod:`tasksrunner.analysis.dataflow` on top of that, and the
interleave module builds on :mod:`tasksrunner.analysis.interleave`.
"""

from __future__ import annotations

from tasksrunner.analysis.rules import (  # noqa: F401
    actors,
    blocking,
    cancelsafety,
    coroutines,
    envflags,
    exflow,
    interleaving,
    lifetime,
    lockgraph,
    locks,
    metricnames,
    routes,
    secrettaint,
    taxonomy,
    threadshared,
    transitive,
    workflows,
)
