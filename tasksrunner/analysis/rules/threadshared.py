"""thread-shared-state: a lockset race detector over class attributes.

Classic Eraser, scaled down to what the graph knows statically: for
every class, collect all ``self.<attr>`` writes outside ``__init__``,
note each write's execution context (from the ProgramGraph's
propagation) and the set of declared locks held at the write. The
boundary of interest is *dispatched-thread code vs everything else*:
a function reachable only from a dispatch site (``Thread(target=)``,
``to_thread``, a timer, an executor) runs on its own thread, while a
function with loop context — or with no inferred context at all — runs
on whichever thread calls it (the event loop, the CLI main thread, an
``atexit`` hook). If an attribute is written on both sides of that
boundary and the intersection of held-lock sets over those writes is
empty, no single lock orders the accesses — the interleaving is a data
race.

``__init__`` writes are exempt (the object is not yet shared), as are
the lock attributes themselves. A write site whose function carries
*both* contexts counts on both sides: the same method called from the
loop and from a worker thread is precisely the hazard.
"""

from __future__ import annotations

from typing import Iterable

from tasksrunner.analysis.core import Finding, ProgramRule, register_program
from tasksrunner.analysis.program import ProgramGraph

_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__", "__init_subclass__"}


@register_program
class ThreadSharedState(ProgramRule):
    id = "thread-shared-state"
    doc = ("attribute written both from dispatched-thread context and "
           "from loop/caller context with no common lock")

    def check(self, graph: ProgramGraph) -> Iterable[Finding]:
        for ckey in sorted(graph.classes):
            cinfo = graph.classes[ckey]
            lock_attrs = graph._all_lock_attrs(cinfo)
            # attr → [(fn, write)] over every function of the class
            writes: dict[str, list] = {}
            for fn in sorted(graph.functions.values(),
                             key=lambda f: (f.relpath, f.lineno)):
                if fn.cls_key != ckey or fn.name in _EXEMPT_METHODS:
                    continue
                for w in fn.writes:
                    if w.attr in lock_attrs:
                        continue
                    writes.setdefault(w.attr, []).append((fn, w))
            for attr in sorted(writes):
                sites = writes[attr]
                thread_sites = [(f, w) for f, w in sites
                                if "thread" in f.contexts]
                # "other side": may run on the loop or on whatever
                # thread calls it — anything not proven thread-only
                other_sites = [(f, w) for f, w in sites
                               if f.contexts != {"thread"}]
                if not thread_sites or not other_sites:
                    continue
                boundary = thread_sites + other_sites
                common = frozenset.intersection(
                    *(w.held_locks for _, w in boundary))
                if common:
                    continue
                tfn, tw = thread_sites[0]
                ofn, ow = other_sites[0]
                thread_why = tfn.context_origin.get("thread", "off-loop")
                other_why = ("event-loop context"
                             if "loop" in ofn.contexts
                             else "caller context")
                yield Finding(
                    path=tfn.relpath, line=tw.lineno, col=1, rule=self.id,
                    message=f"{cinfo.name}.{attr} is written from thread "
                            f"context in {tfn.qualname} ({thread_why}) and "
                            f"from {other_why} in {ofn.qualname} with no "
                            "common lock",
                    chain=(f"{tfn.relpath}:{tw.lineno}",
                           f"{ofn.relpath}:{ow.lineno}"))
