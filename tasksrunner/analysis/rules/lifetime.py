"""resource-lifetime — closable objects must be released on all paths.

A *resource* is the result of a call that hands the function something
it must give back: an in-package constructor whose class defines
``close``/``aclose``/``cancel``/``stop`` (mesh connections, frame
writers, span recorders), a known stdlib factory (``sqlite3.connect``,
``asyncio.open_connection``, ``open``, sockets), or
``asyncio.create_task``. The CFG-based pass tracks each acquisition
along every path and reports the explicit ``return``/``raise`` (or
fall-off-the-end) through which a still-held resource leaks.

A resource stops being the function's problem when it is **released**
(a ``close``/``aclose``/``cancel``/``stop``-style call, or awaiting a
task to completion), **context-managed** (``with``/``async with`` on
the acquisition — never held at all), or **escapes to an owner** (returned,
yielded, stored into an attribute/subscript/container, or passed as a
call argument — the mesh pool appending a connection, the orchestrator
tracking a supervisor task). Exceptional paths are reported only for
*explicit* ``raise`` statements: modelling "any call may throw" would
drown the tree in paths Python programmers handle with outer
try/finally blocks they can see.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tasksrunner.analysis.core import Finding, register_dataflow, DataflowRule
from tasksrunner.analysis.dataflow import (
    Bind,
    Block,
    DataflowAnalysis,
    FunctionInfo,
    NestedDef,
    run_forward,
)

#: stdlib factories whose result must be closed
_FACTORIES = {
    "sqlite3.connect": "sqlite3 connection",
    "asyncio.open_connection": "asyncio stream pair",
    "socket.create_connection": "socket",
    "socket.socket": "socket",
    "open": "file handle",
    "asyncio.create_task": "task",
}

_RELEASE_METHODS = frozenset({"close", "aclose", "cancel", "stop",
                              "shutdown", "release", "terminate", "join",
                              "wait_closed", "unlink", "detach",
                              "close_now"})

#: reserved state key → frozenset of rids released/escaped on some path
_KILLED = "\0killed"


def _kill(state: dict, res: "_Resource") -> None:
    """Release/escape: drop every alias and remember the rid so the
    join does not resurrect it from a sibling path."""
    for other in [k for k, v in state.items()
                  if k != _KILLED and v.rid == res.rid]:
        state.pop(other, None)
    state[_KILLED] = state.get(_KILLED, frozenset()) | {res.rid}


def _unwrap_await(expr: ast.AST) -> ast.AST:
    return expr.value if isinstance(expr, ast.Await) else expr


class _Resource:
    """Identity is the acquisition site, so the fixpoint's state
    comparison is stable across repeated transfer runs."""

    __slots__ = ("rid", "lineno", "desc")

    def __init__(self, rid: tuple, lineno: int, desc: str):
        self.rid = rid  # (lineno, col) of the acquiring statement
        self.lineno = lineno
        self.desc = desc

    def __eq__(self, other) -> bool:
        return isinstance(other, _Resource) and self.rid == other.rid

    def __hash__(self) -> int:
        return hash(self.rid)


@register_dataflow
class ResourceLifetimeRule(DataflowRule):
    id = "resource-lifetime"
    doc = ("objects with close/aclose/cancel must be released, "
           "context-managed, or handed to an owner on every "
           "return/raise path")

    def check(self, dfa: DataflowAnalysis) -> Iterable[Finding]:
        for fn in sorted(dfa.graph.functions.values(),
                         key=lambda f: (f.relpath, f.lineno)):
            yield from self._check_fn(dfa, fn)

    # -- acquisition --------------------------------------------------------

    def _acquired(self, dfa: DataflowAnalysis, fn: FunctionInfo,
                  expr: ast.AST) -> str | None:
        """Resource description when ``expr`` is an acquiring call."""
        expr = _unwrap_await(expr)
        if not isinstance(expr, ast.Call):
            return None
        dotted = dfa.resolve_dotted(fn, expr.func)
        if dotted in _FACTORIES:
            return _FACTORIES[dotted]
        mod = dfa.module(fn)
        cinfo = dfa.graph._class_of_call(mod, expr)
        if cinfo is not None:
            for method in ("close", "aclose", "cancel", "stop"):
                if dfa.graph._method(cinfo, method) is not None:
                    return f"{cinfo.name} (defines {method}())"
        return None

    # -- the per-function pass ---------------------------------------------

    def _check_fn(self, dfa: DataflowAnalysis,
                  fn: FunctionInfo) -> Iterable[Finding]:
        cfg = dfa.cfg(fn)

        def transfer_events(events, state: dict, upto=None) -> dict:
            """state: name → _Resource. Returns the post-state;
            ``upto`` stops *after* processing that event (exit nodes)."""
            state = dict(state)
            for event in events:
                self._event(dfa, fn, event, state)
                if upto is not None and event is upto:
                    break
            return state

        def transfer(block: Block, state_in: dict) -> dict:
            return transfer_events(block.events, state_in)

        def join(a: dict, b: dict) -> dict:
            # may-hold union — but a release/escape observed on *any*
            # merged path kills the resource on all of them. That is
            # what makes ``if conn is not None: conn.close()`` in a
            # finally (the None branch is exactly the never-acquired
            # path) and ``for ...: owner.append(conn)`` (the zero-
            # iteration edge) precise instead of false positives.
            merged = dict(a)
            merged.update({k: v for k, v in b.items() if k not in merged})
            killed = a.get(_KILLED, frozenset()) | b.get(_KILLED, frozenset())
            merged = {k: v for k, v in merged.items()
                      if k == _KILLED or v.rid not in killed}
            if killed:
                merged[_KILLED] = killed
            return merged

        states = run_forward(cfg, {}, transfer, join)
        seen: set[tuple[int, int, str]] = set()
        for exit_ in cfg.exits:
            if exit_.block not in states:
                continue
            block = cfg.blocks[exit_.block]
            state = transfer_events(block.events, states[exit_.block],
                                    upto=exit_.node)
            for name, res in sorted(state.items()):
                if name == _KILLED:
                    continue
                marker = (res.lineno, exit_.lineno, exit_.kind)
                if marker in seen:
                    continue
                seen.add(marker)
                verb = {"return": "the return at line",
                        "raise": "the raise at line",
                        "fall": "falling off the end at line"}[exit_.kind]
                yield Finding(
                    path=fn.relpath, line=res.lineno, col=1, rule=self.id,
                    message=(f"{res.desc} acquired in {fn.qualname} is "
                             f"not released on {verb} {exit_.lineno} — "
                             "close it in a finally, use a with-block, "
                             "or hand it to a tracked owner"),
                    chain=(f"{fn.relpath}:{res.lineno}",
                           f"{fn.relpath}:{exit_.lineno}"))

    # -- transfer -----------------------------------------------------------

    def _event(self, dfa: DataflowAnalysis, fn: FunctionInfo, event,
               state: dict) -> None:
        if isinstance(event, NestedDef):
            # a closure reading a held name takes (shared) ownership —
            # cli-style ``async def main(): ... await host.stop()``
            for node in ast.walk(event.node):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load):
                    res = state.get(node.id)
                    if res is not None:
                        _kill(state, res)
            return
        if isinstance(event, Bind):
            # ``with ACQ() as x`` / ``async with`` — context-managed,
            # never held; a with on a *held* name releases it
            if event.kind == "with" and event.value is not None:
                base = _unwrap_await(event.value)
                if isinstance(base, ast.Name) and base.id in state \
                        and base.id != _KILLED:
                    _kill(state, state[base.id])
                self._escape_uses(event.value, state, skip_value=base)
            return
        if isinstance(event, (ast.Assign, ast.AnnAssign)):
            value = event.value
            if value is None:
                return
            targets = event.targets if isinstance(event, ast.Assign) \
                else [event.target]
            desc = self._acquired(dfa, fn, value)
            names: list[str] = []
            if desc is not None:
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        names.append(tgt.id)
                    elif isinstance(tgt, (ast.Tuple, ast.List)):
                        names.extend(e.id for e in tgt.elts
                                     if isinstance(e, ast.Name))
                    else:
                        desc = None  # stored straight into an owner
                        break
            self._escape_uses(value, state)
            self._releases(dfa, fn, value, state)
            inner = _unwrap_await(value)
            if isinstance(inner, ast.Name) and inner.id in state \
                    and inner.id != _KILLED and any(
                    not isinstance(t, ast.Name) for t in targets):
                _kill(state, state[inner.id])  # self.x = conn: owner store
            if desc is not None and names:
                res = _Resource((event.lineno, event.col_offset),
                                event.lineno, desc)
                killed = state.get(_KILLED, frozenset())
                if res.rid in killed:
                    # re-acquisition at the same site (loop body after a
                    # release) — live again
                    state[_KILLED] = killed - {res.rid}
                for name in names:
                    state[name] = res
            else:
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        state.pop(tgt.id, None)  # rebound, not released
            return
        if isinstance(event, ast.Return):
            if event.value is not None:
                self._releases(dfa, fn, event.value, state)
                for node in ast.walk(event.value):
                    if isinstance(node, ast.Name) and node.id in state \
                            and node.id != _KILLED:
                        _kill(state, state[node.id])  # returned = escaped
            return
        if isinstance(event, ast.Delete):
            for tgt in event.targets:
                if isinstance(tgt, ast.Name):
                    state.pop(tgt.id, None)
            return
        # generic statement: releases, then escapes
        self._releases(dfa, fn, event, state)
        self._escape_uses(event, state)

    def _releases(self, dfa: DataflowAnalysis, fn: FunctionInfo,
                  tree: ast.AST, state: dict) -> None:
        """``x.close()`` / ``await x`` / ``x.cancel()`` — drop every
        name sharing the released resource."""
        def drop(name: str) -> None:
            res = state.get(name)
            if res is not None and name != _KILLED:
                _kill(state, res)

        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _RELEASE_METHODS \
                    and isinstance(node.func.value, ast.Name):
                drop(node.func.value.id)
            elif isinstance(node, ast.Await) \
                    and isinstance(node.value, ast.Name):
                drop(node.value.id)  # awaited to completion (tasks)

    def _escape_uses(self, tree: ast.AST, state: dict,
                     skip_value: ast.AST | None = None) -> None:
        """A held name passed as a call argument, yielded, or placed in
        a container/attribute/subscript store escapes to an owner."""
        for node in ast.walk(tree):
            args: list[ast.AST] = []
            if isinstance(node, ast.Call):
                args = list(node.args) + [kw.value for kw in node.keywords]
            elif isinstance(node, (ast.List, ast.Tuple, ast.Set)):
                args = list(node.elts)
            elif isinstance(node, ast.Dict):
                args = [v for v in node.values if v is not None]
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                    and node.value is not None:
                args = [node.value]
            elif isinstance(node, ast.Starred):
                args = [node.value]
            elif isinstance(node, ast.Lambda):
                args = [n for n in ast.walk(node.body)
                        if isinstance(n, ast.Name)]
            for arg in args:
                if arg is skip_value:
                    continue
                inner = _unwrap_await(arg)
                if isinstance(inner, ast.Name) and inner.id in state \
                        and inner.id != _KILLED:
                    _kill(state, state[inner.id])
