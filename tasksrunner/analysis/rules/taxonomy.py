"""error-taxonomy — sidecar-facing paths speak errors.py.

The sidecar API maps exceptions to HTTP statuses through the
``http_status`` attribute of ``tasksrunner.errors`` types; an ad-hoc
``ValueError`` on a delivery or state path surfaces as a bare 500 with
no taxonomy, breaking both the client-side status mapping and every
dashboard that groups failures by error class. Similarly, a handler
that swallows ``except Exception: pass`` on a hot path turns real
faults into silent latency.

Scope: the sidecar-facing modules listed in :data:`HOT_PATHS` (plus any
file outside the ``tasksrunner`` package — e.g. test fixtures — so the
rule is testable in isolation). Checks:

* ``raise`` of a generic builtin (``Exception``, ``RuntimeError``,
  ``ValueError``, ``TypeError``, ``KeyError``) — use or subclass a
  type from ``tasksrunner/errors.py``;
* a locally defined exception class whose bases are only builtins —
  it belongs in the central taxonomy (or must subclass it);
* ``except Exception:`` / ``except BaseException:`` whose body is only
  ``pass``/``...`` — swallowing on a hot path hides faults;
* a bare ``except:`` anywhere (it catches ``KeyboardInterrupt`` too).
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tasksrunner.analysis.core import FileContext, Finding, Rule, register

#: repo-relative prefixes of the sidecar-facing request/delivery paths
HOT_PATHS = (
    "tasksrunner/sidecar.py",
    "tasksrunner/runtime.py",
    "tasksrunner/client.py",
    "tasksrunner/app.py",
    "tasksrunner/state/",
    "tasksrunner/pubsub/",
    "tasksrunner/bindings/",
    "tasksrunner/invoke/",
    "tasksrunner/component/",
    "tasksrunner/secrets/",
)

_GENERIC = {"Exception", "RuntimeError", "ValueError", "TypeError", "KeyError"}
_BUILTIN_BASES = _GENERIC | {"BaseException", "OSError", "IOError",
                             "LookupError", "ArithmeticError"}


def _on_hot_path(relpath: str) -> bool:
    if not relpath.startswith("tasksrunner/"):
        return True  # out-of-package targets (fixtures) get full checking
    return relpath.startswith(HOT_PATHS)


def _exc_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Call):
        node = node.func
    return node.id if isinstance(node, ast.Name) else None


@register
class ErrorTaxonomy(Rule):
    id = "error-taxonomy"
    doc = ("sidecar-facing paths raise errors.py types; no swallowed or "
           "bare excepts on hot paths")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        hot = _on_hot_path(ctx.relpath)
        for node in self.walk(ctx):
            if isinstance(node, ast.ExceptHandler):
                yield from self._check_handler(ctx, node, hot)
            elif not hot:
                continue
            elif isinstance(node, ast.Raise) and node.exc is not None:
                name = _exc_name(node.exc)
                if name in _GENERIC:
                    yield ctx.finding(
                        self.id, node,
                        f"raise {name} on a sidecar-facing path — raise a "
                        "type from tasksrunner/errors.py so the API maps it "
                        "to a status (ValidationError for bad input, "
                        "StateError/PubSubError/... for backend faults)")
            elif isinstance(node, ast.ClassDef):
                bases = {b.id for b in node.bases if isinstance(b, ast.Name)}
                if bases and bases <= _BUILTIN_BASES:
                    yield ctx.finding(
                        self.id, node,
                        f"exception class {node.name} defined outside the "
                        "taxonomy — move it to tasksrunner/errors.py or "
                        "subclass TasksRunnerError so http_status mapping "
                        "and error dashboards see it")

    def _check_handler(self, ctx: FileContext, node: ast.ExceptHandler,
                       hot: bool) -> Iterator[Finding]:
        if node.type is None:
            yield ctx.finding(
                self.id, node,
                "bare 'except:' catches KeyboardInterrupt/SystemExit — "
                "name the exception (at minimum 'except Exception')")
            return
        if not hot:
            return
        caught = _exc_name(node.type)
        swallows = all(
            isinstance(stmt, ast.Pass)
            or (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is ...)
            for stmt in node.body)
        if caught in ("Exception", "BaseException") and swallows:
            yield ctx.finding(
                self.id, node,
                f"'except {caught}: pass' swallows every fault on a hot "
                "path — log it, narrow the type, or suppress with a "
                "justifying comment")
