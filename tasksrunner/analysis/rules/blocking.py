"""blocking-call-in-async — the event loop must never block.

The whole latency story of this runtime (group-commit writes, sub-ms
busy backoff, p99 histograms) assumes the asyncio loop is free to
schedule: every SQLite statement, file read, and sleep runs on the
dedicated reader/writer threads of ``state/sqlite.py`` and
``pubsub/sqlite.py``. One synchronous ``conn.execute`` or
``time.sleep`` inside an ``async def`` stalls every request in the
process — and profiles as "mysterious p99 spikes", not as an error.

Two checks:

* inside ``async def`` bodies (nested synchronous ``def``/``lambda``
  scopes are excluded — they run wherever they're called, typically on
  an executor thread): any call matching the blocking table below;
* ``time.sleep`` anywhere else — a sync helper sleeping is only
  legitimate on a dedicated thread, which the code must declare, either
  in :data:`OFF_LOOP_ENTRYPOINTS` or with ``# tasklint: off-loop`` on
  the ``def`` line.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tasksrunner.analysis.core import (
    FileContext, Finding, Rule, import_table, register, resolve_call,
)

#: canonical dotted call targets that park the calling thread
BLOCKING_CALLS = {
    "time.sleep": "time.sleep() parks the event loop; use await asyncio.sleep() "
                  "or run the helper on an executor thread",
    "sqlite3.connect": "sqlite3.connect() does disk I/O; open connections on the "
                       "store's dedicated thread",
    "subprocess.run": "subprocess.run() blocks until the child exits; use "
                      "asyncio.create_subprocess_exec",
    "subprocess.call": "subprocess.call() blocks; use asyncio.create_subprocess_exec",
    "subprocess.check_call": "subprocess.check_call() blocks; use "
                             "asyncio.create_subprocess_exec",
    "subprocess.check_output": "subprocess.check_output() blocks; use "
                               "asyncio.create_subprocess_exec",
    "os.system": "os.system() blocks; use asyncio.create_subprocess_exec",
    "socket.create_connection": "socket.create_connection() blocks on the "
                                "handshake; use loop.create_connection",
    "urllib.request.urlopen": "urlopen() blocks on the whole response; use the "
                              "async invoke client",
}

#: builtins / bare names that block
BLOCKING_NAMES = {
    "open": "open() does disk I/O on the loop; read the file on an executor "
            "thread (run_in_executor / asyncio.to_thread)",
}

#: attribute calls that are blocking on the objects this codebase uses
#: them on (sqlite3 connections/cursors, pathlib.Path)
BLOCKING_ATTRS = {
    "execute": "sqlite .execute() runs SQL on the calling thread; submit it to "
               "the store's reader/writer executor",
    "executemany": "sqlite .executemany() blocks; submit it to the store's "
                   "executor",
    "executescript": "sqlite .executescript() blocks; submit it to the store's "
                     "executor",
    "read_text": "Path.read_text() does disk I/O; move it off-loop",
    "write_text": "Path.write_text() does disk I/O; move it off-loop",
    "read_bytes": "Path.read_bytes() does disk I/O; move it off-loop",
    "write_bytes": "Path.write_bytes() does disk I/O; move it off-loop",
}

#: declared dedicated-thread entrypoints: sync helpers that *may* block
#: because the architecture guarantees they only ever run on the
#: store's own threads (see module docstrings of both engines). Keyed
#: by repo-relative path. Kept here — next to the rule — so the
#: allowlist is reviewed whenever the rule is.
OFF_LOOP_ENTRYPOINTS: dict[str, frozenset[str]] = {
    "tasksrunner/state/sqlite.py": frozenset({
        "_begin_immediate",   # writer thread: sub-ms busy backoff
        "_checkpoint_loop",   # dedicated PASSIVE-checkpoint thread
    }),
    "tasksrunner/pubsub/sqlite.py": frozenset({
        "_write_txn",         # db thread: sub-ms busy backoff
        "_checkpoint_loop",   # dedicated PASSIVE-checkpoint thread
    }),
}


class _FnCtx:
    __slots__ = ("node", "is_async", "allowed")

    def __init__(self, node: ast.AST, is_async: bool, allowed: bool):
        self.node = node
        self.is_async = is_async
        self.allowed = allowed


@register
class BlockingCallInAsync(Rule):
    id = "blocking-call-in-async"
    doc = ("no synchronous I/O or sleeps on the event loop; sync helpers "
           "that block must be declared off-loop")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        imports = import_table(ctx.tree)
        allowed_here = OFF_LOOP_ENTRYPOINTS.get(ctx.relpath, frozenset())
        yield from self._scan(ctx, imports, ctx.tree.body, None, allowed_here)

    def _scan(self, ctx: FileContext, imports: dict[str, str],
              body: list[ast.stmt], fn: _FnCtx | None,
              allowed_here: frozenset[str]) -> Iterator[Finding]:
        for stmt in body:
            yield from self._visit(ctx, imports, stmt, fn, allowed_here)

    def _visit(self, ctx: FileContext, imports: dict[str, str],
               node: ast.AST, fn: _FnCtx | None,
               allowed_here: frozenset[str]) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            allowed = (node.name in allowed_here
                       or ctx.marked_off_loop(node))
            sub = _FnCtx(node, isinstance(node, ast.AsyncFunctionDef), allowed)
            for child in ast.iter_child_nodes(node):
                yield from self._visit(ctx, imports, child, sub, allowed_here)
            return
        if isinstance(node, ast.Lambda):
            # a lambda body runs wherever it is *called*; don't blame
            # the enclosing async scope for it
            return
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            # an awaited call is an async API (resiliency .execute(),
            # aiosqlite-style drivers): arguments still get scanned,
            # the call itself is not blocking
            call = node.value
            for child in ast.iter_child_nodes(call):
                yield from self._visit(ctx, imports, child, fn, allowed_here)
            return
        if isinstance(node, ast.Call):
            yield from self._check_call(ctx, imports, node, fn)
        for child in ast.iter_child_nodes(node):
            yield from self._visit(ctx, imports, child, fn, allowed_here)

    def _check_call(self, ctx: FileContext, imports: dict[str, str],
                    call: ast.Call, fn: _FnCtx | None) -> Iterator[Finding]:
        target = resolve_call(imports, call.func)
        in_async = fn is not None and fn.is_async
        allowed = fn is not None and fn.allowed
        if target in BLOCKING_CALLS:
            if target == "time.sleep":
                # blocking everywhere except declared off-loop helpers
                if not allowed:
                    where = ("inside async def" if in_async else
                             "in a function not declared off-loop")
                    yield ctx.finding(
                        self.id, call,
                        f"{BLOCKING_CALLS[target]} ({where}; declare the "
                        "helper in OFF_LOOP_ENTRYPOINTS or mark it "
                        "'# tasklint: off-loop' if it only runs on a "
                        "dedicated thread)")
            elif in_async and not allowed:
                yield ctx.finding(self.id, call, BLOCKING_CALLS[target])
            return
        if not in_async or allowed:
            return
        if isinstance(call.func, ast.Name) and call.func.id in BLOCKING_NAMES:
            yield ctx.finding(self.id, call, BLOCKING_NAMES[call.func.id])
        elif isinstance(call.func, ast.Attribute) and \
                call.func.attr in BLOCKING_ATTRS:
            yield ctx.finding(self.id, call, BLOCKING_ATTRS[call.func.attr])
