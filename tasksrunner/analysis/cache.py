"""Per-file and whole-program result caches.

Linting the whole package parses ~100 modules; editors and `make test`
run it repeatedly, so unchanged files must be free. The cache maps
absolute path → (mtime, size, content sha1, ruleset signature,
findings). The *content hash is the authoritative key*: mtime+size
alone miss same-size edits (editors that pad, ``touch -r`` restoring
an old mtime after a change), so ``get`` always re-hashes the file —
mtime and size are kept as debugging metadata only. Hashing ~100 small
files costs single-digit milliseconds, far below one AST parse.

The ruleset signature hashes the *source of the analysis package
itself* plus the selected rule ids, so editing any rule — or selecting
a different subset — invalidates every entry without a manual version
bump.

The whole-tree phases each store one extra entry (``__program__``,
``__dataflow__``, ``__interleave__``) keyed on a digest of the sorted
(path, content-hash) set: any file appearing, vanishing, or changing
its *bytes* rebuilds the graph; an untouched tree — including one
whose mtimes churned under ``touch`` or a branch switch — makes warm
whole-tree runs free.

Suppression comments live in the linted files, so cached findings are
post-suppression; the baseline is applied after the cache by the
engine (the baseline file can change independently of the sources).
"""

from __future__ import annotations

import hashlib
import json
import pathlib
from typing import Iterable

from tasksrunner.analysis.core import Finding

_PKG = pathlib.Path(__file__).resolve().parent

#: reserved table keys for the whole-tree phase entries — not paths
PROGRAM_KEY = "__program__"
DATAFLOW_KEY = "__dataflow__"
INTERLEAVE_KEY = "__interleave__"
_RESERVED_KEYS = frozenset({PROGRAM_KEY, DATAFLOW_KEY, INTERLEAVE_KEY})

#: (path, mtime_ns, size) → sha1, memoised per process. The proxy key
#: is safe *within* one run (nothing restores mtimes mid-lint); the
#: cross-run lie is exactly what the persisted sha1 guards against.
_digest_memo: dict[tuple[str, int, int], str] = {}


def file_digest(path: pathlib.Path) -> str | None:
    """Content sha1, or None when the file cannot be read."""
    try:
        stat = path.stat()
        key = (str(path), stat.st_mtime_ns, stat.st_size)
        hit = _digest_memo.get(key)
        if hit is not None:
            return hit
        digest = hashlib.sha1(path.read_bytes()).hexdigest()[:16]
    except OSError:
        return None
    _digest_memo[key] = digest
    return digest


def tree_digest(files: Iterable[pathlib.Path]) -> str:
    """Identity of a file *set* for the whole-tree phase caches.

    Content-only, matching the per-file cache's contract above: a
    ``touch`` (or ``git checkout`` restoring identical bytes) must not
    rebuild the ProgramGraph — ``tasksrunner lint --changed`` with an
    empty delta short-circuits to the cached ``__program__`` /
    ``__dataflow__`` / ``__interleave__`` entries only if mtime churn
    is invisible here."""
    h = hashlib.sha1()
    for path in sorted(files):
        h.update(f"{path}|{file_digest(path)}\n".encode())
    return h.hexdigest()[:16]


def ruleset_signature(rule_ids: tuple[str, ...]) -> str:
    h = hashlib.sha1()
    for src in sorted(_PKG.rglob("*.py")):
        h.update(src.name.encode())
        h.update(src.read_bytes())
    h.update("|".join(rule_ids).encode())
    return h.hexdigest()[:16]


class ResultCache:
    def __init__(self, path: pathlib.Path | None, signature: str):
        self.path = path
        self.signature = signature
        self.hits = 0
        self._dirty = False
        self._table: dict[str, dict] = {}
        if path is not None and path.is_file():
            try:
                self._table = json.loads(path.read_text()) or {}
            except ValueError:  # corrupt cache: rebuild silently
                self._table = {}
            # deleted sources leave dead entries behind forever (the
            # save() sweep only drops old-signature rows) — prune any
            # path key whose file is gone, so renames/removals don't
            # grow the cache without bound
            stale = [k for k in self._table
                     if k not in _RESERVED_KEYS
                     and not pathlib.Path(k).is_file()]
            for k in stale:
                del self._table[k]
            if stale:
                self._dirty = True

    def get(self, path: pathlib.Path
            ) -> tuple[list[Finding], int] | None:
        entry = self._table.get(str(path))
        if entry is None or entry.get("sig") != self.signature:
            return None
        digest = file_digest(path)
        if digest is None or entry.get("sha1") != digest:
            return None
        self.hits += 1
        return ([Finding.from_json(d) for d in entry.get("findings", [])],
                int(entry.get("suppressed", 0)))

    def put(self, path: pathlib.Path, findings: list[Finding],
            suppressed: int = 0) -> None:
        try:
            stat = path.stat()
        except OSError:
            return
        self._table[str(path)] = {
            "sig": self.signature,
            "mtime": stat.st_mtime_ns,
            "size": stat.st_size,
            "sha1": file_digest(path),
            "suppressed": suppressed,
            "findings": [f.to_json() for f in findings],
        }
        self._dirty = True

    def get_program(self, tree_hash: str, key: str = PROGRAM_KEY,
                    ) -> tuple[list[Finding], int] | None:
        entry = self._table.get(key)
        if entry is None or entry.get("sig") != self.signature or \
                entry.get("tree") != tree_hash:
            return None
        self.hits += 1
        return ([Finding.from_json(d) for d in entry.get("findings", [])],
                int(entry.get("suppressed", 0)))

    def put_program(self, tree_hash: str, findings: list[Finding],
                    suppressed: int, key: str = PROGRAM_KEY) -> None:
        self._table[key] = {
            "sig": self.signature,
            "tree": tree_hash,
            "suppressed": suppressed,
            "findings": [f.to_json() for f in findings],
        }
        self._dirty = True

    def save(self) -> None:
        if self.path is None or not self._dirty:
            return
        # entries from older rulesets are dead weight — drop them
        live = {k: v for k, v in self._table.items()
                if v.get("sig") == self.signature}
        self.path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.path.with_suffix(".tmp")
        tmp.write_text(json.dumps(live))
        tmp.replace(self.path)
