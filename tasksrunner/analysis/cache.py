"""Per-file result cache.

Linting the whole package parses ~80 modules; editors and `make test`
run it repeatedly, so unchanged files must be free. The cache maps
absolute path → (mtime, size, ruleset signature, findings). The
signature hashes the *source of the analysis package itself* plus the
selected rule ids, so editing any rule — or selecting a different
subset — invalidates every entry without a manual version bump.

Suppression comments live in the linted file, so cached findings are
post-suppression; the baseline is applied after the cache by the
engine (the baseline file can change independently of the sources).
"""

from __future__ import annotations

import hashlib
import json
import pathlib

from tasksrunner.analysis.core import Finding

_PKG = pathlib.Path(__file__).resolve().parent


def ruleset_signature(rule_ids: tuple[str, ...]) -> str:
    h = hashlib.sha1()
    for src in sorted(_PKG.rglob("*.py")):
        h.update(src.name.encode())
        h.update(src.read_bytes())
    h.update("|".join(rule_ids).encode())
    return h.hexdigest()[:16]


class ResultCache:
    def __init__(self, path: pathlib.Path | None, signature: str):
        self.path = path
        self.signature = signature
        self.hits = 0
        self._dirty = False
        self._table: dict[str, dict] = {}
        if path is not None and path.is_file():
            try:
                self._table = json.loads(path.read_text()) or {}
            except ValueError:  # corrupt cache: rebuild silently
                self._table = {}

    def get(self, path: pathlib.Path) -> list[Finding] | None:
        entry = self._table.get(str(path))
        if entry is None or entry.get("sig") != self.signature:
            return None
        stat = path.stat()
        if entry.get("mtime") != stat.st_mtime_ns or \
                entry.get("size") != stat.st_size:
            return None
        self.hits += 1
        return [Finding.from_json(d) for d in entry.get("findings", [])]

    def put(self, path: pathlib.Path, findings: list[Finding]) -> None:
        stat = path.stat()
        self._table[str(path)] = {
            "sig": self.signature,
            "mtime": stat.st_mtime_ns,
            "size": stat.st_size,
            "findings": [f.to_json() for f in findings],
        }
        self._dirty = True

    def save(self) -> None:
        if self.path is None or not self._dirty:
            return
        # entries from older rulesets are dead weight — drop them
        live = {k: v for k, v in self._table.items()
                if v.get("sig") == self.signature}
        self.path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.path.with_suffix(".tmp")
        tmp.write_text(json.dumps(live))
        tmp.replace(self.path)
