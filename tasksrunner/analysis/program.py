"""ProgramGraph — the whole-program IR behind tasklint's
interprocedural rules.

PR 4's rules are deliberately per-file: each sees one AST. That shape
cannot catch the bugs that actually bite this codebase — a sync helper
that blocks three calls deep under an async hot path, a lock-order
cycle split across two modules, or an attribute mutated both on the
event loop and inside a writer thread. The ProgramGraph is built once
per lint run over every target file and gives the program-phase rules
(:mod:`.rules.transitive`, :mod:`.rules.lockgraph`,
:mod:`.rules.threadshared`, :mod:`.rules.routes`) four cross-cutting
views:

* **symbol table** — every module, class, and function (including
  nested defs), keyed ``relpath::Class.method``;
* **call graph** — conservative, name-based edges: plain names through
  the module's import table, ``self.``/``cls.`` method edges (base
  classes resolved within the package), ``Class.method`` and
  ``module.func`` attribute edges. Dispatch sites
  (``asyncio.to_thread``, ``run_in_executor``, ``executor.submit``,
  ``threading.Thread(target=...)``, ``threading.Timer(...)``) become
  *dispatch* edges — the callee runs on another thread;
* **execution contexts** — every function classified ``loop`` (async
  bodies and their transitive sync callees), ``thread`` (dispatch
  targets, ``# tasklint: off-loop`` marked helpers and the
  OFF_LOOP_ENTRYPOINTS allowlist, plus their transitive callees), or
  both. Propagation runs to a fixpoint over non-dispatch edges and
  stops at declared off-loop helpers;
* **lock graph** — which declared ``threading`` locks each function
  acquires (``with self._lock:`` / module-level locks), in what nesting
  order, which locks are held at each call site and each attribute
  write, and whether an ``await`` occurs while a lock is held.

Everything is resolved by name within the lint target — no imports are
executed. Unresolvable calls (dynamic dispatch, foreign libraries)
simply produce no edge: the graph under-approximates reachability, so
interprocedural findings are conservative (a reported chain is a real
syntactic path; absence of a finding is not a proof).
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Iterator

from tasksrunner.analysis.core import OFF_LOOP_RE, SUPPRESS_RE, import_table
from tasksrunner.analysis.rules.blocking import (
    BLOCKING_ATTRS,
    BLOCKING_CALLS,
    BLOCKING_NAMES,
    OFF_LOOP_ENTRYPOINTS,
)

_LOCK_FACTORIES = {"threading.Lock", "threading.RLock",
                   "threading.Condition", "threading.Semaphore",
                   "threading.BoundedSemaphore"}

#: dispatch call shapes: (canonical dotted target or attr name) → index
#: of the argument that names the function run on another thread
_THREAD_ARG = {"asyncio.to_thread": 0}
_THREAD_KW = {"threading.Thread": "target", "threading.Timer": "function"}
#: threading.Timer(interval, function) — positional form
_TIMER_POS = 1


@dataclasses.dataclass
class CallEdge:
    """One resolved call site: ``caller`` invokes ``callee``."""

    callee: str          # FunctionInfo key
    lineno: int
    dispatch: bool       # True = callee runs on another thread
    held_locks: tuple[str, ...]  # lock ids held at the call site


@dataclasses.dataclass
class LockSite:
    """One ``with <lock>:`` acquisition inside a function."""

    lock: str            # canonical lock id
    lineno: int
    awaits_inside: bool  # an await executes while this lock is held
    await_lineno: int | None
    inner: tuple[str, ...]  # locks acquired (directly) while this is held


@dataclasses.dataclass
class AttrWrite:
    """One ``self.<attr>`` store (plain, augmented, or subscript)."""

    attr: str
    lineno: int
    held_locks: frozenset


@dataclasses.dataclass
class BlockingOp:
    """A direct blocking call inside a function body."""

    lineno: int
    target: str          # "time.sleep", ".execute", "open", ...
    message: str


class FunctionInfo:
    __slots__ = ("key", "relpath", "name", "qualname", "lineno", "node",
                 "is_async", "off_loop", "cls_key", "edges", "lock_sites",
                 "writes", "blocking", "contexts", "context_origin")

    def __init__(self, key: str, relpath: str, qualname: str, node: ast.AST,
                 *, is_async: bool, off_loop: bool, cls_key: str | None):
        self.key = key
        self.relpath = relpath
        self.name = qualname.rsplit(".", 1)[-1]
        self.qualname = qualname
        self.lineno = node.lineno
        self.node = node
        self.is_async = is_async
        self.off_loop = off_loop
        self.cls_key = cls_key
        self.edges: list[CallEdge] = []
        self.lock_sites: list[LockSite] = []
        self.writes: list[AttrWrite] = []
        self.blocking: list[BlockingOp] = []
        #: "loop" / "thread" after propagation
        self.contexts: set[str] = set()
        #: context → human-readable provenance, for messages
        self.context_origin: dict[str, str] = {}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<fn {self.key} ctx={sorted(self.contexts)}>"


class ClassInfo:
    __slots__ = ("key", "name", "relpath", "node", "base_names", "methods",
                 "lock_attrs", "attr_types")

    def __init__(self, key: str, name: str, relpath: str, node: ast.ClassDef):
        self.key = key
        self.name = name
        self.relpath = relpath
        self.node = node
        self.base_names: list[str] = []
        self.methods: dict[str, FunctionInfo] = {}
        #: attribute names assigned a threading.Lock()/RLock()/... —
        #: identity of a lock is (class key, attr)
        self.lock_attrs: set[str] = set()
        #: attr → class key, from ``self.x = SomeClass(...)`` and
        #: annotations; lets ``self.x.m()`` resolve to SomeClass.m
        self.attr_types: dict[str, str] = {}


class ModuleInfo:
    __slots__ = ("relpath", "modname", "tree", "source", "lines", "imports",
                 "functions", "classes", "module_locks", "global_types",
                 "suppress_line", "suppress_file")

    def __init__(self, relpath: str, modname: str, source: str,
                 tree: ast.Module):
        self.relpath = relpath
        self.modname = modname
        self.tree = tree
        self.source = source
        self.lines = source.splitlines()
        self.imports = import_table(tree)
        self.functions: dict[str, FunctionInfo] = {}   # module-level defs
        self.classes: dict[str, ClassInfo] = {}
        self.module_locks: set[str] = set()
        #: module-global name → class key, from ``X = SomeClass(...)``
        #: and ``X: SomeClass | None = None`` annotations
        self.global_types: dict[str, str] = {}
        self.suppress_line: dict[int, set[str]] = {}
        self.suppress_file: set[str] = set()

    def marked_off_loop(self, node: ast.AST) -> bool:
        first = min(getattr(node, "lineno", 1),
                    *[d.lineno for d in getattr(node, "decorator_list", [])]
                    or [getattr(node, "lineno", 1)])
        for lineno in range(first, getattr(node, "lineno", first) + 1):
            if 0 < lineno <= len(self.lines) and \
                    OFF_LOOP_RE.search(self.lines[lineno - 1]):
                return True
        return False


def _modname(relpath: str) -> str:
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else \
        relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or relpath


class ProgramGraph:
    """The whole-program view. Build with :meth:`build`; rules query
    ``functions`` / ``classes`` / ``modules`` and the helpers below."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}       # relpath → module
        self.by_modname: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}   # key → fn
        self.classes: dict[str, ClassInfo] = {}        # key → class
        #: class name → class keys (for base-class resolution by name)
        self._class_by_name: dict[str, list[str]] = {}
        self.parse_errors: list[tuple[str, str]] = []

    # -- construction -----------------------------------------------------

    @classmethod
    def build(cls, files: list[tuple[pathlib.Path, str]]) -> "ProgramGraph":
        """``files`` is (absolute path, repo-relative posix path)."""
        graph = cls()
        for path, relpath in files:
            try:
                source = path.read_text()
                tree = ast.parse(source, filename=str(path))
            except (OSError, UnicodeDecodeError, SyntaxError) as exc:
                graph.parse_errors.append((relpath, str(exc)))
                continue
            graph._index_module(relpath, source, tree)
        for mod in graph.modules.values():
            graph._infer_types(mod)
        for mod in graph.modules.values():
            graph._scan_module(mod)
        graph._propagate_contexts()
        return graph

    def _infer_types(self, mod: ModuleInfo) -> None:
        """Nominal typing, one level deep: a name (module global, class
        attribute, or — handled in the body scan — function local) bound
        to ``SomeClass(...)`` or annotated with an in-package class gets
        that class, so method calls through it resolve. Runs after every
        module is indexed, since annotations cross module boundaries."""
        for node in mod.tree.body:
            name, cinfo = None, None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                cinfo = self._class_of_call(mod, node.value)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                name = node.target.id
                cinfo = self._annotation_class(mod, node.annotation) or \
                    self._class_of_call(mod, node.value)
            if name and cinfo is not None:
                mod.global_types.setdefault(name, cinfo.key)
        for cls in mod.classes.values():
            for node in ast.walk(cls.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    attr = _self_attr(node.targets[0])
                    hit = self._class_of_call(mod, node.value)
                elif isinstance(node, ast.AnnAssign):
                    attr = (node.target.id
                            if isinstance(node.target, ast.Name)
                            else _self_attr(node.target))
                    hit = self._annotation_class(mod, node.annotation) or \
                        self._class_of_call(mod, node.value)
                else:
                    continue
                if attr and hit is not None:
                    cls.attr_types.setdefault(attr, hit.key)

    def _class_of_call(self, mod: ModuleInfo,
                       value: ast.AST | None) -> ClassInfo | None:
        """``SomeClass(...)`` → the in-package class it constructs."""
        if not isinstance(value, ast.Call):
            return None
        if isinstance(value.func, ast.Name):
            return self._class_of_name(mod, value.func.id)
        fq = _resolve_dotted(mod.imports, value.func)
        return self._class_fq(fq) if fq else None

    def _class_fq(self, fq: str) -> ClassInfo | None:
        parts = fq.split(".")
        if len(parts) < 2:
            return None
        owner = self.by_modname.get(".".join(parts[:-1]))
        return owner.classes.get(parts[-1]) if owner is not None else None

    def _annotation_class(self, mod: ModuleInfo,
                          node: ast.AST | None) -> ClassInfo | None:
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
            return self._annotation_class(mod, node.left) or \
                self._annotation_class(mod, node.right)
        if isinstance(node, ast.Subscript):  # Optional[X] / list[X]: inner
            return self._annotation_class(mod, node.slice)
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.isidentifier():
            return self._class_of_name(mod, node.value)
        if isinstance(node, ast.Name):
            return self._class_of_name(mod, node.id)
        if isinstance(node, ast.Attribute):
            fq = _resolve_dotted(mod.imports, node)
            return self._class_fq(fq) if fq else None
        return None

    def _index_module(self, relpath: str, source: str,
                      tree: ast.Module) -> None:
        mod = ModuleInfo(relpath, _modname(relpath), source, tree)
        self.modules[relpath] = mod
        self.by_modname[mod.modname] = mod
        for lineno, line in enumerate(source.splitlines(), start=1):
            for match in SUPPRESS_RE.finditer(line):
                scope, raw = match.group(1), match.group(2)
                ids = {r.strip() for r in raw.split(",") if r.strip()}
                if scope == "disable-file":
                    mod.suppress_file.update(ids)
                else:
                    mod.suppress_line.setdefault(lineno, set()).update(ids)
        allow = OFF_LOOP_ENTRYPOINTS.get(relpath, frozenset())

        def index_fn(node, qualname: str, cls: ClassInfo | None) -> None:
            key = f"{relpath}::{qualname}"
            off = (node.name in allow and cls is None) or \
                (node.name in allow and cls is not None) or \
                mod.marked_off_loop(node)
            fn = FunctionInfo(key, relpath, qualname, node,
                              is_async=isinstance(node, ast.AsyncFunctionDef),
                              off_loop=off,
                              cls_key=cls.key if cls is not None else None)
            self.functions[key] = fn
            if cls is not None and "." not in qualname.removeprefix(
                    cls.name + "."):
                cls.methods[node.name] = fn
            elif cls is None and "." not in qualname:
                mod.functions[node.name] = fn
            walk_body(node, qualname, cls)

        def walk_body(parent, prefix: str, cls: ClassInfo | None) -> None:
            for child in ast.iter_child_nodes(parent):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    index_fn(child, f"{prefix}.{child.name}" if prefix
                             else child.name, cls)
                elif isinstance(child, ast.ClassDef):
                    ckey = f"{relpath}::{child.name}"
                    cinfo = ClassInfo(ckey, child.name, relpath, child)
                    self.classes[ckey] = cinfo
                    self._class_by_name.setdefault(child.name, []).append(ckey)
                    if not prefix:
                        mod.classes[child.name] = cinfo
                    for base in child.bases:
                        if isinstance(base, ast.Name):
                            cinfo.base_names.append(base.id)
                        elif isinstance(base, ast.Attribute):
                            cinfo.base_names.append(base.attr)
                    for item in child.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            index_fn(item, f"{child.name}.{item.name}", cinfo)
                        elif isinstance(item, ast.ClassDef):
                            walk_body(child, child.name, None)
                            break
                else:
                    walk_body(child, prefix, cls)

        walk_body(tree, "", None)
        # module-level locks: X = threading.Lock()
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                target = _resolve_dotted(mod.imports, node.value.func)
                if target in _LOCK_FACTORIES:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            mod.module_locks.add(tgt.id)
        # class lock attributes: self.x = threading.Lock() anywhere in class
        for cinfo in mod.classes.values():
            for node in ast.walk(cinfo.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    target = _resolve_dotted(mod.imports, node.value.func)
                    if target in _LOCK_FACTORIES:
                        for tgt in node.targets:
                            attr = _self_attr(tgt)
                            if attr:
                                cinfo.lock_attrs.add(attr)

    # -- symbol resolution -------------------------------------------------

    def _resolve_fq(self, fq: str) -> FunctionInfo | None:
        """"tasksrunner.state.sqlite.SqliteStateStore.close" → fn, by
        longest-module-prefix match, then class-method or module-fn."""
        parts = fq.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = self.by_modname.get(".".join(parts[:cut]))
            if mod is None:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                return mod.functions.get(rest[0])
            if len(rest) == 2:
                cinfo = mod.classes.get(rest[0])
                if cinfo is not None:
                    return self._method(cinfo, rest[1])
            return None
        return None

    def _method(self, cinfo: ClassInfo, name: str,
                _seen: frozenset = frozenset()) -> FunctionInfo | None:
        """Method lookup walking base classes by name (package-only)."""
        if cinfo.key in _seen:
            return None
        fn = cinfo.methods.get(name)
        if fn is not None:
            return fn
        for base_name in cinfo.base_names:
            for bkey in self._class_by_name.get(base_name, ()):
                found = self._method(self.classes[bkey], name,
                                     _seen | {cinfo.key})
                if found is not None:
                    return found
        return None

    def _class_of_name(self, mod: ModuleInfo, name: str) -> ClassInfo | None:
        cinfo = mod.classes.get(name)
        if cinfo is not None:
            return cinfo
        fq = mod.imports.get(name)
        if fq is None:
            return None
        parts = fq.split(".")
        if len(parts) < 2:
            return None
        owner = self.by_modname.get(".".join(parts[:-1]))
        return owner.classes.get(parts[-1]) if owner is not None else None

    def _attr_type(self, cinfo: ClassInfo, attr: str,
                   _seen: frozenset = frozenset()) -> str | None:
        if cinfo.key in _seen:
            return None
        hit = cinfo.attr_types.get(attr)
        if hit is not None:
            return hit
        for base_name in cinfo.base_names:
            for bkey in self._class_by_name.get(base_name, ()):
                hit = self._attr_type(self.classes[bkey], attr,
                                      _seen | {cinfo.key})
                if hit is not None:
                    return hit
        return None

    def _resolve_callee(self, mod: ModuleInfo, fn: FunctionInfo,
                        func_expr: ast.AST, local_defs: dict[str, str],
                        local_types: dict[str, str]) -> FunctionInfo | None:
        if isinstance(func_expr, ast.Name):
            nested = local_defs.get(func_expr.id)
            if nested is not None:
                return self.functions.get(nested)
            local = mod.functions.get(func_expr.id)
            if local is not None:
                return local
            fq = mod.imports.get(func_expr.id)
            return self._resolve_fq(fq) if fq else None
        if isinstance(func_expr, ast.Attribute):
            value, attr = func_expr.value, func_expr.attr
            if isinstance(value, ast.Name):
                if value.id in ("self", "cls") and fn.cls_key is not None:
                    return self._method(self.classes[fn.cls_key], attr)
                cinfo = self._class_of_name(mod, value.id)
                if cinfo is not None:
                    return self._method(cinfo, attr)
                # instance variables: local first, then module global
                ckey = local_types.get(value.id) or \
                    mod.global_types.get(value.id)
                if ckey is not None:
                    return self._method(self.classes[ckey], attr)
            inner = _self_attr(value)  # self.x.m() via inferred attr type
            if inner is not None and fn.cls_key is not None:
                ckey = self._attr_type(self.classes[fn.cls_key], inner)
                if ckey is not None:
                    return self._method(self.classes[ckey], attr)
            fq = _resolve_dotted(mod.imports, func_expr)
            return self._resolve_fq(fq) if fq else None
        return None

    # -- body scan: edges, locks, writes, blocking ------------------------

    def _scan_module(self, mod: ModuleInfo) -> None:
        for fn in self.functions.values():
            if fn.relpath == mod.relpath:
                self._scan_function(mod, fn)

    def _lock_id(self, mod: ModuleInfo, fn: FunctionInfo,
                 expr: ast.AST) -> str | None:
        attr = _self_attr(expr)
        if attr is not None and fn.cls_key is not None:
            cinfo = self.classes[fn.cls_key]
            if attr in self._all_lock_attrs(cinfo):
                return f"{cinfo.key}.{attr}"
            return None
        if isinstance(expr, ast.Name) and expr.id in mod.module_locks:
            return f"{mod.relpath}::{expr.id}"
        return None

    def _all_lock_attrs(self, cinfo: ClassInfo,
                        _seen: frozenset = frozenset()) -> set[str]:
        if cinfo.key in _seen:
            return set()
        attrs = set(cinfo.lock_attrs)
        for base_name in cinfo.base_names:
            for bkey in self._class_by_name.get(base_name, ()):
                attrs |= self._all_lock_attrs(self.classes[bkey],
                                              _seen | {cinfo.key})
        return attrs

    def _scan_function(self, mod: ModuleInfo, fn: FunctionInfo) -> None:
        #: nested ``def``s visible to calls inside this function
        local_defs = {
            child.name: f"{fn.relpath}::{fn.qualname}.{child.name}"
            for child in ast.walk(fn.node)
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
            and child is not fn.node}
        #: function-local ``x = SomeClass(...)`` so ``x.m()`` resolves
        local_types: dict[str, str] = {}
        for child in ast.walk(fn.node):
            if isinstance(child, ast.Assign) and len(child.targets) == 1 \
                    and isinstance(child.targets[0], ast.Name):
                hit = self._class_of_call(mod, child.value)
                if hit is not None:
                    local_types.setdefault(child.targets[0].id, hit.key)
        open_sites: list[LockSite] = []  # stack of held locks

        def visit(node: ast.AST, awaited: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn.node:
                return  # nested defs are their own FunctionInfo
            if isinstance(node, ast.Lambda):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired: list[LockSite] = []
                for item in node.items:
                    lock = self._lock_id(mod, fn, item.context_expr)
                    if lock is not None:
                        site = LockSite(lock=lock, lineno=node.lineno,
                                        awaits_inside=False,
                                        await_lineno=None, inner=())
                        for outer in open_sites:
                            if outer.lock != lock:
                                outer.inner = outer.inner + (lock,)
                        open_sites.append(site)
                        acquired.append(site)
                        fn.lock_sites.append(site)
                for child in ast.iter_child_nodes(node):
                    visit(child, awaited)
                for site in acquired:
                    open_sites.remove(site)
                return
            if isinstance(node, (ast.Await, ast.AsyncFor)):
                for site in open_sites:
                    if not site.awaits_inside:
                        site.awaits_inside = True
                        site.await_lineno = node.lineno
                for child in ast.iter_child_nodes(node):
                    visit(child, isinstance(node, ast.Await))
                return
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                held = frozenset(s.lock for s in open_sites)
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is None and isinstance(tgt, ast.Subscript):
                        attr = _self_attr(tgt.value)
                    if attr is not None:
                        fn.writes.append(AttrWrite(attr=attr,
                                                   lineno=node.lineno,
                                                   held_locks=held))
            if isinstance(node, ast.Call):
                self._scan_call(mod, fn, node, local_defs, local_types,
                                tuple(s.lock for s in open_sites), awaited)
            for child in ast.iter_child_nodes(node):
                visit(child, False)

        for child in ast.iter_child_nodes(fn.node):
            visit(child, False)

    def _scan_call(self, mod: ModuleInfo, fn: FunctionInfo, call: ast.Call,
                   local_defs: dict[str, str], local_types: dict[str, str],
                   held: tuple[str, ...], awaited: bool) -> None:
        target = _resolve_dotted(mod.imports, call.func)
        # dispatch sites: the *argument* function runs on a thread
        dispatched: list[ast.AST] = []
        if target in _THREAD_ARG and len(call.args) > _THREAD_ARG[target]:
            dispatched.append(call.args[_THREAD_ARG[target]])
        if target in _THREAD_KW:
            dispatched.extend(kw.value for kw in call.keywords
                              if kw.arg == _THREAD_KW[target])
            if target == "threading.Timer" and len(call.args) > _TIMER_POS:
                dispatched.append(call.args[_TIMER_POS])
        attr_name = call.func.attr if isinstance(call.func, ast.Attribute) \
            else ""
        if attr_name == "submit" and call.args:
            dispatched.append(call.args[0])
        elif attr_name == "run_in_executor" and len(call.args) >= 2:
            dispatched.append(call.args[1])
        for cand in dispatched:
            callee = self._resolve_callee(mod, fn, cand, local_defs,
                                         local_types)
            if callee is not None:
                fn.edges.append(CallEdge(callee=callee.key,
                                         lineno=call.lineno, dispatch=True,
                                         held_locks=held))
                callee.contexts.add("thread")
                callee.context_origin.setdefault(
                    "thread", f"dispatched at {fn.relpath}:{call.lineno}")
        if dispatched:
            return
        # direct blocking leaf?
        if not awaited:
            if target in BLOCKING_CALLS:
                fn.blocking.append(BlockingOp(
                    lineno=call.lineno, target=target,
                    message=BLOCKING_CALLS[target]))
            elif isinstance(call.func, ast.Name) and \
                    call.func.id in BLOCKING_NAMES:
                fn.blocking.append(BlockingOp(
                    lineno=call.lineno, target=call.func.id,
                    message=BLOCKING_NAMES[call.func.id]))
            elif attr_name in BLOCKING_ATTRS:
                fn.blocking.append(BlockingOp(
                    lineno=call.lineno, target=f".{attr_name}",
                    message=BLOCKING_ATTRS[attr_name]))
        # plain call edge
        callee = self._resolve_callee(mod, fn, call.func, local_defs,
                                     local_types)
        if callee is not None and callee.key != fn.key:
            fn.edges.append(CallEdge(callee=callee.key, lineno=call.lineno,
                                     dispatch=False, held_locks=held))

    # -- context propagation ----------------------------------------------

    def _propagate_contexts(self) -> None:
        work: list[FunctionInfo] = []
        for fn in self.functions.values():
            if fn.is_async:
                fn.contexts.add("loop")
                fn.context_origin.setdefault("loop", "async def")
            if fn.off_loop:
                fn.contexts.add("thread")
                fn.context_origin.setdefault(
                    "thread", "declared off-loop")
            if fn.contexts:
                work.append(fn)
        while work:
            fn = work.pop()
            for edge in fn.edges:
                if edge.dispatch:
                    continue
                callee = self.functions.get(edge.callee)
                if callee is None:
                    continue
                for ctx in fn.contexts:
                    if ctx in callee.contexts:
                        continue
                    if callee.is_async:
                        continue  # async callees are their own loop entry
                    if ctx == "loop" and callee.off_loop:
                        continue  # declared thread-only: trust the marker
                    callee.contexts.add(ctx)
                    callee.context_origin.setdefault(
                        ctx, f"called from {fn.qualname} "
                             f"({fn.relpath}:{edge.lineno})")
                    work.append(callee)

    # -- queries -----------------------------------------------------------

    def iter_functions(self) -> Iterator[FunctionInfo]:
        return iter(self.functions.values())

    def suppressed(self, relpath: str, lineno: int, rule: str) -> bool:
        mod = self.modules.get(relpath)
        if mod is None:
            return False
        return rule in mod.suppress_file or \
            rule in mod.suppress_line.get(lineno, ())

    def frame(self, fn: FunctionInfo, lineno: int) -> str:
        return f"{fn.relpath}:{lineno}"


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _resolve_dotted(imports: dict[str, str], func: ast.AST) -> str | None:
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    dotted = ".".join(reversed(parts))
    head, _, rest = dotted.partition(".")
    base = imports.get(head, head)
    return f"{base}.{rest}" if rest else base
