"""Dataflow phase — per-function CFGs plus interprocedural summaries.

PR 4 gave tasklint syntax (one AST at a time), PR 8 gave it structure
(the whole-program call/lock graph). Neither can answer *flow*
questions: does the value read from the secrets store ever reach a log
call, is this connection closed on the early-return path, which
exception types can escape a route handler. This module supplies the
missing layer:

* :func:`build_cfg` — a per-function control-flow graph over the
  existing AST. Basic blocks hold *events* (simple statements and
  :class:`Bind` markers for loop/with/except bindings); compound
  statements contribute edges, not events. ``try``/``finally`` is
  modelled by pre-creating the handler and finally entry blocks so
  ``return``/``raise``/``break`` inside the body route *through* the
  finally chain, and every function exit is recorded with its kind
  (explicit ``return``, uncaught ``raise``, or falling off the end).

* :func:`run_forward` — the worklist engine: forward abstract
  interpretation to a fixpoint, parameterised by the rule's transfer
  function and join. All shipped abstractions are may-analyses over
  finite label sets, so termination is by lattice height.

* :class:`TaintEngine` — gen/kill taint over the CFG with
  **interprocedural summaries**: one pass per function computes which
  labels (``SECRET`` origins and ``PARAM i`` placeholders) reach each
  sink and the return value; summaries propagate along the
  ProgramGraph call graph to fixpoint, so a token that travels two
  helper calls deep before hitting a logger is still caught, and the
  finding's chain names every hop.

* exception **escape sets** — per-function may-raise summaries
  (explicit raises plus callee escapes, filtered through enclosing
  ``except`` clauses with package + builtin subclass knowledge),
  propagated to fixpoint for the exception-flow rule.

The phase is conservative the same way the program phase is: an edge
the call graph cannot resolve produces no propagation, so a reported
source→sink chain is a real syntactic path, while a silent function is
not a proof. Results are cached under the program-phase tree digest
(see :mod:`.cache`), so warm runs cost one digest pass.
"""

from __future__ import annotations

import ast
from typing import Callable, Iterator

from tasksrunner.analysis.program import (
    FunctionInfo,
    ModuleInfo,
    ProgramGraph,
    _resolve_dotted,
)

# --------------------------------------------------------------------------
# CFG
# --------------------------------------------------------------------------


class Bind:
    """A binding event that is not an ``ast.Assign``: ``for x in it``,
    ``with expr as x``, ``except E as x``. ``target`` may be None
    (``with self._lock:``); ``value`` may be None (except-binding)."""

    __slots__ = ("target", "value", "kind", "lineno")

    def __init__(self, target: ast.AST | None, value: ast.AST | None,
                 kind: str, lineno: int):
        self.target = target
        self.value = value
        self.kind = kind  # "for" | "with" | "except"
        self.lineno = lineno


class Block:
    __slots__ = ("idx", "events", "succs", "preds", "in_finally")

    def __init__(self, idx: int):
        self.idx = idx
        #: simple statements and Bind markers, in execution order
        self.events: list = []
        self.succs: list[int] = []
        self.preds: list[int] = []
        #: True when the block belongs to a ``finally`` suite
        self.in_finally = False


class Exit:
    """One way out of the function."""

    __slots__ = ("block", "kind", "lineno", "node")

    def __init__(self, block: int, kind: str, lineno: int,
                 node: ast.AST | None):
        self.block = block
        self.kind = kind  # "return" | "raise" | "fall"
        self.lineno = lineno
        self.node = node  # the Return/Raise statement, None for "fall"


class CFG:
    __slots__ = ("blocks", "exits")

    def __init__(self) -> None:
        self.blocks: list[Block] = []
        self.exits: list[Exit] = []

    @property
    def entry(self) -> Block:
        return self.blocks[0]


class NestedDef:
    """Event marker for a nested function/class definition: no control
    flow of its own, but its body closes over outer names."""

    __slots__ = ("node", "lineno")

    def __init__(self, node: ast.stmt):
        self.node = node
        self.lineno = node.lineno


_CATCH_ALL = frozenset({"", "Exception", "BaseException"})


def _handler_names(handler: ast.ExceptHandler) -> list[str]:
    """Leaf names of the caught types; [""] for a bare ``except:``."""
    if handler.type is None:
        return [""]
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    out = []
    for t in types:
        if isinstance(t, ast.Attribute):
            out.append(t.attr)
        elif isinstance(t, ast.Name):
            out.append(t.id)
    return out


class _TryFrame:
    __slots__ = ("handlers", "finally_entry", "catch_all", "pending")

    def __init__(self) -> None:
        #: (handler node, entry block) pairs
        self.handlers: list[tuple[ast.ExceptHandler, Block]] = []
        self.finally_entry: Block | None = None
        self.catch_all = False
        #: exit kinds routed through this finally: (kind, lineno, node)
        self.pending: list[tuple[str, int, ast.AST | None]] = []


class _CFGBuilder:
    def __init__(self, fn_node: ast.AST):
        self.cfg = CFG()
        self.cur = self._new()
        #: (header block, after block) per enclosing loop
        self.loops: list[tuple[Block, Block]] = []
        self.tries: list[_TryFrame] = []
        self.fn_node = fn_node

    def _new(self) -> Block:
        b = Block(len(self.cfg.blocks))
        self.cfg.blocks.append(b)
        return b

    def _edge(self, a: Block, b: Block) -> None:
        if b.idx not in a.succs:
            a.succs.append(b.idx)
            b.preds.append(a.idx)

    def _reachable(self, b: Block) -> bool:
        return b.idx == 0 or bool(b.preds)

    # -- exits --------------------------------------------------------------

    def _route_exit(self, kind: str, lineno: int, node: ast.AST | None,
                    frames: list[_TryFrame] | None = None) -> None:
        """Route a return/raise/break target through enclosing
        ``finally`` suites. ``frames`` defaults to the live try stack;
        recursive calls pass the not-yet-unwound tail."""
        if frames is None:
            frames = self.tries
        for i in range(len(frames) - 1, -1, -1):
            frame = frames[i]
            if frame.finally_entry is not None:
                self._edge(self.cur, frame.finally_entry)
                frame.pending.append((kind, lineno, node))
                return
        self.cfg.exits.append(Exit(self.cur.idx, kind, lineno, node))

    def _route_raise(self, lineno: int, node: ast.AST | None) -> None:
        """A ``raise``: conservatively reaches the handlers of each
        enclosing try (stopping at a catch-all), else exits raising."""
        for i in range(len(self.tries) - 1, -1, -1):
            frame = self.tries[i]
            for _handler, entry in frame.handlers:
                self._edge(self.cur, entry)
            if frame.catch_all:
                self.cur = self._new()  # nothing runs after a caught raise
                return
        self._route_exit("raise", lineno, node)
        self.cur = self._new()

    # -- statements ---------------------------------------------------------

    def build(self, body: list[ast.stmt]) -> CFG:
        self._stmts(body)
        if self._reachable(self.cur):
            last = body[-1].lineno if body else 1
            self.cfg.exits.append(Exit(self.cur.idx, "fall", last, None))
        return self.cfg

    def _stmts(self, stmts: list[ast.stmt]) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, ast.If):
            before = self.cur
            after = self._new()
            self.cur = self._new()
            self._edge(before, self.cur)
            self._stmts(node.body)
            if self._reachable(self.cur):
                self._edge(self.cur, after)
            self.cur = self._new()
            self._edge(before, self.cur)
            self._stmts(node.orelse)
            if self._reachable(self.cur):
                self._edge(self.cur, after)
            self.cur = after
        elif isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            header = self._new()
            self._edge(self.cur, header)
            after = self._new()
            self._edge(header, after)
            if isinstance(node, (ast.For, ast.AsyncFor)):
                header.events.append(
                    Bind(node.target, node.iter, "for", node.lineno))
            body = self._new()
            self._edge(header, body)
            self.cur = body
            self.loops.append((header, after))
            self._stmts(node.body)
            self.loops.pop()
            if self._reachable(self.cur):
                self._edge(self.cur, header)
            self.cur = self._new()
            self._edge(header, self.cur)
            self._stmts(node.orelse)
            if self._reachable(self.cur):
                self._edge(self.cur, after)
            self.cur = after
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self.cur.events.append(Bind(item.optional_vars,
                                            item.context_expr, "with",
                                            node.lineno))
            self._stmts(node.body)
        elif isinstance(node, ast.Try):
            self._try(node)
        elif isinstance(node, ast.Return):
            self.cur.events.append(node)
            self._route_exit("return", node.lineno, node)
            self.cur = self._new()
        elif isinstance(node, ast.Raise):
            self.cur.events.append(node)
            self._route_raise(node.lineno, node)
        elif isinstance(node, ast.Break):
            if self.loops:
                self._edge(self.cur, self.loops[-1][1])
            self.cur = self._new()
        elif isinstance(node, ast.Continue):
            if self.loops:
                self._edge(self.cur, self.loops[-1][0])
            self.cur = self._new()
        elif isinstance(node, ast.Match):
            before = self.cur
            after = self._new()
            self._edge(before, after)  # no case may match
            for case in node.cases:
                self.cur = self._new()
                self._edge(before, self.cur)
                self._stmts(case.body)
                if self._reachable(self.cur):
                    self._edge(self.cur, after)
            self.cur = after
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            # nested defs have their own FunctionInfo and contribute no
            # flow here, but closures *capture* outer names — leave a
            # marker so rules can model the capture (e.g. a closure
            # taking ownership of a resource)
            self.cur.events.append(NestedDef(node))
        else:
            self.cur.events.append(node)

    def _try(self, node: ast.Try) -> None:
        frame = _TryFrame()
        after = self._new()
        for handler in node.handlers:
            entry = self._new()
            frame.handlers.append((handler, entry))
            if set(_handler_names(handler)) & _CATCH_ALL:
                frame.catch_all = True
        if node.finalbody:
            frame.finally_entry = self._new()
        body_entry = self._new()
        self._edge(self.cur, body_entry)
        # an exception may fire before any body statement ran: seed each
        # handler with the pre-body state too
        for _handler, entry in frame.handlers:
            self._edge(self.cur, entry)
        self.cur = body_entry
        self.tries.append(frame)
        self._stmts(node.body)
        body_end = self.cur
        # exception after the last body statement
        for _handler, entry in frame.handlers:
            if self._reachable(body_end):
                self._edge(body_end, entry)
        if self._reachable(self.cur):
            self._stmts(node.orelse)
        normal_end = self.cur
        self.tries.pop()

        handler_ends: list[Block] = []
        for handler, entry in frame.handlers:
            self.cur = entry
            if handler.name:
                entry.events.append(Bind(
                    ast.Name(id=handler.name, ctx=ast.Store(),
                             lineno=handler.lineno, col_offset=0),
                    None, "except", handler.lineno))
            self._stmts(handler.body)
            if self._reachable(self.cur):
                handler_ends.append(self.cur)

        if node.finalbody:
            fin = frame.finally_entry
            assert fin is not None
            if self._reachable(normal_end):
                self._edge(normal_end, fin)
            for end in handler_ends:
                self._edge(end, fin)
            self.cur = fin
            mark_from = len(self.cfg.blocks)
            self._stmts(node.finalbody)
            fin.in_finally = True
            for b in self.cfg.blocks[mark_from:]:
                b.in_finally = True
            fin_end = self.cur
            if self._reachable(fin_end) or fin_end is fin:
                self._edge(fin_end, after)
                # re-dispatch the exits that were parked on this finally
                for kind, lineno, enode in frame.pending:
                    self.cur = fin_end
                    self._route_exit(kind, lineno, enode)
            self.cur = after
        else:
            if self._reachable(normal_end):
                self._edge(normal_end, after)
            for end in handler_ends:
                self._edge(end, after)
            self.cur = after


def build_cfg(fn_node: ast.AST) -> CFG:
    """CFG over one function body (the def's own statements only —
    nested defs contribute no events)."""
    return _CFGBuilder(fn_node).build(list(fn_node.body))


# --------------------------------------------------------------------------
# worklist engine
# --------------------------------------------------------------------------


def run_forward(cfg: CFG, init, transfer: Callable, join: Callable,
                ) -> dict[int, object]:
    """Forward may-analysis to fixpoint. ``transfer(block, state) ->
    state`` must be monotone; ``join(a, b)`` the lattice union.
    Returns the state at *entry* of each reachable block index."""
    states: dict[int, object] = {0: init}
    work = [0]
    out_memo: dict[int, object] = {}
    while work:
        idx = work.pop()
        block = cfg.blocks[idx]
        out = transfer(block, states[idx])
        if idx in out_memo and out_memo[idx] == out:
            continue
        out_memo[idx] = out
        for succ in block.succs:
            if succ not in states:
                states[succ] = out
                work.append(succ)
            else:
                merged = join(states[succ], out)
                if merged != states[succ]:
                    states[succ] = merged
                    work.append(succ)
    return states


# --------------------------------------------------------------------------
# analysis facade handed to DataflowRule.check
# --------------------------------------------------------------------------


class DataflowAnalysis:
    """What a dataflow rule sees: the ProgramGraph plus memoised CFGs
    and the shared interprocedural engines."""

    def __init__(self, graph: ProgramGraph):
        self.graph = graph
        self._cfgs: dict[str, CFG] = {}
        self._taint: TaintEngine | None = None
        self._escapes: dict[str, frozenset] | None = None

    def cfg(self, fn: FunctionInfo) -> CFG:
        hit = self._cfgs.get(fn.key)
        if hit is None:
            hit = self._cfgs[fn.key] = build_cfg(fn.node)
        return hit

    def module(self, fn: FunctionInfo) -> ModuleInfo:
        return self.graph.modules[fn.relpath]

    def iter_functions(self) -> Iterator[FunctionInfo]:
        return self.graph.iter_functions()

    def resolve_dotted(self, fn: FunctionInfo, expr: ast.AST) -> str | None:
        """Canonical dotted target of a call through the module's
        import table ("asyncio.shield", "logging.getLogger")."""
        return _resolve_dotted(self.module(fn).imports, expr)

    @property
    def taint(self) -> "TaintEngine":
        if self._taint is None:
            self._taint = TaintEngine(self)
            self._taint.solve()
        return self._taint

    @property
    def escapes(self) -> dict[str, dict[str, tuple]]:
        """function key → {exception name → (lineno, via-callee key or
        None)} for every type that may escape it (see
        :func:`solve_escapes`). The provenance pair reconstructs the
        finding's chain down to the leaf ``raise``."""
        if self._escapes is None:
            self._escapes = solve_escapes(self)
        return self._escapes

    def escape_chain(self, key: str, name: str) -> tuple[str, ...]:
        """``file:line`` frames from ``key``'s raise/call site down to
        the leaf raise of exception ``name``."""
        frames: list[str] = []
        seen: set[str] = set()
        while key and key not in seen:
            seen.add(key)
            site = self.escapes.get(key, {}).get(name)
            if site is None:
                break
            lineno, via = site
            fn = self.graph.functions.get(key)
            if fn is not None:
                frames.append(f"{fn.relpath}:{lineno}")
            if via is None:
                break
            key = via
        return tuple(frames)


# --------------------------------------------------------------------------
# taint engine
# --------------------------------------------------------------------------

#: taint labels: a SECRET origin carries its provenance, a PARAM is a
#: placeholder substituted at call sites during summary application
Label = tuple  # ("SECRET", relpath, lineno, desc) | ("PARAM", index)

#: builtins whose result reveals nothing about a secret argument
_STRIP_CALLS = frozenset({"len", "type", "id", "bool", "isinstance",
                          "hasattr", "callable"})


class SinkHit:
    """A tainted value reaching one sink inside one function."""

    __slots__ = ("lineno", "desc", "labels", "tail")

    def __init__(self, lineno: int, desc: str, labels: frozenset,
                 tail: tuple[str, ...] = ()):
        self.lineno = lineno
        self.desc = desc          # "logging call", "metric label", ...
        self.labels = labels      # which taint reached it
        self.tail = tail          # chain frames below this one (callee side)

    def __eq__(self, other) -> bool:
        return (self.lineno, self.desc, self.labels, self.tail) == \
            (other.lineno, other.desc, other.labels, other.tail)

    def __hash__(self) -> int:
        return hash((self.lineno, self.desc, self.labels, self.tail))


class TaintSpec:
    """The rule-supplied policy: what starts taint, what must not
    receive it, what cleanses it. Subclassed by the secret-taint rule;
    kept here so the engine is testable with toy specs."""

    def source(self, engine: "TaintEngine", fn: FunctionInfo,
               call: ast.Call) -> str | None:
        """Non-None description when the call's result is secret."""
        return None

    def source_expr(self, engine: "TaintEngine", fn: FunctionInfo,
                    expr: ast.AST) -> str | None:
        """Non-call source expressions (attribute reads etc.)."""
        return None

    def sink(self, engine: "TaintEngine", fn: FunctionInfo,
             call: ast.Call) -> str | None:
        """Non-None description when the call is a forbidden sink for
        secret-labelled arguments."""
        return None

    def sanitizer(self, engine: "TaintEngine", fn: FunctionInfo,
                  call: ast.Call) -> bool:
        """True when the call cleanses taint (redact/hash_token)."""
        return False


class TaintEngine:
    """Label-set taint over every function, to interprocedural
    fixpoint. One CFG pass per function per round; labels are
    ``SECRET`` origins (with provenance) plus ``PARAM i``
    placeholders, so a single pass yields both the local findings and
    the caller-facing summary."""

    def __init__(self, dfa: DataflowAnalysis, spec: TaintSpec | None = None):
        self.dfa = dfa
        self.spec = spec or TaintSpec()
        #: fn key → labels that may flow to the return value
        self.ret_labels: dict[str, frozenset] = {}
        #: fn key → sink hits observed inside (labels may be PARAMs)
        self.sink_hits: dict[str, tuple[SinkHit, ...]] = {}
        #: call-site resolution memo: (fn key, lineno) → callee keys
        self._callees: dict[tuple[str, int], list[str]] = {}

    # -- summary application ------------------------------------------------

    def _callee_keys(self, fn: FunctionInfo, call: ast.Call) -> list[str]:
        memo_key = (fn.key, call.lineno)
        hit = self._callees.get(memo_key)
        if hit is None:
            hit = [e.callee for e in fn.edges
                   if e.lineno == call.lineno and not e.dispatch]
            self._callees[memo_key] = hit
        return hit

    def _arg_labels(self, fn: FunctionInfo, call: ast.Call,
                    state: dict) -> list[frozenset]:
        out = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            out.append(self._expr_labels(fn, arg, state))
        return out

    def _substitute(self, labels: frozenset,
                    args: list[frozenset]) -> frozenset:
        """Replace PARAM placeholders in a callee summary with the
        labels of the actual arguments."""
        out = set()
        for label in labels:
            if label[0] == "PARAM":
                idx = label[1]
                if idx < len(args):
                    out |= args[idx]
            else:
                out.add(label)
        return frozenset(out)

    def _expr_labels(self, fn: FunctionInfo, expr: ast.AST,
                     state: dict) -> frozenset:
        """May-labels of one expression under ``state`` (name →
        labels). Calls are NOT descended into — ``_call_labels``
        decides what of its arguments' taint survives the call, which
        is what lets ``redact(token)`` and ``len(token)`` actually
        strip the label instead of re-leaking the inner name."""
        labels: set = set()
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Call):
                labels |= self._call_labels(fn, node, state)
                continue
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                labels |= state.get(node.id, frozenset())
            elif isinstance(node, (ast.Attribute, ast.Subscript)):
                desc = self.spec.source_expr(self, fn, node)
                if desc:
                    labels.add(("SECRET", fn.relpath, node.lineno, desc))
                    continue
            stack.extend(ast.iter_child_nodes(node))
        return frozenset(labels)

    def _call_labels(self, fn: FunctionInfo, call: ast.Call,
                     state: dict) -> frozenset:
        """Labels of a call's *result* (args evaluated via state)."""
        desc = self.spec.source(self, fn, call)
        if desc:
            return frozenset({("SECRET", fn.relpath, call.lineno, desc)})
        if self.spec.sanitizer(self, fn, call):
            return frozenset()
        name = call.func.id if isinstance(call.func, ast.Name) else None
        if name in _STRIP_CALLS:
            return frozenset()
        args = self._arg_labels(fn, call, state)
        merged: set = set()
        for a in args:
            merged |= a
        callees = self._callee_keys(fn, call)
        if callees:
            out: set = set()
            for key in callees:
                out |= self._substitute(
                    self.ret_labels.get(key, frozenset()), args)
            return frozenset(out)
        # unresolved call: assume the result carries its arguments
        return frozenset(merged)

    # -- per-function pass --------------------------------------------------

    def _transfer(self, fn: FunctionInfo, hits: list[SinkHit]):
        def transfer(block: Block, state_in: dict) -> dict:
            state = dict(state_in)
            for event in block.events:
                self._event(fn, event, state, hits)
            return state
        return transfer

    def _assign_names(self, target: ast.AST) -> list[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out = []
            for elt in target.elts:
                out.extend(self._assign_names(elt))
            return out
        if isinstance(target, ast.Starred):
            return self._assign_names(target.value)
        return []

    def _event(self, fn: FunctionInfo, event, state: dict,
               hits: list[SinkHit]) -> None:
        if isinstance(event, NestedDef):
            return  # the nested def is analysed as its own function
        if isinstance(event, Bind):
            if event.value is not None:
                self._scan_calls(fn, event.value, state, hits)
            if event.target is not None:
                labels = self._expr_labels(fn, event.value, state) \
                    if event.value is not None else frozenset()
                for name in self._assign_names(event.target):
                    state[name] = labels
            return
        if isinstance(event, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = event.value
            if value is None:
                return
            self._scan_calls(fn, value, state, hits)
            labels = self._expr_labels(fn, value, state)
            targets = event.targets if isinstance(event, ast.Assign) \
                else [event.target]
            for tgt in targets:
                if isinstance(event, ast.AugAssign) and \
                        isinstance(tgt, ast.Name):
                    state[tgt.id] = state.get(tgt.id, frozenset()) | labels
                    continue
                for name in self._assign_names(tgt):
                    state[name] = labels
            return
        if isinstance(event, ast.Return):
            if event.value is not None:
                self._scan_calls(fn, event.value, state, hits)
                labels = self._expr_labels(fn, event.value, state)
                if labels:
                    self.ret_labels[fn.key] = \
                        self.ret_labels.get(fn.key, frozenset()) | labels
            return
        # any other simple statement: walk it for sink / summary calls
        for node in ast.walk(event):
            if isinstance(node, ast.Call):
                self._check_call(fn, node, state, hits)

    def _scan_calls(self, fn: FunctionInfo, expr: ast.AST, state: dict,
                    hits: list[SinkHit]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._check_call(fn, node, state, hits)

    def _check_call(self, fn: FunctionInfo, call: ast.Call, state: dict,
                    hits: list[SinkHit]) -> None:
        """Sink check + interprocedural param→sink summaries for one
        call site."""
        desc = self.spec.sink(self, fn, call)
        args = self._arg_labels(fn, call, state)
        if desc:
            merged: set = set()
            for a in args:
                merged |= a
            if merged:
                hits.append(SinkHit(call.lineno, desc, frozenset(merged)))
            return
        if self.spec.sanitizer(self, fn, call):
            return
        for key in self._callee_keys(fn, call):
            for hit in self.sink_hits.get(key, ()):
                # only the parameter-dependent part of a callee hit is
                # the caller's problem — the callee's own SECRET labels
                # are reported once, in the callee
                params = frozenset(lb for lb in hit.labels
                                   if lb[0] == "PARAM")
                labels = self._substitute(params, args)
                if labels:
                    callee = self.dfa.graph.functions.get(key)
                    frame = f"{callee.relpath}:{hit.lineno}" if callee \
                        else f"?:{hit.lineno}"
                    hits.append(SinkHit(call.lineno, hit.desc, labels,
                                        tail=(frame,) + hit.tail))

    def _analyse(self, fn: FunctionInfo) -> tuple[frozenset, tuple]:
        cfg = self.dfa.cfg(fn)
        init: dict = {}
        posonly = getattr(fn.node.args, "posonlyargs", [])
        params = list(posonly) + list(fn.node.args.args)
        for i, arg in enumerate(params):
            if arg.arg in ("self", "cls") and i == 0:
                continue
            init[arg.arg] = frozenset({("PARAM", i)})
        hits: list[SinkHit] = []
        self.ret_labels.setdefault(fn.key, frozenset())
        before = self.ret_labels[fn.key]

        def join(a: dict, b: dict) -> dict:
            merged = dict(a)
            for name, labels in b.items():
                merged[name] = merged.get(name, frozenset()) | labels
            return merged

        run_forward(cfg, init, self._transfer(fn, hits), join)
        # dedupe, keep deterministic order
        seen: set = set()
        uniq: list[SinkHit] = []
        for hit in sorted(hits, key=lambda h: (h.lineno, h.desc)):
            marker = (hit.lineno, hit.desc, hit.labels, hit.tail)
            if marker not in seen:
                seen.add(marker)
                uniq.append(hit)
        return (self.ret_labels[fn.key] | before, tuple(uniq))

    # -- interprocedural fixpoint -------------------------------------------

    def solve(self, max_rounds: int = 8) -> None:
        """Iterate per-function passes until return/sink summaries are
        stable. The lattice is finite (labels ⊆ params ∪ sources), so
        this converges; ``max_rounds`` is a safety stop for the
        pathological mutual-recursion case."""
        fns = sorted(self.dfa.graph.functions.values(),
                     key=lambda f: (f.relpath, f.lineno))
        for _round in range(max_rounds):
            changed = False
            for fn in fns:
                ret, hits = self._analyse(fn)
                if ret != self.ret_labels.get(fn.key) or \
                        hits != self.sink_hits.get(fn.key, ()):
                    changed = True
                self.ret_labels[fn.key] = ret
                self.sink_hits[fn.key] = hits
            if not changed:
                break


# --------------------------------------------------------------------------
# exception escape sets
# --------------------------------------------------------------------------

#: builtin exception → parent, enough hierarchy for handler matching
_BUILTIN_PARENT = {
    "ConnectionError": "OSError",
    "ConnectionResetError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "BrokenPipeError": "ConnectionError",
    "FileNotFoundError": "OSError",
    "FileExistsError": "OSError",
    "PermissionError": "OSError",
    "TimeoutError": "OSError",
    "IOError": "OSError",
    "NotADirectoryError": "OSError",
    "IsADirectoryError": "OSError",
    "IndexError": "LookupError",
    "KeyError": "LookupError",
    "JSONDecodeError": "ValueError",
    "UnicodeDecodeError": "ValueError",
    "UnicodeEncodeError": "ValueError",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "RecursionError": "RuntimeError",
    "NotImplementedError": "RuntimeError",
}

#: these never subclass Exception — a catch-all except Exception
#: does not stop them
_NON_EXCEPTION = frozenset({"CancelledError", "SystemExit",
                            "KeyboardInterrupt", "GeneratorExit",
                            "BaseException"})


def exception_catches(graph: ProgramGraph, caught: str, raised: str) -> bool:
    """Does ``except <caught>:`` stop a propagating ``raised``?  Name
    based, walking the package class hierarchy and the builtin table."""
    if caught == "":  # bare except
        return True
    if caught == "BaseException":
        return True
    if caught == "Exception":
        return raised not in _NON_EXCEPTION
    seen: set[str] = set()
    frontier = [raised]
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        if name == caught:
            return True
        parent = _BUILTIN_PARENT.get(name)
        if parent:
            frontier.append(parent)
        for ckey in graph._class_by_name.get(name, ()):
            frontier.extend(graph.classes[ckey].base_names)
    return False


def _raise_name(node: ast.Raise) -> str | None:
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    if isinstance(exc, ast.Attribute):
        return exc.attr
    if isinstance(exc, ast.Name):
        return exc.id
    return None


def solve_escapes(dfa: DataflowAnalysis) -> dict[str, dict[str, tuple]]:
    """Per-function may-escape exception sets, to fixpoint.

    escape(fn) = { explicit raises } ∪ { escapes of resolved callees },
    each filtered through the ``except`` clauses lexically enclosing
    the raise/call site. Bare ``raise`` inside a handler re-raises the
    handler's caught names. Dispatch edges (thread targets) do not
    propagate — their exceptions surface elsewhere. Each escaping name
    maps to ``(lineno, via)``: the first site that introduced it
    (``via`` = callee key when it arrived through a call, None for a
    local raise)."""
    graph = dfa.graph
    # precompute, per function, the raise/call sites with their
    # enclosing handler-name stacks
    sites: dict[str, list[tuple[str, object, tuple]]] = {}
    for fn in graph.functions.values():
        callee_by_line: dict[int, list[str]] = {}
        for edge in fn.edges:
            if not edge.dispatch:
                callee_by_line.setdefault(edge.lineno, []).append(edge.callee)
        events: list[tuple[str, object, tuple]] = []

        def walk(node: ast.AST, guards: tuple, handler_of: tuple) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn.node:
                return
            if isinstance(node, ast.Try):
                body_guards = guards + (tuple(
                    name for h in node.handlers for name in _handler_names(h)),)
                for child in node.body + node.orelse:
                    walk(child, body_guards, handler_of)
                for handler in node.handlers:
                    names = tuple(_handler_names(handler))
                    for child in handler.body:
                        walk(child, guards, names)
                for child in node.finalbody:
                    walk(child, guards, handler_of)
                return
            if isinstance(node, ast.Raise):
                if node.exc is None:
                    for name in handler_of:
                        events.append(("raise", name or "Exception", guards,
                                       node.lineno))
                else:
                    name = _raise_name(node)
                    if name:
                        events.append(("raise", name, guards, node.lineno))
            if isinstance(node, ast.Call):
                for key in callee_by_line.get(node.lineno, ()):
                    events.append(("call", key, guards, node.lineno))
            if isinstance(node, ast.Assert):
                events.append(("raise", "AssertionError", guards,
                               node.lineno))
            for child in ast.iter_child_nodes(node):
                walk(child, guards, handler_of)

        for child in ast.iter_child_nodes(fn.node):
            walk(child, (), ())
        sites[fn.key] = events

    def filtered(name: str, guards: tuple) -> bool:
        """True when the exception survives every enclosing guard."""
        for names in guards:
            for caught in names:
                if exception_catches(graph, caught, name):
                    return False
        return True

    escapes: dict[str, dict[str, tuple]] = {key: {}
                                            for key in graph.functions}
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for key, events in sites.items():
            out = escapes[key]
            for kind, payload, guards, lineno in events:
                if kind == "raise":
                    names: list[tuple[str, str | None]] = [(payload, None)]
                else:
                    names = [(n, payload)
                             for n in sorted(escapes.get(payload, ()))]
                for name, via in names:
                    if name not in out and filtered(name, guards):
                        out[name] = (lineno, via)
                        changed = True
    return escapes
