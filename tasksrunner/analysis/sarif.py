"""SARIF 2.1.0 emitter — tasklint findings as CI-consumable results.

One run object, one ``tool.driver`` listing every rule that executed
(so viewers can show the rule docs), one result per finding. The
``chain`` of interprocedural findings becomes a ``codeFlow`` —
GitHub's SARIF viewer renders it as a step-through path from the async
entry (or taint source) to the offending leaf. ``partialFingerprints``
carries the same line-number-free fingerprint the baseline uses, so CI
annotation dedup survives unrelated edits.
"""

from __future__ import annotations

from typing import Iterable

from tasksrunner.analysis.core import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def _location(path: str, line: int, col: int = 1) -> dict:
    return {
        "physicalLocation": {
            "artifactLocation": {"uri": path, "uriBaseId": "SRCROOT"},
            "region": {"startLine": max(line, 1),
                       "startColumn": max(col, 1)},
        },
    }


def _code_flow(finding: Finding) -> dict:
    locations = []
    for frame in finding.chain:
        # v4 labelled frame: "file:line [role]" — the label becomes the
        # step message; the location parses from the prefix
        site = frame.split(" [", 1)[0]
        rel, _, line = site.rpartition(":")
        if not rel or not line.isdigit():
            continue
        locations.append({
            "location": dict(_location(rel, int(line)),
                             message={"text": frame}),
        })
    return {"threadFlows": [{"locations": locations}]}


def to_sarif(findings: Iterable[Finding], rule_docs: dict[str, str]) -> dict:
    """One SARIF document for one lint run. ``rule_docs`` maps every
    executed rule id to its one-line doc (drives the driver.rules
    metadata; ids seen only in findings are added defensively)."""
    findings = list(findings)
    docs = dict(rule_docs)
    for f in findings:
        docs.setdefault(f.rule, "")
    rule_ids = sorted(docs)
    index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error",
            "message": {"text": f.message},
            "locations": [_location(f.path, f.line, f.col)],
            "partialFingerprints": {"tasklint/v1": f.fingerprint()},
        }
        if f.chain:
            result["codeFlows"] = [_code_flow(f)]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "tasklint",
                    "informationUri": ("https://github.com/tasksrunner/"
                                       "tasksrunner"),
                    "rules": [{
                        "id": rid,
                        "shortDescription": {"text": docs[rid] or rid},
                    } for rid in rule_ids],
                },
            },
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }
